"""L0 communication runtime: the reference's MPI backend (knn_mpi.cpp:123-129,
133-134,224-227,276-277,340,383,395-397 — the 11 entry points in SURVEY.md
§2.8) rebuilt as sharding + XLA collectives over a `jax.sharding.Mesh`.

Mapping (rank ↔ mesh device):
  MPI_Bcast      -> replicated NamedSharding            (collectives.replicate)
  MPI_Scatter    -> sharded NamedSharding / shard_map   (collectives.shard)
  MPI_Allreduce  -> lax.pmin / lax.pmax / lax.psum      (collectives.allreduce_*)
  MPI_Gather     -> lax.all_gather / host fetch         (collectives.gather)
  MPI_Barrier    -> block_until_ready                   (collectives.barrier)
  MPI_Comm_rank  -> lax.axis_index                      (inside shard_map)
  MPI_Comm_size  -> mesh.shape[axis]
  MPI_Abort      -> pad-to-multiple instead             (mesh.pad_to_multiple)

Multi-host (``mpiexec`` across nodes -> one JAX process per host over DCN)
lives in :mod:`knn_tpu.parallel.multihost`: initialize / global_mesh /
shard_across_hosts / process_row_slice.
"""

from knn_tpu.parallel.mesh import (
    make_mesh,
    default_mesh,
    pad_to_multiple,
    QUERY_AXIS,
    DB_AXIS,
)
from knn_tpu.parallel.collectives import (
    replicate,
    shard,
    gather,
    allreduce_min,
    allreduce_max,
    barrier,
    shard_map_compat,
)
from knn_tpu.parallel.sharded import (
    ShardedKNN,
    sharded_knn,
    sharded_knn_predict,
    sharded_minmax,
    sharded_normalize_transductive,
)

__all__ = [
    "make_mesh",
    "default_mesh",
    "pad_to_multiple",
    "QUERY_AXIS",
    "DB_AXIS",
    "replicate",
    "shard",
    "gather",
    "allreduce_min",
    "allreduce_max",
    "barrier",
    "shard_map_compat",
    "ShardedKNN",
    "sharded_knn",
    "sharded_knn_predict",
    "sharded_minmax",
    "sharded_normalize_transductive",
]
