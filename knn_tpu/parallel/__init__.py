"""L0 communication runtime: the reference's MPI backend (knn_mpi.cpp:123-129,
133-134,224-227,276-277,340,383,395-397 — the 11 entry points in SURVEY.md
§2.8) rebuilt as sharding + XLA collectives over a `jax.sharding.Mesh`.

Mapping (rank ↔ mesh device):
  MPI_Bcast      -> replicated NamedSharding            (collectives.replicate)
  MPI_Scatter    -> sharded NamedSharding / shard_map   (collectives.shard)
  MPI_Allreduce  -> lax.pmin / lax.pmax / lax.psum      (collectives.allreduce_*)
  MPI_Gather     -> lax.all_gather / host fetch         (collectives.gather)
  MPI_Barrier    -> block_until_ready                   (collectives.barrier)
  MPI_Comm_rank  -> lax.axis_index                      (inside shard_map)
  MPI_Comm_size  -> mesh.shape[axis]
  MPI_Abort      -> pad-to-multiple instead             (mesh.pad_to_multiple)

Multi-host (``mpiexec`` across nodes -> one JAX process per host over DCN)
lives in :mod:`knn_tpu.parallel.multihost`: initialize / global_mesh /
shard_across_hosts / process_row_slice.
"""

# Attribute access is lazy (PEP 562, the knn_tpu/__init__ idiom) so the
# jax-free members — parallel.crossover's measured table, validators,
# and byte models, consumed by the artifact refresher and the roofline
# model — never pay (or break on) the JAX import the mesh/collective/
# SPMD members need.
import importlib

#: symbol -> defining submodule; resolved on first attribute access
_EXPORTS = {
    "make_mesh": "knn_tpu.parallel.mesh",
    "make_host_mesh": "knn_tpu.parallel.mesh",
    "default_mesh": "knn_tpu.parallel.mesh",
    "pad_to_multiple": "knn_tpu.parallel.mesh",
    "QUERY_AXIS": "knn_tpu.parallel.mesh",
    "DB_AXIS": "knn_tpu.parallel.mesh",
    "HOST_AXIS": "knn_tpu.parallel.mesh",
    "MEASURED_CROSSOVER": "knn_tpu.parallel.crossover",
    "choose_merge": "knn_tpu.parallel.crossover",
    "merge_bytes": "knn_tpu.parallel.crossover",
    "resolve_merge": "knn_tpu.parallel.crossover",
    "replicate": "knn_tpu.parallel.collectives",
    "shard": "knn_tpu.parallel.collectives",
    "gather": "knn_tpu.parallel.collectives",
    "allreduce_min": "knn_tpu.parallel.collectives",
    "allreduce_max": "knn_tpu.parallel.collectives",
    "barrier": "knn_tpu.parallel.collectives",
    "shard_map_compat": "knn_tpu.parallel.collectives",
    "ShardedKNN": "knn_tpu.parallel.sharded",
    "sharded_knn": "knn_tpu.parallel.sharded",
    "sharded_knn_predict": "knn_tpu.parallel.sharded",
    "sharded_minmax": "knn_tpu.parallel.sharded",
    "sharded_normalize_transductive": "knn_tpu.parallel.sharded",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        module = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module 'knn_tpu.parallel' has no attribute {name!r}"
        ) from None
    value = getattr(importlib.import_module(module), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
