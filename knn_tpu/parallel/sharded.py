"""Distributed KNN over a 2-D device mesh — the SPMD program that replaces
the reference's rank-parallel main loop (knn_mpi.cpp:224-227,308-393).

Two sharded axes (see parallel.mesh):

- **query axis** — the reference's strategy: queries scattered, train
  replicated, zero inter-device traffic during the distance phase, results
  stay sharded (the gather at knn_mpi.cpp:340,383 is just an output spec).
- **db axis** — beyond the reference: train rows sharded too.  Each device
  computes a *local* top-k against its train shard with globalized indices,
  then the shards merge.  Two merge strategies, bitwise-identical results:

    * ``allgather``: one `lax.all_gather` of the [Qs, k] candidate lists
      over the db axis, one lexicographic re-select.  One collective, P*k
      candidate volume — the right choice when k*P is small.
    * ``ring``: P-1 `lax.ppermute` steps passing a constant [Qs, k] buffer
      around the db ring, merging locally each step — the KNN analogue of
      ring attention (SURVEY.md §5 long-context row).  Constant memory,
      overlappable with compute; the right shape when P or k is large.

  The merge is the lexicographic (distance, index) top-k (ops.topk), which
  is associative + commutative, so both strategies and any device count
  agree bitwise with the single-device result.

The reference's distributed min-max normalize (knn_mpi.cpp:229-306) maps to
:func:`sharded_minmax`: local extrema + `lax.pmin`/`lax.pmax` over the mesh
— its two `MPI_Allreduce` calls (knn_mpi.cpp:276-277) verbatim.
"""

from __future__ import annotations

import functools
import threading
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from knn_tpu import obs
from knn_tpu.obs import names as _mn
from knn_tpu.ops.normalize import local_minmax, minmax_apply
from knn_tpu.ops.topk import knn_search_tiled, merge_topk, topk_pairs
from knn_tpu.ops.vote import majority_vote
from knn_tpu.parallel import crossover
from knn_tpu.parallel.collectives import (
    allreduce_max,
    allreduce_min,
    gather,
    replicate,
    shard,
    shard_map_compat,
)
from knn_tpu.parallel.mesh import (
    DB_AXIS,
    HOST_AXIS,
    QUERY_AXIS,
    db_axes,
    db_topology,
    pad_to_multiple,
)

_INT_SENTINEL = jnp.iinfo(jnp.int32).max

#: Module-level jitted rescale so repeated jobs hit the jit cache.
_minmax_apply_jit = jax.jit(minmax_apply)


def _ring_merge(d, i, k: int, axis_name: str, n_shards: int):
    """P-1 ppermute steps around the ring; each device ends with the global
    top-k.  Order-independent thanks to the lexicographic merge."""
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def body(_, carry):
        acc_d, acc_i, buf_d, buf_i = carry
        buf_d = lax.ppermute(buf_d, axis_name, perm)
        buf_i = lax.ppermute(buf_i, axis_name, perm)
        acc_d, acc_i = merge_topk(acc_d, acc_i, buf_d, buf_i, k)
        return acc_d, acc_i, buf_d, buf_i

    acc_d, acc_i, _, _ = lax.fori_loop(1, n_shards, body, (d, i, d, i))
    return acc_d, acc_i


def _allgather_merge(d, i, k: int, axis_name: str):
    ad = gather(d, axis_name, axis=0, tiled=False)  # [P, Qs, k]
    ai = gather(i, axis_name, axis=0, tiled=False)
    qs = d.shape[0]
    ad = jnp.moveaxis(ad, 0, 1).reshape(qs, -1)
    ai = jnp.moveaxis(ai, 0, 1).reshape(qs, -1)
    return topk_pairs(ad, ai, k)


def _db_shard_index(hosts: int, chips: int):
    """This device's GLOBAL db-shard index inside shard_map: the flat
    db-axis position, or host-major ``host * chips + chip`` on a
    hierarchical mesh — the row-block order ``P((HOST_AXIS, DB_AXIS))``
    shards with."""
    idx = lax.axis_index(DB_AXIS)
    if hosts > 1:
        idx = lax.axis_index(HOST_AXIS) * chips + idx
    return idx


def _merge_shards(d, gi, keep: int, hosts: int, chips: int,
                  merge: str, dcn_merge: Optional[str]):
    """The hierarchical top-k merge tree, inside shard_map: per-chip
    candidate lists reduce per-host over the ICI db axis first (the
    ``merge`` strategy), then per-host lists merge globally over the
    DCN host axis (``dcn_merge``; strategies may differ — the measured
    crossover picks each level by its own shard count).  Flat meshes
    (hosts == 1) run the single-level merge unchanged.  The
    lexicographic (distance, index) merge is associative + commutative
    (ops.topk), so the two-level tree is bitwise-identical to the flat
    merge — pinned in tests/test_multihost.py."""
    if chips > 1:
        if merge == "ring":
            d, gi = _ring_merge(d, gi, keep, DB_AXIS, chips)
        else:
            d, gi = _allgather_merge(d, gi, keep, DB_AXIS)
    if hosts > 1:
        strat = dcn_merge or merge
        if strat == "ring":
            d, gi = _ring_merge(d, gi, keep, HOST_AXIS, hosts)
        else:
            d, gi = _allgather_merge(d, gi, keep, HOST_AXIS)
    return d, gi


def _pack_bits_u32(mask: jax.Array) -> jax.Array:
    """[Q, B] bool -> [Q, ceil(B/32)] uint32, bit j of word w = column
    32*w + j.  Shrinks the near-tie mask's device->host transfer 32x —
    through the dev harness's ~12 MB/s relay that is wall-clock, not
    tidiness."""
    n_q, b = mask.shape
    nw = -(-b // 32)
    padded = jnp.pad(mask.astype(jnp.uint32), ((0, 0), (0, nw * 32 - b)))
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(padded.reshape(n_q, nw, 32) * weights, axis=-1,
                   dtype=jnp.uint32)


def unpack_bits_u32(words: np.ndarray, n_bits: int) -> np.ndarray:
    """Host inverse of :func:`_pack_bits_u32`: [Q, nw] uint32 -> [Q,
    n_bits] bool."""
    w = np.asarray(words, dtype=np.uint32)
    bits = (w[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
    return bits.reshape(w.shape[0], -1)[:, :n_bits].astype(bool)


def _analysis_window(k: int, m: int) -> int:
    """Width of the device rank-analysis window: the packed program
    output's column layout, _certify_pallas's unpack, and bench.py's
    phase breakdown all derive from THIS — one home, or unpack_certified
    silently slices shifted columns."""
    return min(k + 17, m + 1)


def _overlap_ratio(intervals) -> float:
    """Fraction of the pipeline's wall time during which >= 2 batches
    were simultaneously in flight (interval = coarse-dispatch start to
    result-repair end) — the honest, host-measurable overlap number:
    it reports dispatch-timeline concurrency (what the bounded-depth
    pipeline creates), not device-internal overlap (which needs a
    hardware trace; obs.profiler).  0.0 for < 2 batches."""
    if len(intervals) < 2:
        return 0.0
    events = []
    for s, e in intervals:
        events.append((s, 1))
        events.append((e, -1))
    events.sort()
    in_flight, overlapped, prev = 0, 0.0, None
    for t, delta in events:
        if prev is not None and in_flight >= 2:
            overlapped += t - prev
        in_flight += delta
        prev = t
    wall = max(e for _, e in intervals) - min(s for s, _ in intervals)
    return overlapped / wall if wall > 0 else 0.0


#: db-axis merge strategies — the canonical home is
#: parallel.crossover.STRATEGIES (the measured-crossover module)
_MERGES = crossover.STRATEGIES

#: Certified-path coarse selectors.  "exact" ranks every row (float32
#: lexicographic top-k); "approx" uses the hardware bin-reduction behind
#: lax.approx_max_k (count-below certificate); "pallas" routes to the
#: one-pass self-certifying kernel program (_pallas_certified_program) —
#: it never reaches _local_topk/_knn_program.
SELECTORS = ("exact", "approx", "pallas")


def _local_topk(q, t, k, metric, n_train, train_tile, compute_dtype, selector,
                recall_target=None, hosts=1, chips=1):
    """Local shard top-k with global train indices.

    The last db shard may contain zero-padding rows; their distances are
    forced to +inf *inside* the exact/approx selection (``n_valid``) so a
    pad row can never displace a real neighbor.  The pallas selector masks
    after its bin reduction — a pad row can then shadow one bin of the
    last shard, which the certified pipeline detects and repairs.
    """
    db_idx = _db_shard_index(hosts, chips)
    n_local_valid = jnp.clip(n_train - db_idx * t.shape[0], 0, t.shape[0])
    if selector == "exact":
        d, i = knn_search_tiled(
            q, t, k, metric, train_tile=train_tile, compute_dtype=compute_dtype,
            n_valid=n_local_valid,
        )
    elif selector == "approx":
        from knn_tpu.ops.topk import knn_search_approx

        kw = {} if recall_target is None else {"recall_target": recall_target}
        d, i = knn_search_approx(
            q, t, k, compute_dtype=compute_dtype, n_valid=n_local_valid, **kw
        )
    else:
        raise ValueError(f"unknown selector {selector!r}; expected one of {SELECTORS}")
    pad = i >= n_local_valid
    gi = jnp.where(pad, _INT_SENTINEL, i + db_idx * t.shape[0])
    return jnp.where(pad, jnp.inf, d), gi


def _merged_topk(q, t, k, metric, merge, n_train, train_tile, compute_dtype,
                 hosts, chips, selector="exact", recall_target=None,
                 dcn_merge=None):
    """Shared SPMD body: local shard top-k, then the (hierarchical)
    merge across the db sharding."""
    d, gi = _local_topk(q, t, k, metric, n_train, train_tile, compute_dtype,
                        selector, recall_target, hosts, chips)
    return _merge_shards(d, gi, k, hosts, chips, merge, dcn_merge)


@functools.lru_cache(maxsize=64)
def _knn_program(
    mesh: Mesh,
    k: int,
    metric: str,
    merge: str,
    n_train: int,
    train_tile: Optional[int],
    compute_dtype,
    selector: str = "exact",
    recall_target: Optional[float] = None,
    donate: bool = False,
    dcn_merge: Optional[str] = None,
):
    hosts, chips = db_topology(mesh)

    def spmd(q, t):
        return _merged_topk(
            q, t, k, metric, merge, n_train, train_tile, compute_dtype,
            hosts, chips, selector, recall_target, dcn_merge,
        )

    return jax.jit(
        shard_map_compat(
            spmd,
            mesh=mesh,
            in_specs=(P(QUERY_AXIS), P(db_axes(mesh))),
            out_specs=(P(QUERY_AXIS), P(QUERY_AXIS)),
            check_vma=False,  # merged output is replicated along db by construction
        ),
        # the serving engine donates its per-request query placement so the
        # device buffer recycles instead of accumulating across a stream
        donate_argnums=(0,) if donate else (),
    )


@functools.lru_cache(maxsize=32)
def _hosttier_program(
    mesh: Mesh,
    k: int,
    metric: str,
    merge: str,
    train_tile: Optional[int],
    compute_dtype,
    dcn_merge: Optional[str] = None,
    donate: bool = False,
):
    """The per-sweep program of the host-RAM shard tier: one db SEGMENT
    (streamed host->device this sweep) searched exactly like a resident
    placement, except the valid-row count rides as a TRACED ``[1]``
    operand — so the ragged tail segment pads to the same shape as
    every full segment and all sweeps share ONE compiled executable
    (the flat-per-sweep-latency contract).  ``donate=True`` donates the
    segment buffer so HBM recycles sweep-over-sweep instead of
    accumulating across the dispatch-ahead window; CPU XLA rejects
    donation, so callers pass False there."""
    hosts, chips = db_topology(mesh)

    def spmd(q, t, n_valid):
        db_idx = _db_shard_index(hosts, chips)
        n_local = jnp.clip(n_valid[0] - db_idx * t.shape[0], 0, t.shape[0])
        d, i = knn_search_tiled(
            q, t, k, metric, train_tile=train_tile,
            compute_dtype=compute_dtype, n_valid=n_local,
        )
        pad = i >= n_local
        gi = jnp.where(pad, _INT_SENTINEL, i + db_idx * t.shape[0])
        d = jnp.where(pad, jnp.inf, d)
        return _merge_shards(d, gi, k, hosts, chips, merge, dcn_merge)

    return jax.jit(
        shard_map_compat(
            spmd,
            mesh=mesh,
            in_specs=(P(QUERY_AXIS), P(db_axes(mesh)), P()),
            out_specs=(P(QUERY_AXIS), P(QUERY_AXIS)),
            check_vma=False,
        ),
        donate_argnums=(1,) if donate else (),
    )


def segment_search_program(
    mesh: Mesh,
    k: int,
    metric: str = "l2",
    merge: Optional[str] = None,
    *,
    train_tile: Optional[int] = None,
    compute_dtype=None,
    dcn_merge: Optional[str] = None,
):
    """Public handle on the host-tier segment program for callers that
    stream GATHERED row blocks instead of contiguous db segments — the
    IVF probed-list path (knn_tpu.ivf.index): the gather of probed list
    extents pads to a fixed rung and masks via the same traced
    ``n_valid`` operand, so probing shrinks streamed bytes without new
    kernels or a recompile per probe set.  ``merge`` resolves through
    the same crossover table a :class:`ShardedKNN` placement uses;
    the returned callable is ``prog(qp, tp, n_valid)`` with the
    :func:`_hosttier_program` contract (shared lru compile cache)."""
    _, chips = db_topology(mesh)
    merge, _src = crossover.resolve_merge(merge, k, chips)
    dtype_key = (
        None if compute_dtype is None else jnp.dtype(compute_dtype).name
    )
    return _hosttier_program(mesh, k, metric, merge, train_tile,
                             dtype_key, dcn_merge=dcn_merge)


def query_stream_program(
    mesh: Mesh,
    k: int,
    n_train: int,
    metric: str = "l2",
    merge: Optional[str] = None,
    *,
    train_tile: Optional[int] = None,
    compute_dtype=None,
    dcn_merge: Optional[str] = None,
    donate: bool = False,
):
    """Public handle on the resident-db search program for callers that
    stream QUERY superblocks instead of serving one request batch — the
    bulk kNN-join engine (knn_tpu.join): superblock i+1's host->device
    query transfer overlaps superblock i's device compute under the
    bounded-depth drain-oldest discipline, and ``donate=True`` donates
    each superblock's query placement so HBM recycles block-over-block
    instead of accumulating across the dispatch-ahead window (CPU XLA
    rejects donation; callers pass False there — the same contract as
    :func:`_hosttier_program`'s segment donation).  The returned
    callable is ``prog(qp, tp)`` with the :func:`_knn_program` contract
    (shared lru compile cache: a join stream and a serving placement of
    the same shape share one executable when neither donates)."""
    _, chips = db_topology(mesh)
    merge, _src = crossover.resolve_merge(merge, k, chips)
    dtype_key = (
        None if compute_dtype is None else jnp.dtype(compute_dtype).name
    )
    return _knn_program(mesh, k, metric, merge, n_train, train_tile,
                        dtype_key, donate=donate, dcn_merge=dcn_merge)


#: bounded-retry policy for transient device failures inside long sweeps
#: (SURVEY §5 failure row; the same per-batch unit streaming.py uses).
#: ValueError/TypeError are caller bugs and never retried.  Waits double
#: per attempt so the window can outlast a real hiccup, not just an
#: instantaneous glitch.
_RETRY_ATTEMPTS = 3
_RETRY_WAIT_S = 0.5

#: error-text signatures that identify a DETERMINISTIC failure — one a
#: retry can only repeat (ADVICE r4: a Mosaic compile error or an OOM
#: was retried 3x with ~3.5 s of backoff per batch of a long sweep
#: before surfacing).  Matched case-insensitively against
#: "TypeName: message".
_DETERMINISTIC_SIGNATURES = (
    "resource_exhausted", "resource exhausted", "out of memory",
    "invalid_argument", "invalid argument", "failed_precondition",
    "failed precondition", "unimplemented", "mosaic",
)
#: signatures of KNOWN-transient failures (relay flake vocabulary —
#: r3/r4 session logs): these always get the full bounded-retry window,
#: even when consecutive attempts fail identically.  Checked BEFORE the
#: deterministic set: a flake whose text happens to also embed a
#: deterministic token (e.g. "UNAVAILABLE: peer ran out of memory")
#: must keep its retry window — erring toward retry costs seconds,
#: erring toward fail-fast kills a recoverable sweep.
_TRANSIENT_SIGNATURES = (
    "unavailable", "deadline_exceeded", "deadline exceeded", "aborted",
    "cancelled", "connection", "socket", "data_loss", "data loss",
)


def _classify_failure(e: Exception) -> str:
    """'transient' (full retry window) | 'deterministic' (never retry) |
    'unknown' (retry, but stop once the identical error repeats)."""
    s = f"{type(e).__name__}: {e}".lower()
    if any(sig in s for sig in _TRANSIENT_SIGNATURES):
        return "transient"
    if any(sig in s for sig in _DETERMINISTIC_SIGNATURES):
        return "deterministic"
    return "unknown"


def _retry_wait(attempt: int) -> None:
    import time

    time.sleep(_RETRY_WAIT_S * (2 ** attempt))


def _should_give_up(cls: str, e: Exception,
                    prev: Optional[Exception]) -> bool:
    """True when retrying ``e`` (already classified as ``cls``) cannot
    help: an unknown error whose repr exactly repeats the previous
    attempt's is deterministic in effect, whatever its name."""
    return (cls == "unknown" and prev is not None
            and repr(e) == repr(prev))


def _retry_transient(fn, what: str = "device call",
                     attempts: int = _RETRY_ATTEMPTS):
    """Call ``fn`` with bounded retries on transient (non-ValueError/
    TypeError) failures — the dispatch-side half of the retry story.
    Deterministic failures (compile errors, OOM — _classify_failure)
    propagate immediately; an unrecognized error that repeats verbatim
    stops retrying early."""
    err = None
    for attempt in range(attempts):
        try:
            return fn()
        except (ValueError, TypeError):
            raise  # caller bug: retry cannot help
        except Exception as e:
            cls = _classify_failure(e)
            if cls == "deterministic":
                raise
            if _should_give_up(cls, e, err):
                raise RuntimeError(
                    f"{what} failed after {attempt + 1} attempts "
                    f"(identical error repeated)") from e
            err = e
            if attempt + 1 < attempts:
                _retry_wait(attempt)
    raise RuntimeError(f"{what} failed after {attempts} attempts") from err


def _fetch_or_redispatch(out, redo, what: str = "device fetch",
                         attempts: int = _RETRY_ATTEMPTS):
    """``np.asarray(out)``, re-dispatching via ``redo()`` on transient
    failure — the fetch-side half: async device errors surface at the
    host transfer, after the original dispatch call already returned.
    Same give-up policy as :func:`_retry_transient`."""
    try:
        return np.asarray(out)
    except (ValueError, TypeError):
        raise
    except Exception as e:
        if _classify_failure(e) == "deterministic":
            raise
        err = e
    for attempt in range(attempts - 1):
        _retry_wait(attempt)
        try:
            return np.asarray(redo())
        except (ValueError, TypeError):
            raise
        except Exception as e:
            cls = _classify_failure(e)
            if cls == "deterministic":
                raise
            if _should_give_up(cls, e, err):
                raise RuntimeError(
                    f"{what} failed after {attempt + 2} attempts "
                    f"(identical error repeated)") from e
            err = e
    raise RuntimeError(f"{what} failed after {attempts} attempts") from err


def _row_normalize_f64(x: np.ndarray) -> np.ndarray:
    """Unit rows, float64 norms -> float32 result (accuracy: the cast is
    the only f32 rounding, ~2^-24 relative per entry)."""
    n = np.linalg.norm(x.astype(np.float64), axis=-1, keepdims=True)
    return (x / np.maximum(n, 1e-300)).astype(np.float32)


class ShardedKNN:
    """A placed distributed-KNN program: the database is padded, sharded
    along the db axis, and transferred **once** at construction; every
    subsequent :meth:`search`/:meth:`predict` call reuses the placement and
    the compiled SPMD program.  This is the handle long-running services and
    the batched pipeline use — the one-shot :func:`sharded_knn` /
    :func:`sharded_knn_predict` wrappers construct a throwaway instance.

    The reference has no equivalent: its train set is re-broadcast every
    process launch (knn_mpi.cpp:224-225).
    """

    def __init__(
        self,
        train: jax.Array,
        *,
        mesh: Mesh,
        k: int,
        metric: str = "l2",
        merge: Optional[str] = None,
        dcn_merge: Optional[str] = None,
        train_tile: Optional[int] = None,
        compute_dtype=None,
        labels=None,
        num_classes: Optional[int] = None,
        n_train: Optional[int] = None,
        hbm_budget_bytes: Optional[int] = None,
    ):
        # merge strategies resolve explicit > env (KNN_TPU_MERGE /
        # KNN_TPU_DCN_MERGE) > the SCALING.json-measured crossover table
        # (parallel.crossover) — results are bitwise-identical either
        # way, so the default is free to chase the measured wall clock.
        # On hierarchical meshes ``merge`` is the per-host ICI level and
        # ``dcn_merge`` the cross-host level, each resolved by its own
        # shard count.
        hosts, chips = db_topology(mesh)
        self.merge, self.merge_source = crossover.resolve_merge(
            merge, k, chips)
        self.dcn_merge, self.dcn_merge_source = (
            crossover.resolve_merge(
                dcn_merge, k, hosts, env_name=crossover.DCN_MERGE_ENV)
            if hosts > 1 else (None, None))
        obs.counter(_mn.MERGE_SELECTED, level="intra",
                    strategy=self.merge, source=self.merge_source).inc()
        if self.dcn_merge is not None:
            obs.counter(_mn.MERGE_SELECTED, level="dcn",
                        strategy=self.dcn_merge,
                        source=self.dcn_merge_source).inc()
        merge = self.merge
        # XLA compile events (count + seconds) from every program this
        # placement builds land in the registry; idempotent, no-op when
        # telemetry is off
        obs.install_compile_hook()
        metric = metric.lower()  # dispatch below compares lowercase names
        self._cosine_unit = False  # db rows normalized at placement?
        self._dot_aug = False  # db rows norm-augmented at placement?
        self._dot_shift = 0.0  # M = max f64 squared row norm (dot only)
        #: uint8 source rows (SIFT-style bvecs payloads): kept so an int8
        #: coarse pass reuses the bytes EXACTLY (unit scale, -128 shift —
        #: ops.quantize.from_uint8) instead of round-tripping through f32
        #: quantization.  Cosine normalizes rows at placement and dot
        #: appends a non-byte augmentation column, so the byte-exact
        #: shortcut doesn't apply there.
        self._uint8_train = None
        if (isinstance(train, np.ndarray) and train.dtype == np.uint8
                and metric not in ("cosine", "dot")):
            self._uint8_train = train
            train = train.astype(np.float32)
        #: lazily built int8 db placement (quantized values + scales +
        #: row norms + bound consts), cached per instance — "quantize
        #: once at placement time", the int8 arm's whole HBM story
        self._int8_cache = None
        #: the sub-int8 arms' placements, same lazy discipline: int4 is
        #: one nibble-packed placement; pq keys a small dict by the
        #: (dsub, ncodes) codebook geometry so two grids can coexist
        self._int4_cache = None
        self._pq_cache: dict = {}
        db_shards = hosts * chips
        pre_placed = (
            isinstance(train, jax.Array)
            and train.sharding.is_equivalent_to(
                NamedSharding(mesh, P(db_axes(mesh))), train.ndim
            )
        )
        if pre_placed:
            # already a db-sharded global array (e.g. assembled across
            # hosts by parallel.multihost.shard_across_hosts) — use the
            # placement as-is.  ``n_train`` tells the search programs how
            # many leading rows are real when the caller padded before
            # placing (pad rows past n_train are masked out of every
            # selection, exactly like the host-array path).
            if train.shape[0] % db_shards:
                raise ValueError(
                    f"pre-placed train rows {train.shape[0]} must be a "
                    f"multiple of db_shards={db_shards}; pad before placing"
                )
            self._train_host = None
            tp = train
            n_train = train.shape[0] if n_train is None else n_train
            if not 0 < n_train <= train.shape[0]:
                raise ValueError(
                    f"n_train={n_train} outside (0, {train.shape[0]}]"
                )
        else:
            if n_train is not None:
                raise ValueError("n_train is only for pre-placed arrays")
            if not isinstance(train, jax.Array):
                train = np.asarray(train)  # host padding streams shards on placement
            if metric == "cosine" and isinstance(train, np.ndarray):
                # cosine distance on row-normalized vectors is squared L2
                # (||q^-t^||^2 = 2(1-q^.t^)): normalizing ONCE at placement
                # (float64 norms, f32 result) makes the whole certified-
                # exact machinery available to cosine (search_certified),
                # and pairwise_cosine's internal re-normalization is
                # idempotent so plain search is unchanged.  Zero rows keep
                # themselves (norm clamped).
                train = _row_normalize_f64(train)
                self._cosine_unit = True
            elif metric == "dot" and isinstance(train, np.ndarray):
                # MIPS -> L2 by norm augmentation, ONCE at placement:
                # appending sqrt(M - ||t||^2) to every row (M = max f64
                # squared row norm) and a zero column to every query makes
                # the augmented squared L2
                #   ||q'-t'||^2 = ||q||^2 + M - 2 q.t
                # an affine, strictly decreasing map of the inner product
                # per query — the augmented-L2 ranking IS the MIPS
                # ranking, so the whole certified-exact machinery
                # (search_certified, any precision x kernel) applies.
                # Plain search rides too: _place_queries appends the zero
                # column and the extra 0*aug term leaves pairwise_dot
                # values mathematically unchanged.
                train = np.asarray(train, np.float32)
                t64 = train.astype(np.float64)
                norm2 = np.einsum("nd,nd->n", t64, t64)
                self._dot_shift = float(norm2.max()) if norm2.size else 0.0
                aug = np.sqrt(np.maximum(self._dot_shift - norm2, 0.0))
                train = np.concatenate(
                    [train, aug[:, None].astype(np.float32)], axis=1)
                self._dot_aug = True
            # host copy (unpadded) for certified-path float64 refinement
            self._train_host = train if isinstance(train, np.ndarray) else None
            # pad rows with a huge fill: every selector also masks them by
            # index, but the pallas kernel's exclusion bound stays sharp
            # only if pad rows score far away (ops.pallas_knn.PAD_VAL)
            from knn_tpu.ops.pallas_knn import PAD_VAL

            tp, n_train = pad_to_multiple(train, db_shards, fill=PAD_VAL)
        # --- host-RAM shard tier (the super-HBM escape hatch) ----------
        # When the placement's per-host share exceeds the HBM budget
        # (explicit arg > KNN_TPU_HOSTTIER_BUDGET_BYTES env > unbounded),
        # the database stays in HOST memory partitioned into
        # budget-sized segments (analysis.hbm.plan_segments); search()
        # then streams the segments through the device placement
        # sweep-by-sweep with dispatch-ahead overlap, merging each
        # sweep's candidates into a running top-k carry.  Every segment
        # pads to ONE shape, so all sweeps share one compiled program.
        self._host_tier: Optional[dict] = None
        budget = hbm_budget_bytes
        if budget is None:
            import os as _os

            env_b = _os.environ.get(
                "KNN_TPU_HOSTTIER_BUDGET_BYTES", "").strip()
            if env_b:
                try:
                    budget = int(env_b)
                except ValueError as e:
                    raise ValueError(
                        f"KNN_TPU_HOSTTIER_BUDGET_BYTES={env_b!r} is not "
                        f"an int") from e
        if budget is not None and budget <= 0:
            raise ValueError(f"hbm_budget_bytes must be > 0, got {budget}")
        if budget is not None and not isinstance(tp, np.ndarray):
            # the tier streams from HOST memory; a pre-placed /
            # device-resident array has no host rows to stream from.
            # Refuse loudly when it would not fit rather than silently
            # placing a super-budget corpus resident.
            from knn_tpu.analysis import hbm

            over = hbm.placement_bytes(
                tp.shape[0], tp.shape[1],
                int(jnp.dtype(tp.dtype).itemsize)) > budget * hosts
            if over:
                raise ValueError(
                    f"hbm_budget_bytes={budget} per host cannot hold this "
                    f"{tp.shape[0]}-row placement, and the host-RAM tier "
                    f"needs a host-array construction to stream from; "
                    f"pass the rows as a numpy array (or raise the budget)")
        if budget is not None and isinstance(tp, np.ndarray):
            from knn_tpu.analysis import hbm

            itemsize = int(tp.dtype.itemsize)
            total_b = hbm.placement_bytes(tp.shape[0], tp.shape[1], itemsize)
            if total_b > budget * hosts:
                import os as _os

                env_d = _os.environ.get(
                    "KNN_TPU_HOSTTIER_DEPTH", "").strip()
                try:
                    depth = int(env_d) if env_d else 2
                except ValueError as e:
                    # strict-env discipline (admission/merge switches):
                    # a typo'd knob raises instead of silently running
                    # at the default
                    raise ValueError(
                        f"KNN_TPU_HOSTTIER_DEPTH={env_d!r} is not an "
                        f"int") from e
                segments = hbm.plan_segments(
                    n_train, tp.shape[1], budget, itemsize=itemsize,
                    hosts=hosts, shard_multiple=db_shards)
                seg_rows = segments[0][1] - segments[0][0]
                self._host_tier = {
                    "segments": segments,
                    "segment_rows": seg_rows,
                    "budget_bytes": int(budget),
                    "bytes_per_sweep": hbm.placement_bytes(
                        seg_rows, tp.shape[1], itemsize),
                    "depth": max(1, depth),
                    "itemsize": itemsize,
                }
                obs.gauge(_mn.HOSTTIER_SEGMENT_ROWS).set(float(seg_rows))
        shard_rows = (
            self._host_tier["segment_rows"] if self._host_tier is not None
            else tp.shape[0]
        ) // db_shards
        if k > shard_rows:
            raise ValueError(
                f"k={k} exceeds db shard size {shard_rows}; use fewer db shards"
            )
        if k > n_train:
            raise ValueError(f"k={k} > n_train={n_train}")
        self.mesh = mesh
        self.k = k
        self.metric = metric
        self._db_norm_max_cache: Optional[float] = None
        self.train_tile = train_tile
        self.n_train = n_train
        #: user-facing query/input dim — dot placements append one norm-
        #: augmentation column, so the PLACED width is ``dim_in + 1``
        self.dim_in = int(tp.shape[1]) - (1 if self._dot_aug else 0)
        self._dtype_key = (
            None if compute_dtype is None else jnp.dtype(compute_dtype).name
        )
        if self._host_tier is not None:
            self._tp = None  # segments stream per sweep; nothing resident
            self._last_hosttier: Optional[dict] = None
        else:
            # the reference's Scatter, once (host-major over hosts x
            # chips on hierarchical meshes)
            self._tp = shard(tp, mesh, db_axes(mesh))
        #: (k, placed query rows) -> dispatch count: every distinct pair is
        #: one traced/compiled XLA program shape (compile_cache_stats)
        self._dispatch_shapes: dict = {}
        #: last pipeline-overlap run's measurements (depth, batches,
        #: overlap_ratio, wall_s) — surfaced by search_certified stats
        #: and ServingEngine.stats(); None until an overlap run happens
        self._last_pipeline: Optional[dict] = None
        #: lazily built serving engines, keyed by ladder spec
        #: (buckets, min_bucket, max_bucket) — search_bucketed; the lock
        #: keeps concurrent cold calls from double-building an engine
        #: (each build AOT-compiles executables — seconds on hardware)
        self._serving_engines: dict = {}
        self._engines_lock = threading.Lock()
        self._labels = None
        self.num_classes = num_classes
        if labels is not None:
            if num_classes is None:
                raise ValueError("labels given without num_classes")
            labels = np.asarray(labels, dtype=np.int32)
            if labels.shape != (n_train,):
                raise ValueError(
                    f"labels shape {labels.shape} != (n_train,) = ({n_train},)"
                )
            self._labels = replicate(labels, mesh)  # the reference's Bcast

    @property
    def db_shards(self) -> int:
        """Total db shards: hosts x chips on hierarchical meshes."""
        hosts, chips = db_topology(self.mesh)
        return hosts * chips

    def _shard_rows(self) -> int:
        """Rows per db shard of the resident placement (or of one
        host-tier segment)."""
        if self._host_tier is not None:
            return self._host_tier["segment_rows"] // self.db_shards
        return self._tp.shape[0] // self.db_shards

    def _require_resident(self, what: str) -> None:
        """The paths that read the whole placed database (certified
        pipeline, radius counts, votes, bucketed serving) need it
        RESIDENT; the host-RAM tier only ever has one segment on
        device."""
        if self._tp is None:
            raise ValueError(
                f"{what} needs the full database resident on device, but "
                f"this placement runs the host-RAM shard tier (corpus "
                f"exceeds the {self._host_tier['budget_bytes']}-byte "
                f"per-host HBM budget); use search(), or raise the budget")

    def _record_merge_bytes(self, n_rows: int, k: int) -> None:
        """Mirror the modeled per-level merge volume into the registry
        (crossover.merge_bytes — the same model the roofline's DCN term
        prices)."""
        hosts, chips = db_topology(self.mesh)
        if chips > 1:
            obs.counter(_mn.MERGE_BYTES, level="intra",
                        strategy=self.merge).inc(
                crossover.merge_bytes(n_rows, k, chips, self.merge))
        if hosts > 1 and self.dcn_merge is not None:
            obs.counter(_mn.MERGE_BYTES, level="dcn",
                        strategy=self.dcn_merge).inc(
                crossover.merge_bytes(n_rows, k, hosts, self.dcn_merge))

    def hosttier_stats(self) -> Optional[dict]:
        """The host-RAM tier plan plus the last sweep's measurements
        (sweeps, per-sweep walls, bytes/sweep); None when the placement
        is fully resident."""
        if self._host_tier is None:
            return None
        out = {k: v for k, v in self._host_tier.items() if k != "segments"}
        out["sweeps"] = len(self._host_tier["segments"])
        if self._last_hosttier is not None:
            out["last_search"] = dict(self._last_hosttier)
        return out

    def _place_queries(self, queries):
        if not isinstance(queries, jax.Array):
            queries = np.asarray(queries)
            if (self._dot_aug and queries.ndim == 2
                    and queries.shape[1] == self.dim_in):
                # dot placements are norm-augmented: queries ride with a
                # zero column (q'.t' == q.t).  Already-augmented callers
                # (search_certified) arrive at width dim_in + 1 and pass
                # through untouched.
                queries = np.concatenate(
                    [np.asarray(queries, np.float32),
                     np.zeros((queries.shape[0], 1), np.float32)], axis=1)
        qp, n_q = pad_to_multiple(queries, self.mesh.shape[QUERY_AXIS])
        return shard(qp, self.mesh, QUERY_AXIS), n_q

    def search(
        self, queries: jax.Array, *, k: Optional[int] = None,
        return_sqrt: bool = False,
    ) -> Tuple[jax.Array, jax.Array]:
        """(distances, global indices) [Q, k] of the k nearest database rows.

        ``k`` overrides the constructor's k for this call (e.g. fetching
        k+margin candidates for host refinement) while reusing the same
        device placement; each distinct k compiles its own cached program.

        L2-family distances are SQUARED by default (ranking-equivalent,
        the monotone sqrt at knn_mpi.cpp:48 dropped); ``return_sqrt=True``
        returns true Euclidean values matching the reference / sklearn.
        """
        k = self.k if k is None else k
        shard_rows = self._shard_rows()
        if k > min(self.n_train, shard_rows):
            raise ValueError(f"k={k} exceeds shard rows {shard_rows}")
        if self._host_tier is not None:
            return self._search_host_tier(queries, k, return_sqrt)
        qp, n_q = self._place_queries(queries)
        fn = _knn_program(
            self.mesh, k, self.metric, self.merge, self.n_train,
            self.train_tile, self._dtype_key, dcn_merge=self.dcn_merge,
        )
        shape_key = (k, qp.shape[0])
        self._dispatch_shapes[shape_key] = (
            self._dispatch_shapes.get(shape_key, 0) + 1
        )
        self._record_merge_bytes(qp.shape[0], k)
        d, i = _retry_transient(lambda: fn(qp, self._tp), "search dispatch")
        if return_sqrt:
            from knn_tpu.ops.distance import metric_values

            d = metric_values(d, self.metric)
        return d[:n_q], i[:n_q]

    def _search_host_tier(self, queries, k: int, return_sqrt: bool):
        """The host-RAM tier sweep: stream budget-sized db segments
        host->device one per sweep (ALL sweeps share one compiled
        program — the ragged tail pads to the same shape and masks via
        the traced ``n_valid`` operand), with up to ``depth`` sweeps in
        flight (the PR-1/PR-9 bounded-depth dispatch-ahead discipline:
        drain the oldest before admitting a new one, so segment s+1's
        h2d transfer and distance stream overlap segment s's fetch and
        host merge).  Each fetched sweep's candidates merge into the
        running top-k carry by the SAME lexicographic (distance, index)
        order the device merge uses, so results are bitwise-identical
        to the all-in-HBM placement (per-pair distances are
        placement-invariant; tests/test_hosttier.py pins it).  Returns
        host arrays — the carry lives on host by construction."""
        import time as _time

        from knn_tpu.ops.pallas_knn import PAD_VAL

        ht = self._host_tier
        host = self._train_host
        seg_rows = ht["segment_rows"]
        donate = jax.default_backend() != "cpu"
        prog = _hosttier_program(
            self.mesh, k, self.metric, self.merge, self.train_tile,
            self._dtype_key, dcn_merge=self.dcn_merge, donate=donate)
        qp, n_q = self._place_queries(queries)
        shape_key = (k, qp.shape[0])
        self._dispatch_shapes[shape_key] = (
            self._dispatch_shapes.get(shape_key, 0) + 1
        )

        def launch(lo: int, hi: int):
            seg = host[lo:hi]
            if seg.shape[0] < seg_rows:
                seg = np.pad(seg, ((0, seg_rows - seg.shape[0]), (0, 0)),
                             constant_values=PAD_VAL)
            tp = shard(seg, self.mesh, db_axes(self.mesh))
            nv = replicate(np.asarray([hi - lo], np.int32), self.mesh)
            return prog(qp, tp, nv)

        best_d: Optional[np.ndarray] = None
        best_i: Optional[np.ndarray] = None
        pending: list = []
        sweep_walls: list = []
        t_wall0 = _time.perf_counter()

        def collect() -> None:
            nonlocal best_d, best_i
            lo, hi, t0, out = pending.pop(0)
            # d and i MUST come from the same execution: a transient
            # fetch failure relaunches the sweep and rebinds BOTH
            # outputs (a d from the relaunch paired with an i from the
            # dead original would silently mis-rank)
            cur = {"out": out}

            def redo():
                cur["out"] = launch(lo, hi)
                return cur["out"][0]

            d = _fetch_or_redispatch(out[0], redo, "host-tier fetch")
            i = np.asarray(cur["out"][1])
            sweep_walls.append(_time.perf_counter() - t0)
            # globalize within-segment indices; sentinel rows stay put
            pad = i == _INT_SENTINEL
            gi = np.where(pad, _INT_SENTINEL, i.astype(np.int64) + lo)
            self._record_merge_bytes(qp.shape[0], k)
            obs.counter(_mn.HOSTTIER_SWEEPS).inc()
            obs.histogram(_mn.HOSTTIER_SWEEP_SECONDS).observe(
                sweep_walls[-1])
            if best_d is None:
                best_d, best_i = np.asarray(d), gi
                return
            # ONE home for the host-side lexicographic merge — the same
            # order the device merge tree applies
            from knn_tpu.parallel.multihost import merge_topk_host

            best_d, best_i = merge_topk_host(
                [best_d, np.asarray(d)], [best_i, gi], k)

        for lo, hi in ht["segments"]:
            while len(pending) >= ht["depth"]:
                collect()
            t0 = _time.perf_counter()
            out = _retry_transient(lambda lo=lo, hi=hi: launch(lo, hi),
                                   "host-tier dispatch")
            pending.append((lo, hi, t0, out))
        while pending:
            collect()
        self._last_hosttier = {
            "sweeps": len(ht["segments"]),
            "wall_s": round(_time.perf_counter() - t_wall0, 4),
            "sweep_walls_s": [round(w, 4) for w in sweep_walls],
            "k": k,
            "queries": int(n_q),
        }
        d_out, i_out = best_d[:n_q], best_i[:n_q]
        if return_sqrt:
            from knn_tpu.ops.distance import metric_values

            d_out = np.asarray(metric_values(jnp.asarray(d_out),
                                             self.metric))
        return d_out, i_out

    def search_bucketed(
        self, queries, *, buckets=None, min_bucket: int = 32,
        max_bucket: int = 4096, return_sqrt: bool = False,
    ):
        """Bucketed exact search (numpy results; same neighbors and
        tie-break order as :meth:`search`, and bitwise-identical to a
        :meth:`search` call of the same padded batch — see
        knn_tpu.serving.engine for the exactness contract): the query
        batch pads up to a geometric ladder of
        bucket sizes so ANY traffic pattern of batch shapes hits at most
        ``len(buckets)`` compiled programs, instead of one compile per
        distinct batch size.  The engine behind it (built lazily per
        ladder, reused across calls) AOT-compiles buckets on first use and
        keeps compile/dispatch/latency accounting — see
        :meth:`compile_cache_stats` and :mod:`knn_tpu.serving` for the
        full serving surface (warmup, micro-batching queue, trace
        replay)."""
        self._require_resident("search_bucketed")
        from knn_tpu.serving.buckets import normalize_ladder
        from knn_tpu.serving.engine import ServingEngine

        ladder = (
            None if buckets is None else normalize_ladder(buckets)
        )
        # an explicit ladder fully determines the engine — min/max are
        # ignored then and must not key duplicate engines that would
        # re-AOT-compile identical executables
        key = ladder if ladder is not None else (None, min_bucket, max_bucket)
        with self._engines_lock:
            engine = self._serving_engines.get(key)
            if engine is None:
                # construction is cheap (no compiles happen here); holding
                # the lock just prevents duplicate engines whose separate
                # AOT caches would re-compile identical executables
                engine = ServingEngine(
                    self, buckets=ladder, min_bucket=min_bucket,
                    max_bucket=max_bucket,
                )
                self._serving_engines[key] = engine
        return engine.search(queries, return_sqrt=return_sqrt)

    def compile_cache_stats(self) -> dict:
        """Compile-cache observability for serving: the module program
        cache (shared across instances — ``_knn_program``'s lru_cache) and
        THIS placement's dispatched program shapes.  Each distinct
        ``(k, placed_rows)`` pair is one XLA trace/compile of the search
        program; a healthy bucketed stream keeps ``distinct_shapes``
        bounded by its ladder size while ``dispatches`` grows."""
        info = _knn_program.cache_info()
        out = {
            "program_cache": {
                "hits": info.hits,
                "misses": info.misses,
                "size": info.currsize,
            },
            "distinct_shapes": len(self._dispatch_shapes),
            "dispatches": int(sum(self._dispatch_shapes.values())),
            "shape_counts": {
                f"k{k}xq{q}": int(c)
                for (k, q), c in sorted(self._dispatch_shapes.items())
            },
        }
        if self._serving_engines:
            out["serving_engines"] = [
                e.stats() for e in self._serving_engines.values()
            ]
        return out

    def radius_search(self, queries, radius: float, *, max_neighbors: int):
        """All db rows within ``radius`` per query, bounded at
        ``max_neighbors`` — the sharded form of ops.radius.radius_search.

        Returns ``(dists [Q, M], idx [Q, M], counts [Q])``: the sharded
        nearest-M select masked to the radius (beyond-radius slots
        ``+inf`` / ``-1``) plus the within-radius count from the
        distributed count program (psum over the db axis) — truncation
        (``counts > M``, with ``M = min(max_neighbors, n_train)``) is
        always visible.  l2 family (Euclidean-units radius, squared
        ranking values) and cosine (cosine-distance radius; db rows were
        unit-normalized at placement, queries here; the count runs on
        the unit-vector squared-L2 equivalent ``2 * (1 - sim)``).  L1
        has no sharded count program; when the placement kept a host
        copy of the train array (any host-array construction) it falls
        back to the single-device ops.radius path — mask and count share
        ONE pairwise computation there, so L1 results have the stronger
        single-program boundary contract — and raises for pre-placed
        multi-process arrays (no host copy to fall back to).

        Boundary contract: the mask (the sharded select's values) and
        the count (the count program) are DIFFERENT XLA programs, so a
        row within a float32 ulp of the radius can land on different
        sides in each — counts may differ from the visible in-radius
        entries by such boundary rows, and near-tied in-radius entries
        may ORDER differently than the single-device path (each program
        is lexicographic over its own f32 values).  Decisive semantics
        need a radius off the data's distance values (cf. tests'
        _safe_radius); this is inherent to f32 multi-program arithmetic,
        unlike the single-device ops.radius path whose mask and count
        share one pairwise computation.  bf16 placements are refused outright —
        a bf16-ranked mask against an f32 count would widen the
        boundary band ~2000x."""
        self._require_resident("radius_search")
        from knn_tpu.ops.radius import SENTINEL_IDX, radius_threshold

        if self._dtype_key is not None:
            raise ValueError(
                f"radius_search needs a float32 placement; this program "
                f"was built with compute_dtype={self._dtype_key!r} and "
                f"its mask/count arithmetics would disagree at the "
                f"radius boundary"
            )
        if self.metric in ("l1", "manhattan", "cityblock"):
            # single-device fallback: no sharded L1 count program exists,
            # but ops.radius runs mask and count off ONE pairwise pass
            from knn_tpu.ops.radius import radius_search as _radius_single

            if int(max_neighbors) < 1:
                raise ValueError(
                    f"max_neighbors must be >= 1, got {max_neighbors}")
            try:
                db_host = self._host_train()
            except ValueError as e:
                raise ValueError(
                    "sharded radius_search has no L1 count program and the "
                    "single-device fallback needs a host copy of the "
                    "database; construct ShardedKNN from a host array, or "
                    "use ops.radius.radius_search directly"
                ) from e
            d, i, counts = _radius_single(
                np.asarray(queries, np.float32), db_host, radius,
                max_neighbors=min(int(max_neighbors), self.n_train),
                metric="l1", train_tile=self.train_tile,
            )
            return np.asarray(d), np.asarray(i), np.asarray(counts)
        thr = radius_threshold(radius, self.metric)  # ranking space
        if self.metric == "cosine":
            if not self._cosine_unit:
                raise ValueError(
                    "cosine radius_search needs the database normalized at "
                    "placement; construct ShardedKNN from a host array"
                )
            count_thr = 2.0 * thr  # unit rows: ||q^-t^||^2 = 2 (1 - sim)
            q_count = _row_normalize_f64(np.asarray(queries, np.float32))
        elif self.metric in ("l2", "sql2", "euclidean"):
            count_thr = thr
            q_count = queries
        else:
            raise ValueError(
                f"sharded radius_search supports l2/cosine, not "
                f"{self.metric!r}; use ops.radius.radius_search"
            )
        shard_rows = self._shard_rows()
        m = min(int(max_neighbors), self.n_train)
        if m < 1:
            raise ValueError(f"max_neighbors must be >= 1, got {max_neighbors}")
        if m > shard_rows:
            # NEVER silently narrow: a caller testing counts > M for
            # truncation would read a shard-clamped result as complete
            # (same contract as search()'s k check above)
            raise ValueError(
                f"max_neighbors={m} exceeds db shard size {shard_rows}; "
                f"use fewer db shards"
            )
        d, i = self.search(queries, k=m)
        d, i = np.asarray(d), np.asarray(i)
        # counts: the distributed count-below pass (strictly <);
        # nextafter lifts it to <= in float32.  The l2 branch pays a
        # second h2d placement of the same queries (search placed its
        # own copy internally) — only the cosine branch genuinely needs
        # a different (renormalized) placement; accepted because the
        # count pass needs a query placement either way and search()
        # does not expose its internal one.
        count_fn = _count_program(self.mesh, self.n_train, self.train_tile)
        qp, n_q = self._place_queries(np.asarray(q_count, np.float32))
        thr_vec = np.full(
            qp.shape[0],
            np.nextafter(np.float32(count_thr), np.float32(np.inf)),
            np.float32,
        )
        out = _retry_transient(
            lambda: count_fn(qp, self._tp, thr_vec), "radius count dispatch")
        counts = _fetch_or_redispatch(
            out, lambda: count_fn(qp, self._tp, thr_vec),
            "radius count fetch",
        )[:n_q]
        within = d <= thr
        return (
            np.where(within, d, np.inf),
            np.where(within, i, SENTINEL_IDX),
            counts,
        )

    # -- certified-exact path (ops.certified, distributed) -----------------
    def _host_train(self) -> np.ndarray:
        """Host copy of the (unpadded) database for float64 refinement;
        fetched from the mesh once and cached when the caller didn't keep
        a host array around."""
        if self._train_host is None:
            if not self._tp.is_fully_addressable:
                raise ValueError(
                    "certified search needs a host copy of the database, but "
                    "the pre-placed global array spans multiple processes; "
                    "construct ShardedKNN from a host array instead"
                )
            self._train_host = np.asarray(self._tp)[: self.n_train]
        return self._train_host

    def _db_norm_max(self) -> float:
        """Largest float64 squared row norm of the database — the
        query-independent half of the certificate tolerance; a full-DB
        float64 pass, so computed once per placement and cached."""
        if self._db_norm_max_cache is None:
            db = self._host_train()
            self._db_norm_max_cache = float(
                (db.astype(np.float64) ** 2).sum(-1).max()
            )
        return self._db_norm_max_cache

    def _int8_placement(self) -> dict:
        """The quantized db placement for the int8 coarse pass, built
        LAZILY on first use and cached: per-row symmetric int8 values +
        f32 scales + f32 shifted-space row norms live on device sharded
        along the db axis (1/4 the coarse-pass HBM traffic of the f32
        db), plus the replicated bound-consts vector the certificate
        widens its threshold with (ops.quantize.bound_consts).  uint8
        sources (bvecs payloads) ride byte-exact at unit scale; anything
        else quantizes the host f32 rows once.  The f32 placement
        (``self._tp``) stays — the rescore gather, the fallback
        programs, and every non-int8 selector still read it."""
        if self._int8_cache is None:
            from knn_tpu.ops import quantize as qz

            with self._engines_lock:
                if self._int8_cache is not None:
                    return self._int8_cache
                host = self._host_train()
                if self._uint8_train is not None:
                    qr = qz.from_uint8(self._uint8_train)
                    original = self._uint8_train
                else:
                    qr = qz.quantize_rows_np(host)
                    original = host
                stats = qz.db_bound_stats(qr, original)
                # pad to the f32 placement's row count: zero rows at zero
                # scale with a huge norm score ~PAD_VAL — never candidates
                # (the kernel masks them by index anyway), never deflating
                # an exclusion bound
                rows = self._tp.shape[0]
                pad = rows - qr.values.shape[0]
                vals = np.pad(qr.values, ((0, pad), (0, 0)))
                scl = np.pad(qr.scales, (0, pad)).astype(np.float32)
                # shifted-space f32 row norms, computed in f64 then cast
                # (error < 1 ulp — tighter than an f32 reduction tree)
                tn = np.empty(rows, dtype=np.float32)
                for lo in range(0, host.shape[0], 65536):
                    hs = host[lo : lo + 65536].astype(np.float64) - qr.offset
                    tn[lo : lo + hs.shape[0]] = (hs ** 2).sum(-1)
                from knn_tpu.ops.pallas_knn import PAD_VAL

                tn[host.shape[0]:] = PAD_VAL
                self._int8_cache = {
                    "values": shard(vals, self.mesh, DB_AXIS),
                    "scales": shard(scl, self.mesh, DB_AXIS),
                    "norms": shard(tn, self.mesh, DB_AXIS),
                    "consts": replicate(qz.bound_consts(stats), self.mesh),
                    "offset": float(qr.offset),
                    "stats": stats,
                }
        return self._int8_cache

    def _int4_placement(self) -> dict:
        """The nibble-packed db placement for the int4 coarse pass —
        :meth:`_int8_placement` one byte-width rung down, same lazy
        cache discipline.  Rows quantize per-row symmetric to [-7, 7]
        (ops.quantize.quantize_rows_int4_np), dims zero-pad to a
        DIM_CHUNK multiple, then pack two-nibbles-per-byte
        (ops.quantize.pack_nibbles) — HALF the int8 stream.  The bound
        machinery is shared VERBATIM with int8: the unpacked int8-range
        values feed db_bound_stats (actual residuals), so the
        certificate's ε needs no new derivation.  No uint8 byte-exact
        shortcut here — bytes don't fit 4 bits."""
        if self._int4_cache is None:
            from knn_tpu.ops import quantize as qz
            from knn_tpu.ops.pallas_knn import DIM_CHUNK, PAD_VAL

            with self._engines_lock:
                if self._int4_cache is not None:
                    return self._int4_cache
                host = self._host_train()
                qr = qz.quantize_rows_int4_np(host)
                stats = qz.db_bound_stats(qr, host)
                rows = self._tp.shape[0]
                pad = rows - qr.values.shape[0]
                d = qr.values.shape[1]
                dpad = -(-d // DIM_CHUNK) * DIM_CHUNK - d
                # zero-padded dims pack to the biased-zero nibble (8)
                # and decode back to 0; zero pad ROWS pack to zero
                # bytes, killed by zero scale + PAD_VAL norm like int8
                vals = np.pad(qr.values, ((0, pad), (0, dpad)))
                packed = qz.pack_nibbles(vals)
                scl = np.pad(qr.scales, (0, pad)).astype(np.float32)
                tn = np.empty(rows, dtype=np.float32)
                for lo in range(0, host.shape[0], 65536):
                    hs = host[lo : lo + 65536].astype(np.float64)
                    tn[lo : lo + hs.shape[0]] = (hs ** 2).sum(-1)
                tn[host.shape[0]:] = PAD_VAL
                self._int4_cache = {
                    "values": shard(packed, self.mesh, DB_AXIS),
                    "scales": shard(scl, self.mesh, DB_AXIS),
                    "norms": shard(tn, self.mesh, DB_AXIS),
                    "consts": replicate(qz.bound_consts(stats), self.mesh),
                    "offset": float(qr.offset),
                    "stats": stats,
                }
        return self._int4_cache

    def _pq_placement(self, dsub: Optional[int] = None,
                      ncodes: Optional[int] = None) -> dict:
        """The product-quantized db placement for the pq coarse pass:
        per-subspace codebooks trained ONCE with the IVF tier's seeded
        deterministic k-means (ops.pq.train_pq) and the corpus encoded
        as a list-major [N, m] byte tensor — ``ceil(d/dsub)`` B/row.
        Codes shard along the db axis; the codebooks and the
        per-subspace bound-consts vector replicate (they are tiny).
        Cached per (dsub, ncodes) geometry; defaults come from
        KNN_TPU_PQ_DSUB / KNN_TPU_PQ_NCODES env, else the classic
        (4, 256) point (analysis.widths)."""
        import os as _os

        from knn_tpu.analysis import widths
        from knn_tpu.ops import pq as pqm

        def _env_int(name, fallback):
            raw = _os.environ.get(name, "").strip()
            if not raw:
                return int(fallback)
            try:
                return int(raw)
            except ValueError as e:
                raise ValueError(f"{name}={raw!r} is not an int") from e

        dsub = int(dsub) if dsub else _env_int(
            "KNN_TPU_PQ_DSUB", widths.PQ_DSUB_DEFAULT)
        ncodes = int(ncodes) if ncodes else _env_int(
            "KNN_TPU_PQ_NCODES", widths.PQ_NCODES_DEFAULT)
        key = (dsub, ncodes)
        if key not in self._pq_cache:
            with self._engines_lock:
                if key in self._pq_cache:
                    return self._pq_cache[key]
                host = self._host_train()
                res = pqm.train_pq(host, mesh=self.mesh, dsub=dsub,
                                   ncodes=ncodes)
                rows = self._tp.shape[0]
                # zero-code pad rows reconstruct to an ordinary point;
                # they can transiently occupy candidate slots but the
                # global-index mask (n_train) keeps them out of every
                # answer, and any crowding a tiny pad tail causes lands
                # in the bad-flag -> fallback repair, never silently
                codes = np.pad(res.codes,
                               ((0, rows - res.codes.shape[0]), (0, 0)))
                self._pq_cache[key] = {
                    "codes": shard(codes, self.mesh, DB_AXIS),
                    "books": replicate(res.codebooks, self.mesh),
                    "consts": replicate(pqm.bound_consts_pq(res.stats),
                                        self.mesh),
                    "stats": res.stats,
                    "dsub": dsub,
                    "ncodes": ncodes,
                }
        return self._pq_cache[key]

    def _pallas_operands(self, precision: str) -> tuple:
        """The operand tail of the pallas certified program after
        ``(queries, db)`` — ONE home shared by :meth:`_certify_pallas`
        and bench.py's phase breakdown so neither can call the program
        with the wrong arity: int8/int4 pass the quantized placement
        (packed values for int4); pq passes (codes, codebooks, consts);
        the f32 precisions pass the scalar db-norm bound."""
        if precision in ("int8", "int4"):
            pl = (self._int8_placement() if precision == "int8"
                  else self._int4_placement())
            return (pl["values"], pl["scales"], pl["norms"],
                    pl["consts"])
        if precision == "pq":
            plq = self._pq_placement()
            return (plq["codes"], plq["books"], plq["consts"])
        return (np.float32(self._db_norm_max()),)

    def search_certified(
        self, queries, *, margin: int = 28, selector: str = "approx",
        batch_size: Optional[int] = None, tile_n: Optional[int] = None,
        precision: Optional[str] = None, return_distances: bool = True,
        bin_w: Optional[int] = None, survivors: Optional[int] = None,
        block_q: Optional[int] = None, final_select: Optional[str] = None,
        recall_target: Optional[float] = None,
        binning: Optional[str] = None,
        final_recall_target: Optional[float] = None,
        grid_order: Optional[str] = None,
        kernel: Optional[str] = None,
        tune_cache: Optional[str] = None,
        return_sqrt: bool = False,
        overlap: Optional[bool] = None,
        overlap_depth: Optional[int] = None,
    ):
        """Exact lexicographic top-k via the certified pipeline, sharded.
        Returns (dists_f64, idx, stats).  L2, cosine and dot (the
        certificate is a squared-L2 bound; cosine runs it on unit
        vectors — rows are normalized at placement, queries here — and
        is exact for the f32-row-normalized problem, distances returned
        as 1-similarity; dot/MIPS runs it on the norm-AUGMENTED vectors
        placed at construction — one extra column per row — and is
        exact for the f32-augmented problem, distances mapped back to
        pairwise_dot's negative-inner-product values).  L1 has no
        squared-L2-style bound and stays uncertified.  Two certificate
        strategies by ``selector``:

        - ``"approx"`` / ``"exact"``: coarse top-(k+margin), float64 host
          refine, then a distributed count-below pass (psum over the db
          axis) proves no neighbor was missed — two database passes.
        - ``"pallas"``: the fused kernel's exclusion bound IS the
          certificate (ops.pallas_knn) — ONE database pass; ``tile_n`` and
          ``precision`` tune the kernel.  ``precision="int8"`` streams a
          per-row-quantized int8 db (placed lazily, once — ops.quantize;
          ~2x bf16 MXU throughput, 1/4 the coarse HBM traffic) and widens
          the certify threshold by the PROVABLE per-query quantization
          bound ε, so quantization misses land in the fallback, never in
          the answer; uint8 (bvecs) databases ride byte-exact at unit
          scale.  The f32 placement stays resident for the rescore
          gather and the fallback/count programs.

        Queries failing certification rerun exactly either way; the
        returned INDICES are the exact lexicographic top-k regardless of
        selector.  Distances: the counted selectors return float64-exact
        values (unconditional host refine); the pallas selector returns
        device f32 direct-difference values (relative error <
        ops.pallas_knn.RANK_SLACK = 2^-18) except for near-tied or
        repaired entries, which are float64-exact — the cost of skipping
        the host refine that would otherwise cap throughput at ~4k q/s.

        ``return_distances=False`` returns ``(None, idx, stats)`` for any
        selector; on the pallas selector it also skips the top-k distance
        block's device->host transfer — worth ~20-25% at SIFT shape
        through a slow link, negligible when the sweep is
        compute-dominated (the published gist1m numbers differ only
        within run-to-run noise).

        ``batch_size`` streams the queries in fixed-size batches with the
        device stages pipelined against the host stages: every batch's
        coarse select is dispatched up front (one compiled shape), so the
        host refine / device->host transfer of batch b overlaps the
        device work of batches > b.  None = one batch (all queries at
        once).

        Pallas-selector tuning knobs (``tile_n``, ``block_q``, ``bin_w``,
        ``survivors``, ``precision``, ``final_select``, ``binning``,
        ``grid_order``, ``final_recall_target``, ``kernel``): any knob
        left at None resolves through ``knn_tpu.tuning.resolve`` — the
        persisted autotuner winner for this exact
        ``(device_kind, n, d, k, metric, dtype)`` when one exists
        (``python -m knn_tpu.cli tune``; ``tune_cache`` overrides the
        cache file), else the library defaults — and EXPLICIT values
        always win over both.  ``kernel`` picks the db-streaming
        strategy (ops.pallas_knn.KERNELS: "tiled" | the one-launch
        double-buffered "streaming").  ``recall_target`` tunes the
        counted "approx" selector's per-element ApproxTopK recall
        (None = its default 0.95; raise toward 0.9999 with a wider
        ``margin`` to push the fallback rate below 1%).  The resolved
        knob set and its provenance land in
        ``stats["pallas_knobs"]`` / ``stats["tuning"]``.

        ``overlap`` (pallas selector only) runs the certified program as
        a TWO-STAGE device pipeline split at the packed-candidate
        boundary: batch i's select/rescore/certify tail executes while
        batch i+1's coarse pass streams the database, with at most
        ``overlap_depth`` (default 2; KNN_TPU_PIPELINE_DEPTH) batches in
        flight and the candidate carry buffers donated between stages.
        Results are BITWISE-identical to the sequential path (pinned in
        tests/test_fused_overlap.py); ``stats["pipeline"]`` reports the
        measured dispatch-timeline overlap ratio, mirrored by the
        ``knn_tpu_pipeline_overlap_ratio`` gauge and a
        ``certified.pipeline`` span.  None resolves the
        ``KNN_TPU_PIPELINE_OVERLAP`` env switch (off by default — it is
        a scheduling choice, never a result change, so it is NOT an
        autotuner knob).
        """
        import os as _os

        self._require_resident("search_certified")
        if overlap is None:
            # strict opt-in vocabulary, like serving.admission's env
            # knobs: anything else (off/no/typos) stays sequential
            overlap = _os.environ.get(
                "KNN_TPU_PIPELINE_OVERLAP", "").strip().lower() in (
                    "1", "true", "on", "yes")
        if overlap_depth is None:
            try:
                overlap_depth = int(_os.environ.get(
                    "KNN_TPU_PIPELINE_DEPTH", "2"))
            except ValueError:
                overlap_depth = 2
        if self.metric == "cosine":
            # runs the l2 certificate on unit vectors (db rows were
            # normalized at placement): EXACT for the f32-row-normalized
            # problem; returned distances are converted back to cosine
            # values (1 - q^.t^ = ||q^-t^||^2 / 2) below.  L1 stays
            # uncertified: the count-below / exclusion-bound certificates
            # are squared-L2 inequalities and |q-t|_1 admits no
            # gram-matrix form to bound (SURVEY §7 step 1).
            if not self._cosine_unit:
                raise ValueError(
                    "cosine search_certified needs the database normalized "
                    "at placement; construct ShardedKNN from a host array "
                    "(pre-placed arrays arrive already sharded, so "
                    "row-normalize them and use metric='l2' instead)"
                )
        elif self.metric == "dot":
            # MIPS runs the l2 certificate in the norm-augmented space
            # built at placement (__init__): the augmented-L2 ranking is
            # the inner-product ranking per query (affine map), so the
            # certificate is EXACT for the f32-augmented problem; scores
            # map back to pairwise_dot values (negative inner product)
            # below.
            if not self._dot_aug:
                raise ValueError(
                    "dot search_certified needs the norm-augmented "
                    "placement built at construction; construct ShardedKNN "
                    "from a host array (pre-placed arrays arrive already "
                    "sharded — augment the rows yourself and use "
                    "metric='l2' instead)"
                )
        elif self.metric not in ("l2", "sql2", "euclidean"):
            raise ValueError(
                "search_certified supports the l2, cosine and dot "
                "metrics only")
        if selector not in SELECTORS:
            raise ValueError(f"unknown selector {selector!r}; expected {SELECTORS}")
        from knn_tpu.ops.certified import repair_uncertified

        q_np = np.asarray(queries, dtype=np.float32)
        if self.metric == "cosine":
            q_np = _row_normalize_f64(q_np)
        q_norm2 = None
        if self.metric == "dot":
            # augment queries with the zero column matching the placed
            # rows' augmentation; keep per-query f64 ||q||^2 for the
            # score back-map at the end
            q64 = q_np.astype(np.float64)
            q_norm2 = np.einsum("nd,nd->n", q64, q64)
            q_np = np.concatenate(
                [q_np, np.zeros((q_np.shape[0], 1), np.float32)], axis=1)
        # every certified stage runs in squared-L2 space (for cosine: on
        # the unit vectors placed at construction / normalized above;
        # for dot: on the norm-augmented vectors)
        cert_metric = ("l2" if self.metric in ("cosine", "dot")
                       else self.metric)
        n_q = q_np.shape[0]
        shard_rows = self._shard_rows()
        # margin is bounded by both the db size and the per-shard rows the
        # coarse/fallback programs select from (k itself fits: __init__
        # checks k <= shard_rows)
        m = min(self.k + margin, self.n_train, shard_rows)
        db_np = self._host_train()

        if batch_size is not None and batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        bs = n_q if batch_size is None else batch_size
        # the db-side term of the certificate tolerance is query-independent
        # and cached across calls (a float64 pass over all N rows)
        db_norm_max = self._db_norm_max()
        batches = []
        for lo in range(0, n_q, bs):
            chunk = q_np[lo : lo + bs]
            pad = bs - chunk.shape[0]
            if pad:  # one compiled shape for the tail too
                chunk = np.pad(chunk, ((0, pad), (0, 0)))
            batches.append((lo, chunk, pad))

        d = np.empty((n_q, self.k))
        i = np.empty((n_q, self.k), dtype=np.int64)

        tune_info = None
        if selector == "pallas":
            # ONE knob-resolution home (knn_tpu.tuning): explicit args >
            # the persisted autotuner winner for this placement's shape >
            # library defaults
            from knn_tpu import tuning

            knobs, tune_info = tuning.resolve_full(
                self.n_train, self._tp.shape[1], self.k,
                metric=cert_metric, dtype=self._dtype_key,
                cache_path=tune_cache,
                overrides=dict(
                    tile_n=tile_n, precision=precision, bin_w=bin_w,
                    survivors=survivors, block_q=block_q,
                    final_select=final_select, binning=binning,
                    final_recall_target=final_recall_target,
                    grid_order=grid_order, kernel=kernel,
                ),
            )
            bad, n_corrected = self._certify_pallas(
                batches, bs, m, d, i, q_np, db_np, db_norm_max,
                want_distances=return_distances, overlap=overlap,
                overlap_depth=overlap_depth, **knobs,
            )
        else:
            bad = self._certify_counted(
                batches, bs, m, d, i, q_np, db_np, db_norm_max, selector,
                recall_target=recall_target, metric=cert_metric,
            )

        def _select(qb, widen):
            # widened exact-selector re-select (bounded by the per-shard
            # rows the SPMD select can fetch); the returned f32 scores
            # carry the re-certification exclusion value, so the select
            # must run in f32 (dtype_key None) even when the main path is
            # bf16 — certification_tolerance only covers f32 error
            exact = _knn_program(
                self.mesh, widen, cert_metric, self.merge, self.n_train,
                self.train_tile, None, "exact",
                dcn_merge=self.dcn_merge,
            )
            bq, _ = self._place_queries(qb)
            fs, fi = exact(bq, self._tp)
            n_b = qb.shape[0]
            return np.asarray(fs)[:n_b], np.asarray(fi)[:n_b]

        repair = repair_uncertified(
            d, i, self.k, m, bad, q_np, db_np,
            select_fn=_select,
            max_widen=min(self.n_train, shard_rows),
            db_norm_max=db_norm_max,
        )
        stats = {
            "fallback_queries": int(bad.size),
            "certified": n_q - int(bad.size),
            **repair,
        }
        if selector == "pallas":
            stats["rank_corrected_queries"] = n_corrected
            stats["pallas_knobs"] = knobs
            stats["tuning"] = tune_info
            if overlap and self._last_pipeline is not None:
                stats["pipeline"] = dict(self._last_pipeline)
        # mirror the quality signals into the telemetry registry — the
        # per-call stats dict stays the API, the registry accumulates the
        # process-lifetime truth a scraper reads (docs/OBSERVABILITY.md)
        obs.counter(_mn.CERTIFIED_QUERIES, selector=selector).inc(n_q)
        obs.counter(_mn.CERTIFIED_FALLBACKS, selector=selector).inc(
            int(bad.size))
        obs.counter(_mn.CERTIFIED_GENUINE_MISSES, selector=selector).inc(
            repair.get("fallback_genuine_misses", 0))
        obs.counter(_mn.CERTIFIED_FALSE_ALARMS, selector=selector).inc(
            repair.get("fallback_false_alarms", 0))
        obs.counter(_mn.CERTIFIED_HOST_EXACT, selector=selector).inc(
            repair.get("host_exact_queries", 0))
        if selector == "pallas":
            obs.counter(_mn.CERTIFIED_RANK_CORRECTED).inc(n_corrected)
        if return_distances and self.metric == "cosine":
            # unit-vector squared L2 -> cosine distance values, exactly
            # (matches pairwise_cosine's 1 - similarity convention)
            d *= 0.5
        if return_distances and self.metric == "dot":
            # augmented-space squared L2 -> pairwise_dot values (negative
            # inner product): invert the affine map in f64 —
            # ||q'-t'||^2 = ||q||^2 + M - 2 q.t, so
            # -q.t = (||q'-t'||^2 - ||q||^2 - M) / 2.  Indices and
            # certification are unaffected (the map is monotone per
            # query); values then flow through metric_values like any
            # other metric (dot passes through).
            d -= q_norm2[:, None] + self._dot_shift
            d *= 0.5
        if return_distances and return_sqrt:
            # true Euclidean values (knn_mpi.cpp:48 / sklearn convention);
            # indices and certification are unaffected (monotone map)
            from knn_tpu.ops.distance import metric_values

            d = metric_values(d, self.metric)
        return (d if return_distances else None), i, stats

    def _certify_counted(
        self, batches, bs, m, d, i, q_np, db_np, db_norm_max, selector,
        recall_target: Optional[float] = None, metric: Optional[str] = None,
    ):
        """Two-pass certificate: coarse select + refine, then the
        distributed count-below program proves completeness.  Returns the
        flagged query indices.

        The count threshold is ADAPTIVE: the refine already produced the
        float64 distances of every candidate, so each query counts
        against the midpoint of the first inter-neighbor gap at rank
        j >= k that exceeds twice the count pass's float32 tolerance
        (count <= j proves no outsider sits at or below the j-th
        candidate, and ranks <= j are float64-refined).  The fixed
        ``d_k + tol`` threshold false-alarmed whenever ANY point sat
        within tol of d_k — at SIFT1M scale ~2.4% of queries
        (TUNING_r03: 100/4096 fallbacks, all false alarms at
        recall_target 0.9999); a gap beyond which the midpoint clears
        tol almost always exists inside the margin window, so the
        adaptive form certifies those queries instead."""
        from knn_tpu.ops.certified import certification_tolerance
        from knn_tpu.ops.refine import refine_exact

        n_q = q_np.shape[0]
        k = self.k
        coarse = _knn_program(
            self.mesh, m, metric or self.metric, self.merge, self.n_train,
            self.train_tile, self._dtype_key, selector,
            recall_target=recall_target, dcn_merge=self.dcn_merge,
        )
        count_fn = _count_program(self.mesh, self.n_train, self.train_tile)

        # stage 1: dispatch every batch's coarse select (async on device)
        coarse_out = []
        for lo, chunk, pad in batches:
            qp, _ = self._place_queries(chunk)
            coarse_out.append((
                qp, _retry_transient(lambda q=qp: coarse(q, self._tp),
                                     "coarse dispatch")))

        # stage 2: per batch — sync its candidates, float64 host refine
        # (overlapping later batches' device work), dispatch its count
        count_out = []
        for (lo, chunk, pad), (qp, (_, ci)) in zip(batches, coarse_out):
            take = bs - pad
            ci = _fetch_or_redispatch(
                ci, lambda q=qp: coarse(q, self._tp)[1], "coarse fetch"
            )[:take]
            m_avail = ci.shape[1]
            # refine ALL candidates: ranks k..m feed the gap search
            d_m, i_m = refine_exact(db_np, q_np[lo : lo + take], ci, m_avail)
            d_b, i_b = d_m[:, :k], i_m[:, :k]
            d[lo : lo + take], i[lo : lo + take] = d_b, i_b
            tol = certification_tolerance(
                q_np[lo : lo + take], db_np, db_norm_max=db_norm_max
            )
            # first rank j in [k, m_avail) whose gap d[j] - d[j-1]
            # exceeds 2*tol (js = that j, or k when none does — the
            # fixed-threshold behavior)
            gaps = d_m[:, k:] - d_m[:, k - 1 : -1]  # [take, m_avail - k]
            # the midpoint is cast to f32 for the count program: demand
            # the gap also clear that rounding, and never use a gap to a
            # sentinel (+inf) rank
            f32_round = 4.0 * float(np.finfo(np.float32).eps) * np.abs(
                d_m[:, k:])
            open_gap = (gaps > 2.0 * tol[:, None] + f32_round) & np.isfinite(
                d_m[:, k:])
            if open_gap.shape[1] == 0:  # m == k: no window, fixed threshold
                has = np.zeros(take, dtype=bool)
                js = np.full(take, k)
            else:
                has = open_gap.any(axis=-1)
                js = np.where(has, k + open_gap.argmax(axis=-1), k)
            dj = np.take_along_axis(d_m, js[:, None] - 1, axis=-1)[:, 0]
            # js == m_avail only when has is False (np.where evaluates
            # both branches): clip the gather, the fixed arm wins anyway
            d_js = np.take_along_axis(
                d_m, np.minimum(js, m_avail - 1)[:, None], axis=-1
            )[:, 0]
            mid = np.where(has, 0.5 * (dj + d_js), dj + tol)
            thr_p = np.full(qp.shape[0], -np.inf, dtype=np.float32)
            thr_p[:take] = mid
            thr_s = shard(thr_p, self.mesh, QUERY_AXIS)
            count_out.append((
                lo, take, js, qp, thr_s, mid, d_m[:, k - 1].copy(),
                _retry_transient(lambda q=qp, t=thr_s: count_fn(q, self._tp, t),
                                 "count dispatch"),
            ))

        # stage 3: collect certificates (count <= per-query rank bound)
        flagged = []
        for lo, take, js, qp, thr_s, mid, d_k, c in count_out:
            c_np = _fetch_or_redispatch(
                c, lambda q=qp, t=thr_s: count_fn(q, self._tp, t),
                "count fetch")
            over = c_np[:take] > js
            flagged.append(lo + np.flatnonzero(over))
            # certificate-margin telemetry: per certified query, the
            # headroom between the k-th refined distance and the count
            # threshold it was proven against (relative; ~0 = one
            # near-boundary point away from a fallback)
            ok = ~over
            if obs.enabled() and ok.any():
                denom = np.maximum(np.abs(mid[ok]), 1e-30)
                obs.histogram(_mn.CERTIFIED_MARGIN, path="sharded"
                              ).observe_many(
                    ((mid[ok] - d_k[ok]) / denom).tolist())
        return np.concatenate(flagged) if flagged else np.empty(0, np.int64)

    def _pallas_setup(self, margin: int, tile_n: Optional[int],
                      precision: str, bin_w: Optional[int] = None,
                      survivors: Optional[int] = None,
                      block_q: Optional[int] = None,
                      final_select: str = "exact",
                      include_distances: bool = True,
                      binning: str = "grouped",
                      final_recall_target: Optional[float] = None,
                      grid_order: str = "query_major",
                      kernel: str = "tiled",
                      split: bool = False):
        """(program, m, analysis_window) for the one-pass certified
        path — the ONE home of the kernel-geometry margin cap and the
        packed-output window, shared by :meth:`_certify_pallas` and
        bench.py's phase breakdown so they can never measure different
        programs or unpack different column layouts."""
        from knn_tpu.ops.pallas_knn import (
            BIN_W,
            TILE_N,
            _geometry,
            effective_tile,
        )

        from knn_tpu.utils.config import CERTIFIED_PRECISIONS

        if precision not in CERTIFIED_PRECISIONS:
            # "default" has no certified tolerance model (its matmul error
            # is ~2^-10 relative — certificate-hostile); refuse rather
            # than silently certify garbage
            raise ValueError(
                f"precision {precision!r} has no certified tolerance "
                f"model; use one of {CERTIFIED_PRECISIONS}"
            )
        quant_offset = 0.0
        if precision in ("int8", "int4"):
            # builds (and caches) the quantized placement: the program
            # needs the translation-invariance shift as a static constant
            quant_offset = (self._int8_placement() if precision == "int8"
                            else self._int4_placement())["offset"]

        eff_bin = bin_w or BIN_W
        shard_rows = self._shard_rows()
        # same tile the kernel will pick (ONE home for the arithmetic:
        # ops.pallas_knn.effective_tile), so the m-cap below matches the
        # kernel's real candidate width
        eff_tile = effective_tile(shard_rows, tile_n or TILE_N, eff_bin,
                                  survivors, binning,
                                  min(self.k + margin, shard_rows) + 2)
        _, _, out_w, _ = _geometry(eff_tile, eff_bin, survivors, binning)
        # m is bounded by the db, the per-shard rows, and the kernel's
        # per-shard candidate width minus the two slots the exclusion
        # value needs (ops.pallas_knn.local_certified_candidates)
        m = min(self.k + margin, self.n_train, shard_rows,
                -(-shard_rows // eff_tile) * out_w - 2)
        if m <= self.k:
            raise ValueError(
                f"pallas selector: margin headroom m={m} <= k={self.k} on "
                f"{shard_rows}-row shards; lower tile_n or use "
                f"selector='approx'"
            )
        # the program gets setup's RESOLVED tile, not the raw request:
        # m was capped so that width(eff_tile) >= m+2, which makes the
        # kernel's own effective_tile(min_width=m+2) a fixpoint — the
        # tile the kernel runs is provably the tile this m-cap assumed
        # (ADVICE r4: the raw-tile plumbing let the two diverge on small
        # padded dbs where m is capped by n_train)
        if split:
            # the two-stage pipeline's program pair, split at the
            # packed-candidate boundary; the tail donates the candidate
            # carries on backends whose XLA honors donation
            import jax as _jax

            coarse = _pallas_coarse_program(
                self.mesh, m, eff_tile, precision, bin_w=bin_w,
                survivors=survivors, block_q=block_q,
                final_select=final_select, binning=binning,
                grid_order=grid_order, kernel=kernel,
                quant_offset=quant_offset,
            )
            tail = _pallas_tail_program(
                self.mesh, m, self.k, self.merge, precision,
                n_train=self.n_train, final_select=final_select,
                include_distances=include_distances,
                final_recall_target=final_recall_target,
                quant_offset=quant_offset,
                donate=_jax.default_backend() != "cpu",
                dcn_merge=self.dcn_merge,
            )
            return (coarse, tail), m, _analysis_window(self.k, m)
        prog = _pallas_certified_program(
            self.mesh, m, self.k, self.merge, eff_tile, precision,
            n_train=self.n_train, bin_w=bin_w, survivors=survivors,
            block_q=block_q, final_select=final_select,
            include_distances=include_distances, binning=binning,
            final_recall_target=final_recall_target,
            grid_order=grid_order, kernel=kernel,
            quant_offset=quant_offset, dcn_merge=self.dcn_merge,
        )
        return prog, m, _analysis_window(self.k, m)

    def _certify_pallas(
        self, batches, bs, m, d, i, q_np, db_np, db_norm_max, *,
        tile_n, precision, want_distances=True, bin_w=None, survivors=None,
        block_q=None, final_select="exact", binning="grouped",
        final_recall_target=None, grid_order="query_major",
        kernel="tiled", overlap=False, overlap_depth=2,
    ):
        """One-pass certificate, host side.  The device already ranked the
        candidates, flagged uncertified rows, and marked near-tie pairs
        (_pallas_certified_program); the host fetches ONLY the windowed
        indices, the bit-packed tight-pair mask, and the bad flags (plus
        the top-k distance block when ``want_distances``) — nothing wider
        crosses the slow device->host link — then repairs tie runs in
        float64 (ops.refine.rank_correct_runs).  Returns (flagged query
        indices, rank-corrected query count).

        ``overlap=True`` runs the TWO-STAGE pipeline instead of the
        one-shot program: the certified program is split at the
        packed-candidate boundary (coarse kernel | select/rescore/
        certify tail — _pallas_setup(split=True)), with at most
        ``overlap_depth`` batches in flight (the PR-1 dispatch-ahead
        discipline: drain the oldest before admitting a new one) so
        batch i's rescore/certify/fetch/host-repair overlaps batch
        i+1's coarse db stream.  Results are bitwise-identical to the
        sequential path — both run the same kernel, the same
        select/rescore ops, and the SAME certify/pack tail
        (_certify_pack_spmd) — pinned in tests/test_fused_overlap.py.
        The measured dispatch-timeline overlap lands in
        ``self._last_pipeline`` + the knn_tpu_pipeline_overlap_ratio
        gauge + a certified.pipeline span."""
        import time as _time

        from knn_tpu.ops.refine import rank_correct_runs

        k = self.k
        prog, m, w = self._pallas_setup(m - self.k, tile_n, precision,
                                        bin_w=bin_w, survivors=survivors,
                                        block_q=block_q,
                                        final_select=final_select,
                                        include_distances=want_distances,
                                        binning=binning,
                                        final_recall_target=final_recall_target,
                                        grid_order=grid_order,
                                        kernel=kernel, split=overlap)

        # stage 1: dispatch every batch (async on device).  The operand
        # tail is precision-shaped (int8: the quantized placement; f32:
        # the scalar norm bound) — ONE home, _pallas_operands
        ops_tail = self._pallas_operands(precision)
        if precision in ("int8", "int4", "pq") and obs.enabled():
            # the per-query certified quantization bound ε — the quality
            # signal the device certificate computes and discards
            # (quantize.score_error_bound_device / pq's twin):
            # recomputed host-side (O(Q·D), noise next to the O(Q·N·D)
            # sweep) and recorded as a distribution so a scraper sees
            # how tight the bound ran, not just the bench's one max
            if precision == "pq":
                from knn_tpu.ops.pq import score_error_bound_pq

                eps = score_error_bound_pq(
                    q_np, self._pq_placement()["stats"])
            else:
                from knn_tpu.ops.quantize import score_error_bound

                pl = (self._int8_placement() if precision == "int8"
                      else self._int4_placement())
                eps = score_error_bound(q_np, pl["stats"],
                                        offset=pl["offset"])
            obs.histogram(_mn.CERTIFIED_QUANT_BOUND).observe_many(eps)
        bad_mask = np.zeros(q_np.shape[0], dtype=bool)
        n_corrected = 0

        def repair(lo, pad, packed, redo):
            """ONE fetch of the packed output (the relay charges a fixed
            latency per transfer), then float64 tie-run repair — shared
            verbatim by the sequential and pipelined paths."""
            nonlocal n_corrected
            take = bs - pad
            packed_np = _fetch_or_redispatch(packed, redo, "pallas fetch")
            gi_np, tight_np, bad_np, dk_np = unpack_certified(
                packed_np[:take], k, w, want_distances
            )
            dc, ic, n_c = rank_correct_runs(
                gi_np, tight_np, k, q_np[lo : lo + take], db_np,
                d32k=None if dk_np is None else dk_np.astype(np.float64),
            )
            n_corrected += n_c
            if dc is not None:
                d[lo : lo + take] = dc
            i[lo : lo + take] = ic
            bad_mask[lo : lo + take] = bad_np

        if overlap:
            coarse, tail = prog
            depth = max(1, int(overlap_depth))
            intervals = []
            pending = []
            t_wall0 = _time.perf_counter()

            def finalize(rec):
                lo, pad, redo, packed, t0 = rec
                repair(lo, pad, packed, redo)
                intervals.append((t0, _time.perf_counter()))

            for lo, chunk, pad in batches:
                # the bounded in-flight window: drain the oldest batch
                # (its tail already executed while later coarse passes
                # streamed) before admitting a new one — the same
                # depth discipline ServingEngine.replay() runs
                while len(pending) >= depth:
                    finalize(pending.pop(0))
                t0 = _time.perf_counter()
                qp, _ = self._place_queries(chunk)

                def launch(q=qp):
                    # one dispatch unit: the tail consumes (donates) the
                    # coarse stage's candidate carries, so any retry
                    # must re-run the coarse pass too
                    cand = coarse(q, self._tp, *ops_tail)
                    return tail(q, self._tp, *cand, *ops_tail)

                packed = _retry_transient(launch, "pallas pipeline dispatch")
                pending.append((lo, pad, launch, packed, t0))
            while pending:
                finalize(pending.pop(0))
            wall = _time.perf_counter() - t_wall0
            ratio = _overlap_ratio(intervals)
            self._last_pipeline = {
                "depth": depth,
                "batches": len(batches),
                "overlap_ratio": round(ratio, 4),
                "wall_s": round(wall, 4),
            }
            obs.gauge(_mn.PIPELINE_OVERLAP_RATIO).set(ratio)
            obs.record_span("certified.pipeline", None, wall,
                            batches=len(batches), depth=depth,
                            overlap_ratio=round(ratio, 4))
            return np.flatnonzero(bad_mask), n_corrected

        outs = []
        for lo, chunk, pad in batches:
            qp, _ = self._place_queries(chunk)
            outs.append((qp, _retry_transient(
                lambda q=qp: prog(q, self._tp, *ops_tail),
                "pallas dispatch")))

        # stage 2: per batch — fetch + repair, in dispatch order
        for (lo, chunk, pad), (qp, packed) in zip(batches, outs):
            repair(lo, pad, packed,
                   lambda q=qp: prog(q, self._tp, *ops_tail))
        return np.flatnonzero(bad_mask), n_corrected

    def predict_certified(
        self, queries, *, margin: int = 28, selector: str = "approx",
        batch_size: Optional[int] = None, tile_n: Optional[int] = None,
        precision: Optional[str] = None, kernel: Optional[str] = None,
        tune_cache: Optional[str] = None,
    ):
        """Certified-exact classification: exact neighbor sets from
        :meth:`search_certified`, then the reference vote (ops.vote).
        Returns (labels [Q] int32, stats).  Kernel knobs left at None
        resolve through ``knn_tpu.tuning`` exactly like
        :meth:`search_certified`."""
        if self._labels is None:
            raise RuntimeError("ShardedKNN built without labels; predict unavailable")
        _, idx, stats = self.search_certified(
            queries, margin=margin, selector=selector, batch_size=batch_size,
            tile_n=tile_n, precision=precision, kernel=kernel,
            tune_cache=tune_cache,
            return_distances=False,  # labels only: skip the d transfer
        )
        labels_host = np.asarray(self._labels)
        votes = majority_vote(jnp.asarray(labels_host[idx]), self.num_classes)
        return np.asarray(votes), stats

    def predict(self, queries: jax.Array) -> jax.Array:
        """Predicted labels [Q] — requires ``labels`` at construction."""
        if self._labels is None:
            raise RuntimeError("ShardedKNN built without labels; predict unavailable")
        self._require_resident("predict")
        qp, n_q = self._place_queries(queries)
        fn = _predict_program(
            self.mesh, self.k, self.num_classes, self.metric, self.merge,
            self.n_train, self.train_tile, self._dtype_key,
            dcn_merge=self.dcn_merge,
        )
        out = _retry_transient(lambda: fn(qp, self._tp, self._labels),
                               "predict dispatch")
        return out[:n_q]


def sharded_knn(
    queries: jax.Array,
    train: jax.Array,
    k: int,
    *,
    mesh: Mesh,
    metric: str = "l2",
    merge: Optional[str] = None,
    train_tile: Optional[int] = None,
    compute_dtype=None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact KNN sharded over ``mesh``: (distances, global indices), [Q, k].

    Queries are sharded along the query axis, train along the db axis; both
    are padded to the mesh (the reference aborts instead,
    knn_mpi.cpp:127-129).  Results are bitwise-equal to single-device
    ``knn_search`` for any mesh shape and either merge strategy.  One-shot
    wrapper over :class:`ShardedKNN`.
    """
    prog = ShardedKNN(
        train, mesh=mesh, k=k, metric=metric, merge=merge,
        train_tile=train_tile, compute_dtype=compute_dtype,
    )
    return prog.search(queries)


@functools.lru_cache(maxsize=64)
def _predict_program(
    mesh: Mesh,
    k: int,
    num_classes: int,
    metric: str,
    merge: str,
    n_train: int,
    train_tile: Optional[int],
    compute_dtype,
    donate: bool = False,
    dcn_merge: Optional[str] = None,
):
    hosts, chips = db_topology(mesh)

    def spmd(q, t):
        return _merged_topk(
            q, t, k, metric, merge, n_train, train_tile, compute_dtype,
            hosts, chips, dcn_merge=dcn_merge,
        )

    knn = shard_map_compat(
        spmd,
        mesh=mesh,
        in_specs=(P(QUERY_AXIS), P(db_axes(mesh))),
        out_specs=(P(QUERY_AXIS), P(QUERY_AXIS)),
        check_vma=False,
    )

    def run(q, t, labels):
        # the vote runs OUTSIDE the shard_map body (still inside the one
        # jitted program, still on device): with check_vma/check_rep off,
        # GSPMD is free to assume a query-spec'd output is replicated
        # along the db axis, and on 2-D meshes it miscompiled the
        # in-body vote of the TILED search (every query shard got shard
        # 0's votes).  On the global [Q, k] index array the partitioner
        # handles the replicated-label gather + vote natively.
        _, gi = knn(q, t)
        safe = jnp.minimum(gi, n_train - 1)  # sentinel survives only if n_train < k (raised)
        return majority_vote(labels[safe], num_classes)

    return jax.jit(run, donate_argnums=(0,) if donate else ())


def sharded_knn_predict(
    train: jax.Array,
    train_labels: jax.Array,
    queries: jax.Array,
    *,
    k: int,
    num_classes: int,
    mesh: Mesh,
    metric: str = "l2",
    merge: Optional[str] = None,
    train_tile: Optional[int] = None,
    compute_dtype=None,
) -> jax.Array:
    """Distributed classify: the whole reference KNN phase (distance fill →
    select → vote, knn_mpi.cpp:308-393) as one SPMD program.  Labels ride
    replicated (they are tiny next to features); votes happen on-device so
    only final labels leave the mesh.  One-shot wrapper over
    :class:`ShardedKNN`."""
    prog = ShardedKNN(
        train, mesh=mesh, k=k, metric=metric, merge=merge,
        train_tile=train_tile, compute_dtype=compute_dtype,
        labels=train_labels, num_classes=num_classes,
    )
    return prog.predict(queries)


@functools.lru_cache(maxsize=32)
def _pallas_certified_program(
    mesh: Mesh, m: int, k: int, merge: str, tile_n: Optional[int],
    precision: str, n_train: Optional[int] = None,
    bin_w: Optional[int] = None, survivors: Optional[int] = None,
    block_q: Optional[int] = None, final_select: str = "exact",
    include_distances: bool = True, binning: str = "grouped",
    final_recall_target: Optional[float] = None,
    grid_order: str = "query_major",
    kernel: str = "tiled",
    quant_offset: float = 0.0,
    dcn_merge: Optional[str] = None,
):
    """ONE-pass sharded self-certifying coarse select + device rank +
    device certificate (ops.pallas_knn.local_certified_candidates per
    shard): candidates arrive as direct-difference f32 distances already
    in lexicographic order, merged across the db axis (ring/allgather as
    usual) while the kernel-space exclusion bounds pmin.

    The certificate and the near-tie analysis run ON DEVICE, and every
    host-facing output is packed into ONE int32 array — the dev
    harness's device->host relay charges ~65 ms latency PER FETCH on
    top of ~19 MB/s, so one call for one [Q, W + nw + 1 (+ k)] array
    beats four small ones by several fixed latencies per sweep.  Packed
    columns (see ``unpack_certified`` for the host-side inverse):

      [0, W)            i32   ranked global db row indices over the
                              analysis window W = min(k+17, m+1),
      [W, W+nw)         u32-bits  near-tie mask, bit-packed: bit j is 1
                              when positions j, j+1 are closer than
                              RANK_SLACK and sit before the top-k set
                              boundary's first big gap,
      [W+nw]            i32   bad flag: uncertified OR boundary-
                              unresolvable rows (repair reruns exactly),
      [W+nw+1, +k)      f32-bitcast  ranked direct-difference top-k
                              distances (``include_distances`` only —
                              label/index consumers skip the columns).

    Soundness: a db row outside the candidates has kernel score >= lb,
    or was merge-dropped with direct distance >= d32[:, m]; ``bad`` is
    the union of both checks plus rows whose tie run crosses the
    analysis window (no provable top-k boundary).

    ``precision="int8"`` swaps the operand tail: instead of the scalar
    ``db_norm_max`` the program takes the quantized placement
    ``(values, scales, norms)`` (each db-sharded) plus the replicated
    bound-consts vector, and the certificate's tolerance becomes the
    per-query PROVABLE quantization bound ε (ops.quantize.
    score_error_bound_device) — the kernel scores and lb live in the
    ``quant_offset``-shifted space, so the comparison uses the shifted
    query norm (squared L2 is translation invariant; the f32 rescore
    distances d32 are space-independent up to RANK_SLACK, which the
    derivation already budgets)."""
    from knn_tpu.ops.pallas_knn import (
        BIN_W,
        BLOCK_Q,
        TILE_N,
        local_certified_candidates,
    )

    hosts, chips = db_topology(mesh)
    eff_tile = tile_n or TILE_N
    eff_bin = bin_w or BIN_W
    eff_bq = block_q or BLOCK_Q
    w = _analysis_window(k, m)

    def spmd(q, t, *tail):
        db_q, db_pq, consts, db_norm_max = _split_operand_tail(
            precision, tail)
        d32, li, lb = local_certified_candidates(
            q, t, m, tile_n=eff_tile, bin_w=eff_bin, survivors=survivors,
            block_q=eff_bq, final_select=final_select, precision=precision,
            binning=binning, final_recall_target=final_recall_target,
            grid_order=grid_order, kernel=kernel,
            db_int8=db_q if precision == "int8" else None,
            db_int4=db_q if precision == "int4" else None,
            db_pq=db_pq, offset=quant_offset,
        )
        return _certify_pack_spmd(
            q, t, d32, li, lb, consts=consts, db_norm_max=db_norm_max,
            precision=precision, quant_offset=quant_offset, m=m, k=k, w=w,
            merge=merge, n_train=n_train, hosts=hosts, chips=chips,
            dcn_merge=dcn_merge,
            include_distances=include_distances,
            pq_dsub=None if db_pq is None else int(db_pq[1].shape[2]),
        )

    return jax.jit(
        shard_map_compat(
            spmd,
            mesh=mesh,
            in_specs=(P(QUERY_AXIS), P(db_axes(mesh)),
                      *_tail_specs(precision, mesh)),
            out_specs=P(QUERY_AXIS),
            check_vma=False,
        )
    )


def _tail_specs(precision: str, mesh: Mesh):
    """shard_map in_specs of the precision-shaped operand tail
    (ShardedKNN._pallas_operands): int8/int4 = the quantized placement
    (db-sharded values/scales/norms + replicated bound consts), pq =
    db-sharded codes + replicated codebooks + replicated per-subspace
    bound consts, f32 = the replicated scalar db-norm bound."""
    dbp = db_axes(mesh)
    if precision in ("int8", "int4"):
        return (P(dbp), P(dbp), P(dbp), P())
    if precision == "pq":
        return (P(dbp), P(), P())
    return (P(),)


def _split_operand_tail(precision: str, tail):
    """(db_quant, db_pq, consts, db_norm_max) from the operand tail —
    the per-precision unpacking every pallas-certified program shares.
    ``db_quant`` is the (values, scales, norms) triple of the int8 OR
    int4 arm (packed bytes for int4 — the kernel keyword decides which
    contract it rides); ``db_pq`` is (codes, codebooks)."""
    if precision in ("int8", "int4"):
        tq, ts, tnr, consts = tail
        return (tq, ts, tnr), None, consts, None
    if precision == "pq":
        codes, books, consts = tail
        return None, (codes, books), consts, None
    (db_norm_max,) = tail
    return None, None, None, db_norm_max


def _certify_pack_spmd(q, t, d32, li, lb, *, consts, db_norm_max,
                       precision, quant_offset, m, k, w, merge, n_train,
                       hosts, chips, include_distances,
                       dcn_merge=None, pq_dsub=None):
    """The certify/pack tail of the pallas certified program, from one
    shard's ranked candidates ``(d32, li, lb)`` to the packed host-facing
    int32 array — ONE home shared by the one-shot program
    (:func:`_pallas_certified_program`) and the pipeline-overlap tail
    stage (:func:`_pallas_tail_program`), which is what makes the
    two-stage path bitwise-identical to the sequential one: same merge,
    same rank analysis, same certificate, same packing, running inside
    either program."""
    from knn_tpu.ops.pallas_knn import RANK_SLACK

    db_shards = hosts * chips
    db_idx = _db_shard_index(hosts, chips)
    gi = jnp.where(li == _INT_SENTINEL, _INT_SENTINEL,
                   li + db_idx * t.shape[0])
    if n_train is not None:
        # pre-placed databases may be zero-padded by the caller (the
        # multihost contract); rows past n_train are padding, and a
        # zero pad row sits at the origin — mask by GLOBAL index so
        # it can never be returned as a neighbor
        pad = gi >= n_train
        gi = jnp.where(pad, _INT_SENTINEL, gi)
        d32 = jnp.where(pad, jnp.inf, d32)
    if db_shards > 1:
        # hierarchical merge tree: per-chip -> per-host over ICI, then
        # per-host -> global over DCN; the exclusion bound pmins over
        # every db-sharding axis in one reduction
        d32, gi = _merge_shards(d32, gi, m + 1, hosts, chips, merge,
                                dcn_merge)
        lb = lax.pmin(
            lb,
            axis_name=(HOST_AXIS, DB_AXIS) if hosts > 1 else DB_AXIS)

    # --- device rank analysis over the window [0, w) ---------------
    dw = d32[:, :w]
    gaps = dw[:, 1:] - dw[:, :-1]  # [Q, w-1]
    # isfinite guard: an (x, inf-sentinel) pair yields inf <= inf,
    # which must not count as a near-tie
    tight = (gaps <= RANK_SLACK * dw[:, 1:]) & jnp.isfinite(dw[:, 1:])
    pair = lax.broadcasted_iota(jnp.int32, tight.shape, 1)
    big_after = (~tight) & (pair >= k - 1)
    has_stop = big_after.any(axis=-1)
    stop = jnp.where(has_stop, jnp.argmax(big_after, axis=-1), w - 1)
    # rows without a provable boundary (or junk near it) rerun exactly
    unresolved = (~has_stop) | ~jnp.isfinite(dw[:, : k + 1]).all(-1)
    tight_use = tight & (pair < stop[:, None]) & ~unresolved[:, None]

    # --- device certificate ----------------------------------------
    # tolerances mirror ops.pallas_knn.kernel_tolerance and include
    # the extra f32 reduction this on-device path adds (q_norm +
    # s_k arithmetic, <= ~12 eps of the norm scale): "highest" budgets
    # 32 eps total; bf16x3's 2^-14 dwarfs the f32 terms either way.
    # int8/int4 tolerances are the per-query PROVABLE quantization
    # bound ε from the ACTUAL residual norms — byte-exact data (bvecs)
    # gets an ε of pure f32 slack, tighter than bf16x3's; pq's is the
    # per-subspace Cauchy-Schwarz bound (ops.pq, same actual-residual
    # discipline hoisted per subspace at encode time).
    q32 = q.astype(jnp.float32)
    if precision in ("int8", "int4"):
        from knn_tpu.ops.quantize import score_error_bound_device

        q_norm, tol = score_error_bound_device(
            q32 - quant_offset, consts)
    elif precision == "pq":
        from knn_tpu.ops.pq import score_error_bound_pq_device

        q_norm, tol = score_error_bound_pq_device(
            q32, consts, dsub=pq_dsub)
    elif precision in ("bf16x3", "bf16x3f"):
        q_norm = jnp.sum(q32 * q32, axis=-1)
        tol = 2.0 ** -14 * (q_norm + db_norm_max)
    else:
        q_norm = jnp.sum(q32 * q32, axis=-1)
        tol = 32.0 * float(np.finfo(np.float32).eps) * (
            q_norm + db_norm_max)
    d_k = dw[:, k - 1]
    s_k = d_k - q_norm
    bad = s_k + RANK_SLACK * d_k + tol >= lb
    if db_shards > 1:
        # merge-dropped candidates have direct-diff f32 distance
        # >= the (m+1)-th kept; require true-distance clearance
        bad = bad | (d_k + RANK_SLACK * d_k
                     >= d32[:, m] * (1.0 - RANK_SLACK))
    bad = bad | unresolved
    cols = [
        gi[:, :w],
        lax.bitcast_convert_type(_pack_bits_u32(tight_use), jnp.int32),
        bad.astype(jnp.int32)[:, None],
    ]
    if include_distances:
        cols.append(lax.bitcast_convert_type(d32[:, :k], jnp.int32))
    return jnp.concatenate(cols, axis=1)


@functools.lru_cache(maxsize=32)
def _pallas_coarse_program(
    mesh: Mesh, m: int, tile_n: Optional[int], precision: str,
    bin_w: Optional[int] = None, survivors: Optional[int] = None,
    block_q: Optional[int] = None, final_select: str = "exact",
    binning: str = "grouped", grid_order: str = "query_major",
    kernel: str = "tiled", quant_offset: float = 0.0,
):
    """Stage 1 of the two-stage certified pipeline: the db-streaming
    coarse pass alone (ops.pallas_knn.local_coarse_candidates per
    shard), returning the packed per-shard candidate blocks
    ``(cd, ci, bounds)`` concatenated along the db axis — the
    packed-candidate boundary the pipeline overlap splits the certified
    program on.  Takes the SAME operand tail as the one-shot program
    (unused pieces ignored) so callers keep ONE operand home."""
    from knn_tpu.ops.pallas_knn import (
        BIN_W,
        BLOCK_Q,
        TILE_N,
        local_coarse_candidates,
    )

    dbp = db_axes(mesh)

    def spmd(q, t, *tail):
        db_q, db_pq, _, _ = _split_operand_tail(precision, tail)
        return local_coarse_candidates(
            q, t, m, tile_n=tile_n or TILE_N, bin_w=bin_w or BIN_W,
            survivors=survivors, block_q=block_q or BLOCK_Q,
            precision=precision, binning=binning,
            grid_order=grid_order, kernel=kernel,
            db_int8=db_q if precision == "int8" else None,
            db_int4=db_q if precision == "int4" else None,
            db_pq=db_pq,
            offset=quant_offset, final_select=final_select,
        )

    return jax.jit(
        shard_map_compat(
            spmd,
            mesh=mesh,
            in_specs=(P(QUERY_AXIS), P(dbp), *_tail_specs(precision, mesh)),
            out_specs=(P(QUERY_AXIS, dbp), P(QUERY_AXIS, dbp),
                       P(QUERY_AXIS, dbp)),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=32)
def _pallas_tail_program(
    mesh: Mesh, m: int, k: int, merge: str, precision: str,
    n_train: Optional[int] = None, final_select: str = "exact",
    include_distances: bool = True,
    final_recall_target: Optional[float] = None,
    quant_offset: float = 0.0, donate: bool = False,
    dcn_merge: Optional[str] = None,
):
    """Stage 2 of the two-stage certified pipeline: final select +
    rescore gather (ops.pallas_knn.local_select_rescore) + the shared
    certify/pack tail (:func:`_certify_pack_spmd`).  ``donate=True``
    donates the candidate carry buffers (cd/ci/bounds — the largest
    arrays in flight) to the program so each batch's carries recycle
    instead of accumulating across the pipeline window; CPU XLA rejects
    donation, so callers pass False there."""
    from knn_tpu.ops.pallas_knn import local_select_rescore

    hosts, chips = db_topology(mesh)
    dbp = db_axes(mesh)
    w = _analysis_window(k, m)

    def spmd(q, t, cd, ci, bounds, *tail):
        _, db_pq, consts, db_norm_max = _split_operand_tail(
            precision, tail)
        d32, li, lb = local_select_rescore(
            q, t, cd, ci, bounds, m, final_select=final_select,
            final_recall_target=final_recall_target,
        )
        return _certify_pack_spmd(
            q, t, d32, li, lb, consts=consts, db_norm_max=db_norm_max,
            precision=precision, quant_offset=quant_offset, m=m, k=k, w=w,
            merge=merge, n_train=n_train, hosts=hosts, chips=chips,
            dcn_merge=dcn_merge,
            include_distances=include_distances,
            pq_dsub=None if db_pq is None else int(db_pq[1].shape[2]),
        )

    return jax.jit(
        shard_map_compat(
            spmd,
            mesh=mesh,
            in_specs=(P(QUERY_AXIS), P(dbp), P(QUERY_AXIS, dbp),
                      P(QUERY_AXIS, dbp), P(QUERY_AXIS, dbp),
                      *_tail_specs(precision, mesh)),
            out_specs=P(QUERY_AXIS),
            check_vma=False,
        ),
        donate_argnums=(2, 3, 4) if donate else (),
    )


def unpack_certified(
    packed: np.ndarray, k: int, w: int, with_distances: bool
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Host inverse of ``_pallas_certified_program``'s packed output:
    (gi [Q, w] i32, tight [Q, w-1] bool, bad [Q] bool, dk [Q, k] f32 or
    None)."""
    arr = np.ascontiguousarray(np.asarray(packed))
    nw = -(-(w - 1) // 32)
    gi = arr[:, :w]
    tight = unpack_bits_u32(arr[:, w : w + nw].view(np.uint32), w - 1)
    bad = arr[:, w + nw] != 0
    dk = None
    if with_distances:
        dk = np.ascontiguousarray(
            arr[:, w + nw + 1 : w + nw + 1 + k]
        ).view(np.float32)
    return gi, tight, bad, dk


@functools.lru_cache(maxsize=32)
def _count_program(mesh: Mesh, n_train: int, train_tile: Optional[int]):
    """Per-query count of db rows with squared-L2 distance strictly below
    the query's threshold — the distributed certificate pass of
    ops.certified (matmul-bound, no selection).  Counts psum over the db
    axis; output replicated there."""
    from knn_tpu.ops.certified import count_below

    hosts, chips = db_topology(mesh)
    dbp = db_axes(mesh)
    tile = train_tile or 131072

    def spmd(q, t, thr):
        db_idx = _db_shard_index(hosts, chips)
        n_local_valid = jnp.clip(n_train - db_idx * t.shape[0], 0, t.shape[0])
        # count within the local shard, masking padding rows via a
        # +inf-threshold trick: rows >= n_local_valid can't be < thr
        local = count_below.__wrapped__(
            t, q, thr, tile=min(tile, t.shape[0]), n_valid=n_local_valid
        )
        if hosts * chips > 1:
            local = lax.psum(local, dbp if hosts > 1 else DB_AXIS)
        return local

    return jax.jit(
        shard_map_compat(
            spmd,
            mesh=mesh,
            in_specs=(P(QUERY_AXIS), P(dbp), P(QUERY_AXIS)),
            out_specs=P(QUERY_AXIS),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=16)
def _minmax_program(mesh: Mesh, n_arrays: int):
    axes = (QUERY_AXIS, HOST_AXIS, DB_AXIS) if HOST_AXIS in mesh.shape \
        else (QUERY_AXIS, DB_AXIS)

    def spmd(*arrays):
        lo, hi = None, None
        for a in arrays:
            alo, ahi = local_minmax(a)
            lo = alo if lo is None else jnp.minimum(lo, alo)
            hi = ahi if hi is None else jnp.maximum(hi, ahi)
        # The reference's two Allreduces, knn_mpi.cpp:276-277:
        lo = allreduce_min(lo, axes)
        hi = allreduce_max(hi, axes)
        return lo, hi

    return jax.jit(
        shard_map_compat(
            spmd,
            mesh=mesh,
            in_specs=tuple(P(axes) for _ in range(n_arrays)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def sharded_minmax(
    arrays: Sequence[jax.Array], *, mesh: Mesh
) -> Tuple[jax.Array, jax.Array]:
    """Distributed per-dim (min, max) over the union of several [N_i, D]
    arrays — the reference's transductive extrema phase (knn_mpi.cpp:245-277)
    with pmin/pmax standing in for its Allreduce pair.  Row padding uses
    edge replication, which leaves extrema unchanged.  Empty arrays are the
    reduce identity (+inf, -inf), matching ops.normalize.local_minmax."""
    arrays = list(arrays)
    if not arrays:
        raise ValueError("sharded_minmax needs at least one array")
    dim = arrays[0].shape[-1]
    nonempty = [a for a in arrays if a.shape[0] > 0]
    if not nonempty:
        return (
            jnp.full((dim,), jnp.inf, dtype=jnp.float32),
            jnp.full((dim,), -jnp.inf, dtype=jnp.float32),
        )
    n_dev = mesh.size
    padded = []
    for a in nonempty:
        n = a.shape[0]
        target = max(-(-n // n_dev) * n_dev, n_dev)
        if target != n:
            pad_fn = np.pad if isinstance(a, np.ndarray) else jnp.pad
            a = pad_fn(a, ((0, target - n), (0, 0)), mode="edge")
        padded.append(shard(
            a, mesh,
            (QUERY_AXIS, HOST_AXIS, DB_AXIS) if HOST_AXIS in mesh.shape
            else (QUERY_AXIS, DB_AXIS)))
    fn = _minmax_program(mesh, len(padded))
    return fn(*padded)


def sharded_normalize_transductive(
    train: jax.Array,
    test: Optional[jax.Array] = None,
    val: Optional[jax.Array] = None,
    *,
    mesh: Mesh,
):
    """Reference L2 phase (knn_mpi.cpp:229-306) on the mesh: joint extrema
    over train ∪ test ∪ val, then in-place rescale with constant dims passed
    through.  Returns (train', test', val') with None passed through."""
    present = [a for a in (train, test, val) if a is not None]
    lo, hi = sharded_minmax(present, mesh=mesh)
    return tuple(
        None if a is None else _minmax_apply_jit(a, lo, hi) for a in (train, test, val)
    )
