"""The measured ring/allgather crossover — merge-strategy selection as
DATA, not caller folklore.

``SCALING.json`` (scripts/scaling_study.py) measured both db-axis merge
strategies at equal total work across mesh shapes and k.  The verdict
is a crossover, not a winner: allgather's one-collective P·k candidate
volume wins at small shard counts and large ones whose ring would pay
P-1 latency hops, while the ring's constant-memory (P-1)·k pipeline
wins in between and at large k where the gathered volume dominates.
Until this module, that measurement drove nothing — ``merge=`` was a
caller-chosen kwarg defaulting to allgather everywhere.

This is the jax-free home of

- :data:`MEASURED_CROSSOVER` — the argmin-wall strategy per measured
  ``(k, shards)`` point, pinned against ``SCALING.json`` itself by
  tests/test_collectives.py (edit the JSON and the table must follow);
- :func:`choose_merge` / :func:`resolve_merge` — nearest-measured-point
  lookup with the precedence **explicit caller > env switch
  (``KNN_TPU_MERGE`` / ``KNN_TPU_DCN_MERGE``) > measured table**;
- :func:`merge_bytes` — the collective-volume model behind the
  ``merge_bytes_per_sweep`` column (allgather moves ``Q·k·8·P`` bytes,
  ring ``Q·k·8·(P-1)``; 8 = f32 distance + i32 index per candidate),
  reused by the roofline's DCN term;
- :func:`validate_multihost_block` — structural validation of the
  ``multihost`` block bench.py emits and the artifact refresher
  refuses when malformed (the roofline-block discipline).

Everything here is plain arithmetic on plain numbers so the refresher,
the sentinel lint, and the roofline model import it without JAX.
"""

from __future__ import annotations

import math
import os
from typing import Dict, Optional, Tuple

#: the two db-axis merge strategies (mirrors parallel.sharded._MERGES)
STRATEGIES = ("allgather", "ring")

#: where a resolved strategy came from, in precedence order
SOURCES = ("explicit", "env", "measured")

#: env switches overriding the measured default at each merge level
#: (the flat/intra-host ICI level and the cross-host DCN level) —
#: cataloged in knn_tpu.analysis.switches
MERGE_ENV = "KNN_TPU_MERGE"
DCN_MERGE_ENV = "KNN_TPU_DCN_MERGE"

#: bytes one (distance f32, index i32) candidate pair moves
CANDIDATE_BYTES = 8

#: ``(k, shards) -> strategy``: the argmin-wall_s strategy at every
#: measured SCALING.json point (mesh column "QxP" contributes P).
#: tests/test_collectives.py re-derives this from the JSON — the table
#: can never silently drift from the measurement it claims to persist.
MEASURED_CROSSOVER: Dict[Tuple[int, int], str] = {
    (10, 2): "allgather",
    (10, 4): "ring",
    (10, 8): "allgather",
    (100, 2): "ring",
    (100, 4): "ring",
    (100, 8): "allgather",
}


def _nearest(value: int, measured) -> int:
    """The measured grid point nearest ``value`` in log space (both
    axes are geometric: k 10/100, shards 2/4/8); ties take the smaller
    point — the conservative, lower-volume regime."""
    v = math.log(max(1, int(value)))
    return min(sorted(set(measured)), key=lambda m: (abs(math.log(m) - v), m))


def choose_merge(k: int, shards: int) -> str:
    """The measured-table strategy for a ``(k, shards)`` merge — the
    nearest measured point's argmin.  ``shards <= 1`` needs no merge;
    allgather (a no-op there) is returned for uniformity."""
    if shards <= 1:
        return "allgather"
    ks = {mk for mk, _ in MEASURED_CROSSOVER}
    ps = {mp for _, mp in MEASURED_CROSSOVER}
    return MEASURED_CROSSOVER[(_nearest(k, ks), _nearest(shards, ps))]


def resolve_merge(
    explicit: Optional[str], k: int, shards: int, *,
    env_name: str = MERGE_ENV,
) -> Tuple[str, str]:
    """``(strategy, source)`` under the precedence explicit > env >
    measured table.  A malformed env value raises rather than silently
    steering a merge (the admission-control strict-env discipline)."""
    if explicit is not None:
        if explicit not in STRATEGIES:
            raise ValueError(
                f"unknown merge {explicit!r}; expected one of {STRATEGIES}")
        return explicit, "explicit"
    env = os.environ.get(env_name, "").strip().lower()
    if env:
        if env not in STRATEGIES:
            raise ValueError(
                f"{env_name}={env!r} is not one of {STRATEGIES}")
        return env, "env"
    return choose_merge(k, shards), "measured"


def merge_bytes(n_queries: int, k: int, shards: int, strategy: str) -> int:
    """Total candidate bytes one merge moves across the axis for a
    ``[n_queries, k]`` result: allgather ships every shard's list to
    every shard (``Q·k·8·P``), the ring passes a constant buffer P-1
    hops (``Q·k·8·(P-1)``).  Reproduces SCALING.json's
    ``merge_bytes_per_sweep`` column exactly (pinned in tests)."""
    if strategy not in STRATEGIES:
        raise ValueError(
            f"unknown merge {strategy!r}; expected one of {STRATEGIES}")
    if shards <= 1:
        return 0
    hops = shards if strategy == "allgather" else shards - 1
    return int(n_queries) * int(k) * CANDIDATE_BYTES * hops


def validate_multihost_block(block) -> list:
    """Structural validation of a ``multihost`` bench block.  Returns a
    list of error strings, empty when well-formed — the artifact
    refresher REFUSES malformed blocks (the roofline/knee discipline:
    a corrupt block would poison curated baselines silently).  A shim
    over the artifact-schema catalog (:mod:`knn_tpu.analysis.
    artifacts`, the ``multihost`` entry) with the legacy error strings
    byte-identical."""
    from knn_tpu.analysis.artifacts import validate

    return validate("multihost", block, style="legacy")


__all__ = [
    "STRATEGIES",
    "SOURCES",
    "MERGE_ENV",
    "DCN_MERGE_ENV",
    "MEASURED_CROSSOVER",
    "choose_merge",
    "resolve_merge",
    "merge_bytes",
    "validate_multihost_block",
]
