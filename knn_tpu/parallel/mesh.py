"""Device-mesh construction and shape utilities.

The reference's process grid is a flat `MPI_COMM_WORLD` of N ranks
(knn_mpi.cpp:123-125) used for exactly one thing: sharding queries.  The TPU
mesh is 2-D from the start, because the framework shards **two** axes the
reference never could:

  - ``query`` axis: data parallelism over query rows — the direct analogue
    of the reference's `MPI_Scatter` of test/val shards (knn_mpi.cpp:226-227).
  - ``db`` axis: sharding of the train/database rows — the axis the
    reference *replicates* via `MPI_Bcast` (knn_mpi.cpp:224-225); sharding it
    is the KNN analogue of ring-attention/sequence parallelism (SURVEY.md §5)
    and is what lets a 1M+-row database scale past one device's HBM.

The reference aborts when sizes don't divide the rank count
(knn_mpi.cpp:127-129); here :func:`pad_to_multiple` pads instead and callers
mask/slice the padding away.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

QUERY_AXIS = "query"
DB_AXIS = "db"
#: the cross-host axis of a hierarchical mesh (make_host_mesh): db rows
#: shard over (HOST_AXIS, DB_AXIS) — host-major, so each host's
#: contiguous row block subdivides across its own chips.  Merges then
#: go per-chip -> per-host over ICI (DB_AXIS) and per-host -> global
#: over DCN (HOST_AXIS); see parallel.sharded.
HOST_AXIS = "host"


def make_mesh(
    query_shards: Optional[int] = None,
    db_shards: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 2-D ``Mesh`` with axes ``(QUERY_AXIS, DB_AXIS)``.

    ``query_shards=None`` takes every remaining device after ``db_shards``.
    A single-device mesh (1, 1) is valid and runs the same SPMD program the
    pod runs — there is no separate single-device code path.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if query_shards is None:
        if n % db_shards:
            raise ValueError(f"{n} devices not divisible by db_shards={db_shards}")
        query_shards = n // db_shards
    need = query_shards * db_shards
    if need > n:
        raise ValueError(f"mesh {query_shards}x{db_shards} needs {need} devices, have {n}")
    grid = np.asarray(devices[:need]).reshape(query_shards, db_shards)
    return Mesh(grid, (QUERY_AXIS, DB_AXIS))


def make_host_mesh(
    query_shards: Optional[int] = None,
    db_hosts: int = 1,
    db_shards: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 3-D hierarchical ``Mesh`` with axes ``(QUERY_AXIS, HOST_AXIS,
    DB_AXIS)``: database rows shard over hosts (DCN boundary, major)
    then over each host's chips (ICI, minor).  On real pods pass
    ``devices=jax.devices()`` (the global, process-spanning list) with
    ``db_hosts = jax.process_count()``; single-process, the host axis
    is a logical fold of the local devices — same SPMD program, same
    merge tree, pinned bitwise-identical to the flat mesh in
    tests/test_multihost.py."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if db_hosts < 1 or db_shards < 1:
        raise ValueError(
            f"db_hosts={db_hosts} and db_shards={db_shards} must be >= 1")
    per_q = db_hosts * db_shards
    if query_shards is None:
        if n % per_q:
            raise ValueError(
                f"{n} devices not divisible by db_hosts*db_shards={per_q}")
        query_shards = n // per_q
    need = query_shards * per_q
    if need > n:
        raise ValueError(
            f"mesh {query_shards}x{db_hosts}x{db_shards} needs {need} "
            f"devices, have {n}")
    grid = np.asarray(devices[:need]).reshape(
        query_shards, db_hosts, db_shards)
    return Mesh(grid, (QUERY_AXIS, HOST_AXIS, DB_AXIS))


def is_hier(mesh: Mesh) -> bool:
    """Whether ``mesh`` carries the cross-host axis (make_host_mesh)."""
    return HOST_AXIS in mesh.shape


def db_axes(mesh: Mesh):
    """The db-sharding axis spec entry: the flat ``DB_AXIS`` or the
    host-major ``(HOST_AXIS, DB_AXIS)`` pair on hierarchical meshes —
    what every ``P(...)`` db spec and multi-axis collective uses."""
    return (HOST_AXIS, DB_AXIS) if is_hier(mesh) else DB_AXIS


def db_topology(mesh: Mesh) -> Tuple[int, int]:
    """``(hosts, chips_per_host)`` of the db sharding; hosts == 1 on a
    flat mesh.  Total db shards = hosts * chips."""
    return mesh.shape.get(HOST_AXIS, 1), mesh.shape[DB_AXIS]


def default_mesh(db_shards: int = 1) -> Mesh:
    """Mesh over all local devices; queries get every device not used by db."""
    return make_mesh(None, db_shards)


def pad_to_multiple(
    x, multiple: int, axis: int = 0, *, fill: float = 0.0
) -> Tuple[jax.Array, int]:
    """Pad ``x`` along ``axis`` up to the next multiple with ``fill``.

    Returns (padded, original_size).  Replaces the reference's divisibility
    `MPI_Abort` (knn_mpi.cpp:127-129): any size works on any mesh.

    Every selection path masks pad rows by index, so ``fill`` never affects
    results — but the Pallas kernel's exclusion-bound certificate
    (ops.pallas_knn) is *faster* when pad rows score far away, so database
    padding passes a huge fill (see ``ShardedKNN``).

    NumPy inputs are padded **on host** so a later sharded ``device_put``
    streams each shard straight to its device — the full array never
    materializes on one device (the HBM-scaling contract of the db axis).
    """
    n = x.shape[axis]
    padded = -(-n // multiple) * multiple
    if padded == n:
        return x, n
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, padded - n)
    if isinstance(x, np.ndarray):
        return np.pad(x, widths, constant_values=fill), n
    import jax.numpy as jnp

    return jnp.pad(x, widths, constant_values=fill), n
