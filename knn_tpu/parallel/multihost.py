"""Multi-host execution — the DCN half of the communication backend.

The reference scales with ``mpiexec -n N`` across nodes: every rank is an
OS process and MPI wires them together (knn_mpi.cpp:123-125; report PDF
p.5-7 §2.2).  The TPU-native equivalent is one JAX process per host joined
through :func:`jax.distributed.initialize`; after that, ``jax.devices()``
is the *global* device list, the 2-D mesh (parallel.mesh) spans every
host, and the SAME SPMD programs (parallel.sharded) run unchanged — XLA
routes collectives over ICI within a slice and DCN across slices.  There
is no second code path: multi-host is a bigger mesh.

What this module adds is the data-movement story MPI gets from its
collectives: each host holds only its own slice of the database/queries
(the reference instead makes rank 0 read everything and Bcast it —
knn_mpi.cpp:154-175,224), and :func:`shard_across_hosts` assembles those
host-local rows into one globally-sharded ``jax.Array`` without any host
ever materializing the full matrix.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from knn_tpu.parallel.mesh import DB_AXIS, QUERY_AXIS, make_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join this process to the multi-host runtime (the reference's
    ``MPI_Init``, knn_mpi.cpp:123).  No-op when single-process or already
    initialized, so driver code can call it unconditionally."""
    if num_processes is None or num_processes <= 1:
        return
    # already-joined guard WITHOUT jax.process_count(): that call would
    # initialize the local backend first, after which distributed init
    # can no longer succeed
    try:
        from jax._src import distributed as _distributed

        if getattr(_distributed.global_state, "client", None) is not None:
            return
    except ImportError:  # internal layout moved; fall through to init
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(
    query_shards: Optional[int] = None, db_shards: int = 1
) -> Mesh:
    """The (query, db) mesh over every device of every host — the
    reference's ``MPI_COMM_WORLD`` (knn_mpi.cpp:124-125)."""
    return make_mesh(query_shards, db_shards, devices=jax.devices())


def shard_across_hosts(
    local_rows: np.ndarray,
    mesh: Mesh,
    axis_name: str = DB_AXIS,
) -> jax.Array:
    """Assemble per-host row blocks into one global ``jax.Array`` sharded
    along ``axis_name`` — the multi-host ``MPI_Scatter`` (knn_mpi.cpp:
    226-227) with no root: every host contributes the rows it already has,
    concatenated in process order.  Row counts must be equal across hosts
    (pad with :func:`knn_tpu.parallel.mesh.pad_to_multiple` first — prefer
    ``fill=ops.pallas_knn.PAD_VAL`` so the pallas certificate's exclusion
    bound stays sharp; zero fill is correct but costs fallbacks — and pass
    the true pre-pad row count to ``ShardedKNN(..., n_train=...)`` so pad
    rows stay masked); the global row count is
    ``local_rows.shape[0] * process_count``.

    Single-process, this is exactly a sharded ``device_put``.
    """
    local_rows = np.asarray(local_rows)
    pc = jax.process_count()
    axis_size = int(np.prod([mesh.shape[a] for a in (
        (axis_name,) if isinstance(axis_name, str) else axis_name
    )]))
    if axis_size % pc:
        raise ValueError(
            f"mesh axis {axis_name!r} (size {axis_size}) must be a multiple "
            f"of process_count={pc} to scatter rows across hosts; with fewer "
            "shards than processes the array would be replicated and every "
            "host would need the full matrix"
        )
    spec = [None] * local_rows.ndim
    spec[0] = axis_name
    sharding = NamedSharding(mesh, P(*spec))
    global_shape = (
        local_rows.shape[0] * pc,
        *local_rows.shape[1:],
    )
    return jax.make_array_from_process_local_data(
        sharding, local_rows, global_shape
    )


def process_row_slice(n_global_rows: int) -> slice:
    """Which contiguous rows of a [N, D] global matrix this process should
    load from disk — the per-rank read assignment the reference hard-codes
    by rank id (knn_mpi.cpp:154-222).  Rows must already be padded to a
    multiple of process_count."""
    pc = jax.process_count()
    if n_global_rows % pc:
        raise ValueError(
            f"{n_global_rows} rows not divisible by {pc} processes; pad first"
        )
    per = n_global_rows // pc
    pid = jax.process_index()
    return slice(pid * per, (pid + 1) * per)


__all__ = [
    "initialize",
    "global_mesh",
    "shard_across_hosts",
    "process_row_slice",
]
