"""Multi-host execution — the DCN half of the communication backend.

The reference scales with ``mpiexec -n N`` across nodes: every rank is an
OS process and MPI wires them together (knn_mpi.cpp:123-125; report PDF
p.5-7 §2.2).  The TPU-native equivalent is one JAX process per host joined
through :func:`jax.distributed.initialize`; after that, ``jax.devices()``
is the *global* device list, the 2-D mesh (parallel.mesh) spans every
host, and the SAME SPMD programs (parallel.sharded) run unchanged — XLA
routes collectives over ICI within a slice and DCN across slices.  There
is no second code path: multi-host is a bigger mesh.

What this module adds is the data-movement story MPI gets from its
collectives: each host holds only its own slice of the database/queries
(the reference instead makes rank 0 read everything and Bcast it —
knn_mpi.cpp:154-175,224), and :func:`shard_across_hosts` assembles those
host-local rows into one globally-sharded ``jax.Array`` without any host
ever materializing the full matrix.

Two DCN transports for the hierarchical merge's global level:

- **in-mesh** — a process-spanning ``make_host_mesh`` placement; XLA
  runs the host-axis collectives over DCN (parallel.sharded's merge
  tree).  Needs a backend that can execute cross-process computations.
- **host-mediated** — :class:`MultiHostKNN`: per-host candidates
  computed on each process's own devices, exchanged through the
  ``jax.distributed`` coordinator's key-value store
  (:func:`dcn_allgather_arrays`) and merged on host
  (:func:`merge_topk_host`, the same lexicographic order).  Works on
  every supported jaxlib — it is the 2-process CPU CI lane — and is
  bitwise-identical to the single-host reference.
"""

from __future__ import annotations

import base64
import io
import itertools
import threading
import time
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from knn_tpu import obs
from knn_tpu.obs import ident as _ident
from knn_tpu.obs import names as _mn
from knn_tpu.parallel import crossover
from knn_tpu.parallel.mesh import DB_AXIS, QUERY_AXIS, make_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join this process to the multi-host runtime (the reference's
    ``MPI_Init``, knn_mpi.cpp:123).  No-op when single-process or already
    initialized, so driver code can call it unconditionally."""
    if num_processes is None or num_processes <= 1:
        return
    # already-joined guard WITHOUT jax.process_count(): that call would
    # initialize the local backend first, after which distributed init
    # can no longer succeed
    try:
        from jax._src import distributed as _distributed

        if getattr(_distributed.global_state, "client", None) is not None:
            return
    except ImportError:  # internal layout moved; fall through to init
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    # stamp the process identity every snapshot / JSONL line carries
    # (knn_tpu.obs.ident) — the fleet aggregator attributes members by
    # it.  Only the init args: touching jax.process_index() here could
    # initialize the local backend earlier than callers expect.
    stamp = {"process_count": int(num_processes),
             "coordinator_address": coordinator_address}
    if process_id is not None:
        stamp["process_index"] = int(process_id)
    _ident.set_identity(**stamp)


def global_mesh(
    query_shards: Optional[int] = None, db_shards: int = 1
) -> Mesh:
    """The (query, db) mesh over every device of every host — the
    reference's ``MPI_COMM_WORLD`` (knn_mpi.cpp:124-125)."""
    return make_mesh(query_shards, db_shards, devices=jax.devices())


def shard_across_hosts(
    local_rows: np.ndarray,
    mesh: Mesh,
    axis_name: str = DB_AXIS,
) -> jax.Array:
    """Assemble per-host row blocks into one global ``jax.Array`` sharded
    along ``axis_name`` — the multi-host ``MPI_Scatter`` (knn_mpi.cpp:
    226-227) with no root: every host contributes the rows it already has,
    concatenated in process order.  Row counts must be equal across hosts
    (pad with :func:`knn_tpu.parallel.mesh.pad_to_multiple` first — prefer
    ``fill=ops.pallas_knn.PAD_VAL`` so the pallas certificate's exclusion
    bound stays sharp; zero fill is correct but costs fallbacks — and pass
    the true pre-pad row count to ``ShardedKNN(..., n_train=...)`` so pad
    rows stay masked); the global row count is
    ``local_rows.shape[0] * process_count``.

    Single-process, this is exactly a sharded ``device_put``.
    """
    local_rows = np.asarray(local_rows)
    pc = jax.process_count()
    axis_size = int(np.prod([mesh.shape[a] for a in (
        (axis_name,) if isinstance(axis_name, str) else axis_name
    )]))
    if axis_size % pc:
        raise ValueError(
            f"mesh axis {axis_name!r} (size {axis_size}) must be a multiple "
            f"of process_count={pc} to scatter rows across hosts; with fewer "
            "shards than processes the array would be replicated and every "
            "host would need the full matrix"
        )
    spec = [None] * local_rows.ndim
    spec[0] = axis_name
    sharding = NamedSharding(mesh, P(*spec))
    global_shape = (
        local_rows.shape[0] * pc,
        *local_rows.shape[1:],
    )
    return jax.make_array_from_process_local_data(
        sharding, local_rows, global_shape
    )


def process_row_slice(n_global_rows: int) -> slice:
    """Which contiguous rows of a [N, D] global matrix this process should
    load from disk — the per-rank read assignment the reference hard-codes
    by rank id (knn_mpi.cpp:154-222).  Rows must already be padded to a
    multiple of process_count."""
    pc = jax.process_count()
    if n_global_rows % pc:
        raise ValueError(
            f"{n_global_rows} rows not divisible by {pc} processes; pad first"
        )
    per = n_global_rows // pc
    pid = jax.process_index()
    return slice(pid * per, (pid + 1) * per)


# --- host-mediated DCN merge (the transport that works on ANY jaxlib) --

#: bounded last-merge report for /statusz + doctor (obs.health reads it)
_REPORT_LOCK = threading.Lock()
_LAST_REPORT: dict = {}

#: per-process replica counter: KV keys embed the replica's construction
#: ordinal, so two replicas (or two searches of one replica) can never
#: collide on a coordinator key — construction and call order must match
#: across processes anyway (the SPMD collective discipline)
_INSTANCE_SEQ = itertools.count()


def last_report() -> Optional[dict]:
    """The last cross-host merge's observability snapshot (hosts,
    strategy, straggler gap, merge bytes) — the /statusz "multihost"
    section; None until a merge ran in this process."""
    with _REPORT_LOCK:
        return dict(_LAST_REPORT) if _LAST_REPORT else None


def _update_report(**kw) -> None:
    with _REPORT_LOCK:
        _LAST_REPORT.clear()
        _LAST_REPORT.update(kw)


def _kv_client():
    """The jax.distributed coordinator's key-value client — the DCN
    side channel every jaxlib build carries once ``initialize`` ran,
    even the ones whose CPU backend cannot EXECUTE cross-process
    computations ("Multiprocess computations aren't implemented": the
    collective would run inside XLA; this store runs beside it)."""
    from jax._src import distributed

    client = getattr(distributed.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "jax.distributed is not initialized; call "
            "multihost.initialize(...) first")
    return client


def _encode_arrays(*arrays) -> str:
    buf = io.BytesIO()
    np.savez(buf, *[np.ascontiguousarray(np.asarray(a)) for a in arrays])
    return base64.b64encode(buf.getvalue()).decode("ascii")


def _decode_arrays(raw: str, n: int) -> List[np.ndarray]:
    with np.load(io.BytesIO(base64.b64decode(raw))) as z:
        return [z[f"arr_{i}"] for i in range(n)]


def dcn_allgather_arrays(arrays: Sequence[np.ndarray], *, tag: str,
                         timeout_s: float = 180.0) -> List[List[np.ndarray]]:
    """Allgather a tuple of host arrays across every jax.distributed
    process through the coordinator KV store — the host-mediated DCN
    collective.  Returns one array list per process, in process order.
    ``tag`` must be unique per logical call and identical across
    processes (every process must make the same sequence of calls —
    the usual collective discipline, enforced here by the blocking
    get's timeout rather than a hang)."""
    pc = jax.process_count()
    if pc == 1:
        return [[np.asarray(a) for a in arrays]]
    client = _kv_client()
    n = len(arrays)
    own_key = f"knn_tpu/dcn/{tag}/{jax.process_index()}"
    client.key_value_set(own_key, _encode_arrays(*arrays))
    out: List[List[np.ndarray]] = []
    for p in range(pc):
        if p == jax.process_index():
            out.append([np.asarray(a) for a in arrays])
            continue
        raw = client.blocking_key_value_get(
            f"knn_tpu/dcn/{tag}/{p}", int(timeout_s * 1000))
        out.append(_decode_arrays(raw, n))
    # reclaim coordinator memory: once EVERY process has read every
    # list (the barrier), each deletes its own key — without this a
    # long-lived replica grows the coordinator by one payload per
    # search forever.  Older jaxlibs without barrier/delete degrade to
    # leaving the keys (bounded only by process lifetime — documented).
    try:
        client.wait_at_barrier(f"knn_tpu/dcn/{tag}/read",
                               int(timeout_s * 1000))
        client.key_value_delete(own_key)
    except AttributeError:
        pass
    return out


def merge_topk_host(d_lists: Sequence[np.ndarray],
                    i_lists: Sequence[np.ndarray],
                    k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side lexicographic (distance, index) top-k merge of
    per-host candidate lists — the same associative merge order
    ops.topk runs on device, so the merged result is bitwise-identical
    to a single placement ranking all rows (pinned in
    tests/test_multihost.py)."""
    cd = np.concatenate(list(d_lists), axis=1)
    ci = np.concatenate(list(i_lists), axis=1)
    order = np.lexsort((ci, cd), axis=-1)[:, :k]
    return (np.take_along_axis(cd, order, axis=-1),
            np.take_along_axis(ci, order, axis=-1))


class MultiHostKNN:
    """One logical serving replica spanning ``jax.distributed``
    processes, each holding ONLY its own contiguous row block — the
    reference's ``mpiexec -n N`` scale-out (knn_mpi.cpp:123-175) without
    its replicate-everything memory wall.

    The merge tree is hierarchical: per-chip candidate lists reduce
    per-host inside the local :class:`~knn_tpu.parallel.sharded.
    ShardedKNN` program (ICI — the local mesh's db axis, ring/allgather
    by the measured crossover), then the per-host [Q, k] lists merge
    globally over DCN.  The DCN transport here is HOST-MEDIATED: lists
    travel through the coordinator KV store and merge on host
    (:func:`merge_topk_host`) — ~Q·k·8 bytes per host per query batch,
    the volume :func:`knn_tpu.parallel.crossover.merge_bytes` prices —
    which works on every jaxlib build, including the ones whose CPU
    backend cannot execute cross-process XLA computations (the 2-process
    CI lane).  On pods whose backend CAN span processes, the in-mesh
    alternative is a hierarchical ``make_host_mesh`` placement over
    ``jax.devices()`` — same tree, collectives instead of the KV hop.

    Every process must hold the SAME row count (pad the tail host) and
    call each search method in the same order with the same queries —
    the usual SPMD collective discipline.  Results are bitwise-identical
    to a single-host ShardedKNN over the concatenated rows: per-pair
    distances are placement-invariant and both merge levels are the
    associative lexicographic order.
    """

    def __init__(
        self,
        local_rows,
        *,
        k: int,
        metric: str = "l2",
        merge: Optional[str] = None,
        dcn_merge: Optional[str] = None,
        db_shards: int = 1,
        train_tile: Optional[int] = None,
        compute_dtype=None,
        n_local: Optional[int] = None,
        mesh: Optional[Mesh] = None,
    ):
        from knn_tpu.parallel.sharded import ShardedKNN

        local_rows = np.asarray(local_rows)
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        if mesh is None:
            mesh = make_mesh(None, db_shards, devices=jax.local_devices())
        self._local = ShardedKNN(
            local_rows, mesh=mesh, k=k, metric=metric, merge=merge,
            train_tile=train_tile, compute_dtype=compute_dtype,
        )
        if n_local is not None and n_local != local_rows.shape[0]:
            raise ValueError(
                f"n_local={n_local} != local rows {local_rows.shape[0]}; "
                f"pad every host to the same row count first")
        self.n_local = local_rows.shape[0]
        self.row_offset = self.process_index * self.n_local
        self.n_global = self.n_local * self.process_count
        self.k = k
        self.metric = self._local.metric
        if self.process_count > 1:
            # the KV transport IS an allgather (every host's list ships
            # to every host); advertising the crossover table's pick
            # here would claim an override that changes nothing.  The
            # ring/allgather choice belongs to the in-mesh path
            # (make_host_mesh + ShardedKNN.dcn_merge); an explicit
            # non-allgather request is refused rather than ignored.
            if dcn_merge is not None and dcn_merge != "allgather":
                raise ValueError(
                    f"MultiHostKNN's host-mediated DCN transport is "
                    f"inherently an allgather; dcn_merge={dcn_merge!r} "
                    f"cannot take effect — use the in-mesh "
                    f"make_host_mesh path for ring merges")
            self.dcn_merge, self.dcn_merge_source = "allgather", "transport"
            obs.counter(_mn.MERGE_SELECTED, level="dcn",
                        strategy=self.dcn_merge,
                        source=self.dcn_merge_source).inc()
        else:
            self.dcn_merge, self.dcn_merge_source = None, None
        self._instance = next(_INSTANCE_SEQ)
        self._seq = itertools.count()
        try:
            kind = jax.local_devices()[0].device_kind
        except Exception:  # backendless builds: identity stays honest
            kind = None
        _ident.set_identity(process_index=self.process_index,
                            process_count=self.process_count,
                            device_kind=kind)

    def _local_report(self, wall: float) -> None:
        """Single-process degenerate: no DCN level, but /statusz still
        gets a fresh snapshot (both search paths call this)."""
        _update_report(hosts=1, process_index=0, transport="local",
                       dcn_merge=None, dcn_merge_bytes=0,
                       straggler_gap_s=0.0, straggler_host=0,
                       host_walls_s=[round(wall, 6)])

    def _dcn_merge(self, d: np.ndarray, gi: np.ndarray, k: int,
                   local_wall_s: float, tag: str, extra=(),
                   trace_id: Optional[str] = None,
                   t_start: Optional[float] = None):
        """Exchange this host's globalized candidate list (+ optional
        per-host ``extra`` payload arrays), its local wall time, and
        its trace id, merge, record the straggler gap (max-min
        per-host wall — what /statusz attributes, with the argmax host
        named) and the DCN volume.  Returns ``(merged_d, merged_gi,
        info)`` where ``info`` carries the per-process walls, gap,
        straggler host, canonical trace id, bytes, and each process's
        extra arrays — ONE exchange/metrics/report home for both
        search paths.

        Trace stitching: each process's trace id rides the same
        coordinator-KV exchange as the candidate lists, the FIRST
        non-empty id in process order becomes the request's canonical
        cross-host id, and every process emits one ``multihost.merge``
        span under it carrying all per-host walls — so one host's
        event stream (or N merged streams) reconstructs the cross-host
        waterfall (knn_tpu.obs.waterfall.stitch_multihost) with the
        straggler gap as explicit per-host wait segments."""
        tid_arr = np.frombuffer((trace_id or "").encode("ascii"),
                                dtype=np.uint8)
        lists = dcn_allgather_arrays(
            (d, gi, *extra, tid_arr, np.float64(local_wall_s)), tag=tag)
        walls = [float(rec[-1]) for rec in lists]
        gap = max(walls) - min(walls)
        straggler = int(np.argmax(walls))
        ctid = next(
            (t for t in (bytes(rec[-2].tobytes()).decode("ascii")
                         for rec in lists) if t), None)
        md, mi = merge_topk_host([r[0] for r in lists],
                                 [r[1] for r in lists], k)
        bytes_moved = crossover.merge_bytes(
            d.shape[0], k, self.process_count, "allgather")
        obs.gauge(_mn.MERGE_STRAGGLER_GAP).set(gap)
        obs.counter(_mn.MERGE_BYTES, level="dcn",
                    strategy="allgather").inc(bytes_moved)
        _update_report(
            hosts=self.process_count,
            process_index=self.process_index,
            transport="kv",
            dcn_merge=self.dcn_merge,
            dcn_merge_source=self.dcn_merge_source,
            dcn_merge_bytes=bytes_moved,
            straggler_gap_s=round(gap, 6),
            straggler_host=straggler,
            host_walls_s=[round(w, 6) for w in walls],
        )
        if t_start is not None:
            obs.record_span(
                "multihost.merge", ctid,
                time.perf_counter() - t_start,
                host=self.process_index,
                hosts=self.process_count,
                local_wall_s=round(local_wall_s, 6),
                walls_s=[round(w, 6) for w in walls],
                straggler_host=straggler,
                straggler_gap_s=round(gap, 6),
                tag=tag,
            )
        info = {
            "walls_s": walls,
            "straggler_gap_s": gap,
            "straggler_host": straggler,
            "trace_id": ctid,
            "bytes": bytes_moved,
            "extra": [rec[2:-2] for rec in lists],
        }
        return md, mi, info

    def search(self, queries, *, k: Optional[int] = None,
               return_sqrt: bool = False,
               trace_id: Optional[str] = None):
        """Global (distances, indices) [Q, k] over every host's rows —
        bitwise-identical to a single-host ``ShardedKNN.search`` of the
        concatenated database.  ``trace_id`` (minted here when absent
        and telemetry is on) is propagated through the DCN exchange so
        the cross-host waterfall stitches under one id."""
        k = self.k if k is None else k
        if trace_id is None:
            trace_id = obs.new_trace_id()
        t0 = time.perf_counter()
        d, i = self._local.search(queries, k=k)
        d = np.asarray(d)
        gi = np.asarray(i).astype(np.int64) + self.row_offset
        wall = time.perf_counter() - t0
        if self.process_count > 1:
            d, gi, _ = self._dcn_merge(
                d, gi, k, wall,
                f"r{self._instance}/search/{next(self._seq)}",
                trace_id=trace_id, t_start=t0)
        else:
            self._local_report(wall)
        if return_sqrt:
            from knn_tpu.ops.distance import metric_values

            d = np.asarray(metric_values(d, self.metric))
        return d, gi

    def search_certified(self, queries, trace_id: Optional[str] = None,
                         **kwargs):
        """Certified-exact global top-k: each host certifies the exact
        top-k of ITS row block (the full search_certified machinery —
        selector/precision/kernel knobs pass through), then the exact
        per-host lists merge over DCN.  The merge of exact disjoint-
        block top-k lists IS the exact global top-k, so the
        certification guarantee survives the tree; ``stats`` sums the
        per-host certification counters and carries the straggler
        gap (with the argmax host named)."""
        k = self.k
        if trace_id is None:
            trace_id = obs.new_trace_id()
        t0 = time.perf_counter()
        d, i, stats = self._local.search_certified(queries, **kwargs)
        wall = time.perf_counter() - t0
        gi = np.asarray(i).astype(np.int64) + self.row_offset
        if kwargs.get("return_distances") is False:
            raise ValueError(
                "MultiHostKNN.search_certified merges on distances; "
                "return_distances=False is not supported")
        d = np.asarray(d)
        if self.process_count > 1:
            # per-host certification counters ride the same exchange as
            # the candidate lists
            counts = np.asarray(
                [stats.get("fallback_queries", 0),
                 stats.get("certified", 0)], np.int64)
            d, gi, info = self._dcn_merge(
                d, gi, k, wall,
                f"r{self._instance}/certified/{next(self._seq)}",
                extra=(counts,), trace_id=trace_id, t_start=t0)
            stats = dict(stats)
            stats["per_host"] = {
                "fallback_queries": [int(e[0][0]) for e in info["extra"]],
                "certified": [int(e[0][1]) for e in info["extra"]],
                "walls_s": [round(w, 6) for w in info["walls_s"]],
            }
            stats["straggler_gap_s"] = round(info["straggler_gap_s"], 6)
            stats["straggler_host"] = info["straggler_host"]
        else:
            self._local_report(wall)
        return d, gi, stats

    # -- mutation refusals (knn_tpu.index, docs/INDEX.md) ---------------
    def _refuse_mutation(self, what: str):
        from knn_tpu.index.artifact import MutationUnsupportedError

        raise MutationUnsupportedError(
            f"{what}: MultiHostKNN spans {self.process_count} "
            f"process(es) with no write replication protocol — a "
            f"single-host write would silently serve stale results "
            f"from the other hosts; rebuild the replica from the "
            f"updated corpus, or serve a mutable corpus from a "
            f"single-host MutableIndex (docs/INDEX.md)")

    def insert(self, vectors=None, ids=None):
        """LOUD refusal — see :mod:`knn_tpu.index` for the single-host
        mutable path."""
        self._refuse_mutation("insert")

    def delete(self, ids=None):
        """LOUD refusal — see :mod:`knn_tpu.index` for the single-host
        mutable path."""
        self._refuse_mutation("delete")


__all__ = [
    "initialize",
    "global_mesh",
    "shard_across_hosts",
    "process_row_slice",
    "MultiHostKNN",
    "dcn_allgather_arrays",
    "merge_topk_host",
    "last_report",
]
