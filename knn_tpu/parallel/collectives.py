"""The reference's MPI collective surface (SURVEY.md §2.8, 11 entry points)
as XLA-native primitives.

Two levels:

- **Placement collectives** (`replicate`, `shard`): the Bcast/Scatter of
  knn_mpi.cpp:224-227 are not runtime calls on TPU — they are *shardings*.
  `device_put` with a `NamedSharding` moves the data once; every subsequent
  jitted program reads it in place.  XLA inserts the actual ICI transfers.

- **Compute collectives** (`allreduce_min/max`, inside-shard_map helpers):
  the Allreduce MAX/MIN of knn_mpi.cpp:276-277 become `lax.pmin`/`lax.pmax`
  over mesh axis names; Gather (knn_mpi.cpp:340,383) becomes
  `lax.all_gather` or simply an unsharded output spec.

`barrier` reproduces the Barrier+Wtime timing fence (knn_mpi.cpp:133-134,
395-396): JAX dispatch is async, so wall-clock timing without
`block_until_ready` measures dispatch, not compute.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The measured ring/allgather crossover (SCALING.json) lives jax-free in
# parallel.crossover so the artifact refresher and the roofline model
# can read it without a backend; re-exported here because strategy
# choice is a property of this collective surface.
from knn_tpu.parallel.crossover import (  # noqa: F401  (re-export)
    MEASURED_CROSSOVER,
    choose_merge,
    merge_bytes,
    resolve_merge,
)


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across the API move: top-level ``jax.shard_map``
    (new JAX, ``check_vma`` kwarg) when present, else
    ``jax.experimental.shard_map.shard_map`` (``check_rep`` kwarg — the
    same switch under its pre-rename name).  Every SPMD program in
    parallel.sharded routes through this one shim."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def replicate(x, mesh: Mesh) -> jax.Array:
    """MPI_Bcast (knn_mpi.cpp:224-225): one copy of ``x`` on every device."""
    return jax.device_put(x, NamedSharding(mesh, P()))


def shard(x, mesh: Mesh, axis_name: str, axis: int = 0) -> jax.Array:
    """MPI_Scatter (knn_mpi.cpp:226-227): split ``x`` along ``axis`` across
    the mesh axis ``axis_name``.  Size must divide the axis; callers pad
    first via mesh.pad_to_multiple."""
    spec = [None] * x.ndim
    spec[axis] = axis_name
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def gather(
    x: jax.Array,
    axis_name: Union[str, Sequence[str]],
    *,
    axis: int = 0,
    tiled: bool = True,
) -> jax.Array:
    """MPI_Gather (knn_mpi.cpp:340,383): assemble the per-device shards along
    ``axis``.  Every device receives the full array (i.e. MPI_Allgather —
    a root-only gather has no cheaper TPU analogue; the reference's root
    rank is just "whoever writes the file").  ``tiled=True`` concatenates
    shards; ``tiled=False`` stacks a new leading device axis.  Call inside
    shard_map."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def allreduce_min(x: jax.Array, axis_name: Union[str, Sequence[str]]) -> jax.Array:
    """MPI_Allreduce(MPI_MIN) (knn_mpi.cpp:277).  Call inside shard_map."""
    return lax.pmin(x, axis_name)


def allreduce_max(x: jax.Array, axis_name: Union[str, Sequence[str]]) -> jax.Array:
    """MPI_Allreduce(MPI_MAX) (knn_mpi.cpp:276).  Call inside shard_map."""
    return lax.pmax(x, axis_name)


def barrier(*arrays) -> None:
    """MPI_Barrier before MPI_Wtime (knn_mpi.cpp:133-134,395-396): block the
    host until every listed device computation has retired."""
    for a in jax.tree_util.tree_leaves(arrays):
        if isinstance(a, jax.Array):
            a.block_until_ready()
