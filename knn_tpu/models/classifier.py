"""KNN classifier: the reference's whole pipeline (distance -> sort -> vote,
knn_mpi.cpp:308-393) as a fit/predict estimator.

TPU-first design: predict is a single jitted program — tiled distance
matmul, streaming top-k, vectorized reference-semantics vote — compiled once
per (batch_shape, k, metric) and reused across query batches.  Queries are
processed in fixed-size batches (padding the tail) so XLA sees static shapes.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from knn_tpu.ops.normalize import minmax_apply, minmax_stats
from knn_tpu.ops.topk import knn_search_tiled
from knn_tpu.ops.vote import majority_vote


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "num_classes", "train_tile", "compute_dtype")
)
def knn_predict(
    train: jax.Array,
    train_labels: jax.Array,
    queries: jax.Array,
    *,
    k: int,
    num_classes: int,
    metric: str = "l2",
    train_tile: Optional[int] = None,
    compute_dtype=None,
) -> jax.Array:
    """Functional core: predicted labels [Q] for one query batch.

    The fused equivalent of the reference's per-query loop
    (knn_mpi.cpp:315-338): distance fill -> top-k select -> majority vote.
    """
    _, idx = knn_search_tiled(
        queries, train, k, metric, train_tile=train_tile, compute_dtype=compute_dtype
    )
    return majority_vote(train_labels[idx], num_classes)


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "train_tile", "compute_dtype")
)
def knn_kneighbors(
    train: jax.Array,
    queries: jax.Array,
    *,
    k: int,
    metric: str = "l2",
    train_tile: Optional[int] = None,
    compute_dtype=None,
) -> Tuple[jax.Array, jax.Array]:
    """(distances, indices) of the k nearest train rows per query."""
    return knn_search_tiled(
        queries, train, k, metric, train_tile=train_tile, compute_dtype=compute_dtype
    )


class KNNClassifier:
    """Brute-force KNN classifier with the reference's semantics.

    Args mirror the reference's compile-time config block
    (knn_mpi.cpp:108-119) but are runtime parameters:
      k: neighbors (ref ``K`` :109).
      metric: 'l2' | 'l1' | 'cosine' | 'dot' (ref ``Euclidean_distance`` :114).
      num_classes: ref ``class_cnt`` :113; inferred from labels if None.
      normalize: min-max normalize train at fit and queries at predict using
        **train-only** stats.  (The reference's transductive train∪test∪val
        normalization lives in knn_tpu.pipeline, which reproduces the full
        job; an estimator must not peek at queries at fit time.)
      train_tile: stream the database in tiles of this many rows (None =
        materialize the full |Q|x|T| distance matrix per batch).
      batch_size: queries per compiled step (tail batch is padded).
      compute_dtype: matmul input dtype, e.g. jnp.bfloat16 for MXU speed.
      mesh: a ``jax.sharding.Mesh`` from :func:`knn_tpu.parallel.make_mesh`
        — fit places the database across it once and every predict/
        kneighbors runs the sharded SPMD program (parallel.ShardedKNN).
        None = single-device jitted path (identical results).
      merge: db-axis merge strategy when meshed ('allgather' | 'ring').
      mode: 'exact' | 'certified' (meshed, l2 or cosine) — certified runs
        the coarse+certificate pipeline; neighbor indices (and hence
        labels) are still exact (cosine: for the f32-row-normalized
        problem, see ShardedKNN.search_certified).
      selector: coarse selector for certified mode ('approx' | 'pallas' |
        'exact').  The pallas selector returns f32-accurate kneighbors
        distances (see ShardedKNN.search_certified); the others float64.
    """

    def __init__(
        self,
        k: int = 5,
        metric: str = "l2",
        num_classes: Optional[int] = None,
        normalize: bool = False,
        train_tile: Optional[int] = None,
        batch_size: Optional[int] = None,
        compute_dtype=None,
        mesh=None,
        merge: str = "allgather",
        mode: str = "exact",
        selector: str = "approx",
    ):
        if mode not in ("exact", "certified"):
            raise ValueError(f"unknown mode {mode!r}")
        if mode == "certified" and mesh is None:
            raise ValueError("mode='certified' needs a mesh (make_mesh(1, 1) is fine)")
        if mode == "certified" and metric not in ("l2", "sql2", "euclidean",
                                                  "cosine"):
            raise ValueError(
                "mode='certified' supports the l2 and cosine metrics only")
        self.k = k
        self.metric = metric
        self.num_classes = num_classes
        self.normalize = normalize
        self.train_tile = train_tile
        self.batch_size = batch_size
        self.compute_dtype = compute_dtype
        self.mesh = mesh
        self.merge = merge
        self.mode = mode
        self.selector = selector
        self._train = None
        self._labels = None
        self._mins = None
        self._maxs = None
        self._program = None

    # -- fit ---------------------------------------------------------------
    def fit(self, X, y) -> "KNNClassifier":
        X = jnp.asarray(X)
        y = jnp.asarray(y, dtype=jnp.int32)
        if X.ndim != 2 or y.ndim != 1 or X.shape[0] != y.shape[0]:
            raise ValueError(f"bad shapes: X {X.shape}, y {y.shape}")
        if self.k > X.shape[0]:
            raise ValueError(f"k={self.k} > n_train={X.shape[0]}")
        if self.num_classes is None:
            self.num_classes = int(jnp.max(y)) + 1
        if self.normalize:
            self._mins, self._maxs = minmax_stats([X])
            X = minmax_apply(X, self._mins, self._maxs)
        self._train = X
        self._labels = y
        self._program = None  # a refit must never serve the old placement
        if self.mesh is not None:
            from knn_tpu.parallel.sharded import ShardedKNN

            # placed once; every predict/kneighbors reuses the placement
            self._program = ShardedKNN(
                np.asarray(X), mesh=self.mesh, k=self.k, metric=self.metric,
                merge=self.merge, train_tile=self.train_tile,
                compute_dtype=self.compute_dtype,
                labels=np.asarray(y), num_classes=self.num_classes,
            )
        return self

    def _require_fit(self):
        if self._train is None:
            raise RuntimeError("call fit() before predict()/kneighbors()")

    def _prep_queries(self, Q) -> jax.Array:
        Q = jnp.asarray(Q)
        if Q.ndim != 2 or Q.shape[1] != self._train.shape[1]:
            raise ValueError(f"queries {Q.shape} vs train {self._train.shape}")
        if self.normalize:
            Q = minmax_apply(Q, self._mins, self._maxs)
        return Q

    def _batched(self, Q, fn, n_out: int):
        """Run fn over fixed-size query batches, padding the tail — the
        static-shape replacement for the reference's divisibility aborts
        (knn_mpi.cpp:127-129)."""
        n = Q.shape[0]
        bs = self.batch_size or n
        outs = []
        for start in range(0, n, bs):
            chunk = Q[start : start + bs]
            if chunk.shape[0] < bs:
                chunk = jnp.pad(chunk, ((0, bs - chunk.shape[0]), (0, 0)))
            res = fn(chunk)
            res = res if isinstance(res, tuple) else (res,)
            outs.append(tuple(r[: min(bs, n - start)] for r in res))
        if len(outs) == 1:
            cat = outs[0]
        else:
            # host-side concatenate: XLA GSPMD (jax 0.4.x) miscompiles
            # jnp.concatenate of query-sharded batch outputs on a 2-D
            # mesh — it psums the db-replicated copies, returning labels
            # db_shards x too large — while fetch-then-concat is immune
            # (the estimator's consumers cross to host anyway)
            cat = tuple(
                jnp.asarray(np.concatenate(
                    [np.asarray(o[i]) for o in outs], axis=0))
                for i in range(n_out)
            )
        return cat if n_out > 1 else cat[0]

    # -- inference ---------------------------------------------------------
    def predict(self, Q) -> jax.Array:
        """Predicted labels [Q] — the reference's KNN phase + vote."""
        self._require_fit()
        Q = self._prep_queries(Q)
        if self._program is not None:
            if self.mode == "certified":
                labels, _ = self._program.predict_certified(
                    np.asarray(Q), selector=self.selector,
                    batch_size=self.batch_size,
                )
                return jnp.asarray(labels)
            return self._batched(Q, self._program.predict, 1)
        return self._batched(
            Q,
            lambda c: knn_predict(
                self._train,
                self._labels,
                c,
                k=self.k,
                num_classes=self.num_classes,
                metric=self.metric,
                train_tile=self.train_tile,
                compute_dtype=self.compute_dtype,
            ),
            1,
        )

    def kneighbors(self, Q, *, return_sqrt: bool = False
                   ) -> Tuple[jax.Array, jax.Array]:
        """(distances, indices) of the k nearest neighbors per query.

        L2-family distances are SQUARED by default (the reference's
        monotone sqrt, knn_mpi.cpp:48, is dropped for ranking);
        ``return_sqrt=True`` returns true Euclidean values matching
        ``Euclidean_D`` / sklearn."""
        self._require_fit()
        Q = self._prep_queries(Q)
        if self._program is not None:
            if self.mode == "certified":
                d, i, _ = self._program.search_certified(
                    np.asarray(Q), selector=self.selector,
                    batch_size=self.batch_size, return_sqrt=return_sqrt,
                )
                return jnp.asarray(d), jnp.asarray(i)
            d, i = self._batched(Q, self._program.search, 2)
        else:
            d, i = self._batched(
                Q,
                lambda c: knn_kneighbors(
                    self._train,
                    c,
                    k=self.k,
                    metric=self.metric,
                    train_tile=self.train_tile,
                    compute_dtype=self.compute_dtype,
                ),
                2,
            )
        if return_sqrt:
            from knn_tpu.ops.distance import metric_values

            d = metric_values(d, self.metric)
        return d, i

    def score(self, Q, y) -> float:
        """Accuracy — ``acc_calc`` (knn_mpi.cpp:69-84)."""
        pred = np.asarray(self.predict(Q))
        return float(np.mean(pred == np.asarray(y)))
