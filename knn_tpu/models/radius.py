"""Radius-neighbors classifier — fixed-radius voting on top of
ops.radius (beyond the reference's fixed-K vote, same vote semantics).

The vote among in-radius neighbors reuses the reference's exact
first-to-reach-max tie-break (ops.vote, knn_mpi.cpp:324-336): in-radius
neighbors form the ascending-distance prefix of the bounded result, and
masked slots carry label -1, which ``jax.nn.one_hot`` drops from the
histogram — so the running-argmax semantics carry over unchanged.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from knn_tpu.ops.normalize import minmax_apply, minmax_stats
from knn_tpu.ops.radius import SENTINEL_IDX, radius_search
from knn_tpu.ops.vote import majority_vote


class RadiusNeighborsClassifier:
    """Classify by majority vote among all training points within
    ``radius`` of the query (nearest ``max_neighbors`` of them when more
    are inside — see ``strict``).

    Args:
      radius: metric-units radius (Euclidean for l2 — see
        ops.radius.radius_threshold).
      max_neighbors: bounded result width M (TPU needs static shapes).
        ``strict=True`` (default) raises when any query has more than M
        in-radius neighbors, so the vote is never silently truncated;
        ``strict=False`` votes among the nearest M — a documented
        approximation, with the exact counts still available via
        :meth:`radius_neighbors`.
      outlier_label: label for queries with ZERO in-radius neighbors;
        None (default) raises on the first outlier instead.
      metric / normalize / train_tile / compute_dtype: as KNNClassifier.
    """

    def __init__(
        self,
        radius: float,
        *,
        max_neighbors: int = 128,
        metric: str = "l2",
        num_classes: Optional[int] = None,
        normalize: bool = False,
        train_tile: Optional[int] = None,
        compute_dtype=None,
        outlier_label: Optional[int] = None,
        strict: bool = True,
    ):
        from knn_tpu.ops.radius import radius_threshold

        radius_threshold(radius, metric)  # validate radius/metric pairing now
        self.radius = radius
        self.max_neighbors = max_neighbors
        self.metric = metric
        self.num_classes = num_classes
        self.normalize = normalize
        self.train_tile = train_tile
        self.compute_dtype = compute_dtype
        self.outlier_label = outlier_label
        self.strict = strict
        self._train = None
        self._labels = None
        self._mins = None
        self._maxs = None

    def fit(self, X, y) -> "RadiusNeighborsClassifier":
        X = jnp.asarray(X)
        y = jnp.asarray(y, dtype=jnp.int32)
        if X.ndim != 2 or y.ndim != 1 or X.shape[0] != y.shape[0]:
            raise ValueError(f"bad shapes: X {X.shape}, y {y.shape}")
        if self.num_classes is None:
            self.num_classes = int(jnp.max(y)) + 1
        if self.normalize:
            self._mins, self._maxs = minmax_stats([X])
            X = minmax_apply(X, self._mins, self._maxs)
        self._train = X
        self._labels = y
        return self

    def _require_fit(self):
        if self._train is None:
            raise RuntimeError("call fit() before predict()/radius_neighbors()")

    def _prep_queries(self, Q):
        Q = jnp.asarray(Q)
        if Q.ndim != 2 or Q.shape[1] != self._train.shape[1]:
            raise ValueError(f"queries {Q.shape} vs train {self._train.shape}")
        if self.normalize:
            Q = minmax_apply(Q, self._mins, self._maxs)
        return Q

    def radius_neighbors(self, Q):
        """(dists [Q, M], idx [Q, M], counts [Q]) — see ops.radius."""
        self._require_fit()
        return radius_search(
            self._prep_queries(Q), self._train, self.radius,
            max_neighbors=self.max_neighbors, metric=self.metric,
            train_tile=self.train_tile, compute_dtype=self.compute_dtype,
        )

    def predict(self, Q):
        self._require_fit()
        _, idx, counts = self.radius_neighbors(Q)
        counts = np.asarray(counts)
        if self.strict and (counts > self.max_neighbors).any():
            worst = int(counts.max())
            raise ValueError(
                f"{int((counts > self.max_neighbors).sum())} queries have "
                f"more than max_neighbors={self.max_neighbors} in-radius "
                f"neighbors (max {worst}); raise max_neighbors, shrink the "
                f"radius, or pass strict=False to vote among the nearest "
                f"{self.max_neighbors}"
            )
        idx = np.asarray(idx)
        labels = np.asarray(self._labels)[np.clip(idx, 0, None)]
        labels = np.where(idx == SENTINEL_IDX, -1, labels)  # one_hot drops -1
        pred = np.asarray(majority_vote(jnp.asarray(labels), self.num_classes))
        outliers = counts == 0
        if outliers.any():
            if self.outlier_label is None:
                raise ValueError(
                    f"{int(outliers.sum())} queries have no neighbors within "
                    f"radius {self.radius}; widen the radius or set "
                    f"outlier_label"
                )
            pred = np.where(outliers, np.int32(self.outlier_label), pred)
        return jnp.asarray(pred)

    def score(self, Q, y) -> float:
        pred = np.asarray(self.predict(Q))
        return float(np.mean(pred == np.asarray(y)))
