"""Radius-neighbors estimators — fixed-radius voting/regression on top of
ops.radius (beyond the reference's fixed-K vote, same vote semantics).

The classifier's vote among in-radius neighbors reuses the reference's
exact first-to-reach-max tie-break (ops.vote, knn_mpi.cpp:324-336):
in-radius neighbors form the ascending-distance prefix of the bounded
result, and masked slots carry label -1, which ``jax.nn.one_hot`` drops
from the histogram — so the running-argmax semantics carry over
unchanged.  The regressor aggregates in-radius targets (uniform mean or
inverse-distance weights, the same weighting home as KNNRegressor).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from knn_tpu.ops.normalize import minmax_apply, minmax_stats
from knn_tpu.ops.radius import SENTINEL_IDX, radius_search
from knn_tpu.ops.vote import majority_vote


class _RadiusNeighborsBase:
    """Shared fit / query-prep / bounded radius search / truncation guard
    of the radius estimators.  See RadiusNeighborsClassifier for the
    parameter semantics."""

    def __init__(
        self,
        radius: float,
        *,
        max_neighbors: int = 128,
        metric: str = "l2",
        normalize: bool = False,
        train_tile: Optional[int] = None,
        compute_dtype=None,
        strict: bool = True,
    ):
        from knn_tpu.ops.radius import radius_threshold

        radius_threshold(radius, metric)  # validate radius/metric pairing now
        self.radius = radius
        self.max_neighbors = max_neighbors
        self.metric = metric
        self.normalize = normalize
        self.train_tile = train_tile
        self.compute_dtype = compute_dtype
        self.strict = strict
        self._train = None
        self._y = None
        self._mins = None
        self._maxs = None

    def _fit_targets(self, y):  # subclass: dtype/validation of y
        raise NotImplementedError

    def fit(self, X, y):
        X = jnp.asarray(X)
        y_raw = jnp.asarray(y)
        # shape compatibility BEFORE subclass target processing: a failed
        # fit must leave no half-inferred state (e.g. num_classes) behind
        if X.ndim != 2 or X.shape[0] != y_raw.shape[0]:
            raise ValueError(f"bad shapes: X {X.shape}, y {y_raw.shape}")
        y = self._fit_targets(y_raw)
        if self.normalize:
            self._mins, self._maxs = minmax_stats([X])
            X = minmax_apply(X, self._mins, self._maxs)
        self._train = X
        self._y = y
        return self

    def _require_fit(self):
        if self._train is None:
            raise RuntimeError("call fit() before predict()/radius_neighbors()")

    def _prep_queries(self, Q):
        Q = jnp.asarray(Q)
        if Q.ndim != 2 or Q.shape[1] != self._train.shape[1]:
            raise ValueError(f"queries {Q.shape} vs train {self._train.shape}")
        if self.normalize:
            Q = minmax_apply(Q, self._mins, self._maxs)
        return Q

    def radius_neighbors(self, Q):
        """(dists [Q, M], idx [Q, M], counts [Q]) — see ops.radius."""
        self._require_fit()
        return radius_search(
            self._prep_queries(Q), self._train, self.radius,
            max_neighbors=self.max_neighbors, metric=self.metric,
            train_tile=self.train_tile, compute_dtype=self.compute_dtype,
        )

    def _checked_neighbors(self, Q):
        """radius_neighbors + the strict truncation guard, as numpy."""
        from knn_tpu.ops.radius import check_truncation

        d, idx, counts = self.radius_neighbors(Q)
        counts = np.asarray(counts)
        if self.strict:
            check_truncation(
                counts, self.max_neighbors,
                f"aggregate the nearest {self.max_neighbors}")
        return np.asarray(d), np.asarray(idx), counts


class RadiusNeighborsClassifier(_RadiusNeighborsBase):
    """Classify by majority vote among all training points within
    ``radius`` of the query (nearest ``max_neighbors`` of them when more
    are inside — see ``strict``).

    Args:
      radius: metric-units radius (Euclidean for l2 — see
        ops.radius.radius_threshold).
      max_neighbors: bounded result width M (TPU needs static shapes).
        ``strict=True`` (default) raises when any query has more than M
        in-radius neighbors, so the vote is never silently truncated;
        ``strict=False`` votes among the nearest M — a documented
        approximation, with the exact counts still available via
        :meth:`radius_neighbors`.
      outlier_label: label for queries with ZERO in-radius neighbors;
        None (default) raises on the first outlier instead.
      metric / normalize / train_tile / compute_dtype: as KNNClassifier.
    """

    def __init__(
        self,
        radius: float,
        *,
        num_classes: Optional[int] = None,
        outlier_label: Optional[int] = None,
        **kwargs,
    ):
        super().__init__(radius, **kwargs)
        self.num_classes = num_classes
        self.outlier_label = outlier_label

    def _fit_targets(self, y):
        y = jnp.asarray(y, dtype=jnp.int32)
        if y.ndim != 1:
            raise ValueError(f"labels must be 1-D, got {y.shape}")
        if self.num_classes is None:
            self.num_classes = int(jnp.max(y)) + 1
        return y

    def predict(self, Q):
        self._require_fit()
        _, idx, counts = self._checked_neighbors(Q)
        labels = np.asarray(self._y)[np.clip(idx, 0, None)]
        labels = np.where(idx == SENTINEL_IDX, -1, labels)  # one_hot drops -1
        pred = np.asarray(majority_vote(jnp.asarray(labels), self.num_classes))
        outliers = counts == 0
        if outliers.any():
            if self.outlier_label is None:
                raise ValueError(
                    f"{int(outliers.sum())} queries have no neighbors within "
                    f"radius {self.radius}; widen the radius or set "
                    f"outlier_label"
                )
            pred = np.where(outliers, np.int32(self.outlier_label), pred)
        return jnp.asarray(pred)

    def score(self, Q, y) -> float:
        pred = np.asarray(self.predict(Q))
        return float(np.mean(pred == np.asarray(y)))


class RadiusNeighborsRegressor(_RadiusNeighborsBase):
    """Regress as the (optionally inverse-distance-weighted) mean target
    over all training points within ``radius``.

    ``weights``: 'uniform' | 'distance' (1/d, same convention as
    KNNRegressor — l2 distances are sqrt'ed before weighting).
    ``outlier_value``: prediction for queries with zero in-radius
    neighbors; None (default) raises instead.  Other args as
    RadiusNeighborsClassifier.
    """

    def __init__(
        self,
        radius: float,
        *,
        weights: str = "uniform",
        outlier_value: Optional[float] = None,
        **kwargs,
    ):
        if weights not in ("uniform", "distance"):
            raise ValueError(f"unknown weights {weights!r}")
        super().__init__(radius, **kwargs)
        self.weights = weights
        self.outlier_value = outlier_value

    def _fit_targets(self, y):
        return jnp.asarray(y, dtype=jnp.float32)

    def predict(self, Q):
        self._require_fit()
        d, idx, counts = self._checked_neighbors(Q)
        within = idx != SENTINEL_IDX
        targets = np.asarray(self._y)[np.clip(idx, 0, None)].astype(np.float64)
        if targets.ndim == 3:
            within_t = within[..., None]
        else:
            within_t = within
        n_sel = np.maximum(within.sum(axis=1), 1)
        if self.weights == "uniform":
            pred = (np.where(within_t, targets, 0.0).sum(axis=1)
                    / (n_sel[:, None] if targets.ndim == 3 else n_sel))
        else:
            from knn_tpu.models.regressor import DIST_FLOOR, L2_FAMILY

            # float64 weights: a float32 array would underflow the
            # 1e-300 zero-sum guard below to 0 (0/0 on all-outlier rows)
            dv = d.astype(np.float64)
            if self.metric.lower() in L2_FAMILY:
                dv = np.sqrt(np.maximum(dv, 0.0))  # ranking space is squared
            w = np.where(within, 1.0 / np.maximum(dv, DIST_FLOOR), 0.0)
            w = w / np.maximum(w.sum(axis=1, keepdims=True), 1e-300)
            wt = w[..., None] if targets.ndim == 3 else w
            pred = (wt * np.where(within_t, targets, 0.0)).sum(axis=1)
        outliers = counts == 0
        if outliers.any():
            if self.outlier_value is None:
                raise ValueError(
                    f"{int(outliers.sum())} queries have no neighbors within "
                    f"radius {self.radius}; widen the radius or set "
                    f"outlier_value"
                )
            fill = np.float64(self.outlier_value)
            pred = np.where(
                outliers[:, None] if pred.ndim == 2 else outliers, fill, pred)
        return jnp.asarray(pred.astype(np.float32))

    def score(self, Q, y) -> float:
        """R^2 (coefficient of determination), sklearn convention:
        constant-y outputs score 1.0 when predicted exactly (else 0.0),
        and multi-output y averages per-output R^2 uniformly."""
        y = np.atleast_2d(np.asarray(y, dtype=np.float64).T).T
        pred = np.atleast_2d(
            np.asarray(self.predict(Q), dtype=np.float64).T).T
        ss_res = ((y - pred) ** 2).sum(axis=0)
        ss_tot = ((y - y.mean(axis=0)) ** 2).sum(axis=0)
        varying = ss_tot > 0
        r2 = np.where(
            varying,
            1.0 - ss_res / np.where(varying, ss_tot, 1.0),
            np.where(ss_res == 0, 1.0, 0.0),
        )
        return float(r2.mean())
