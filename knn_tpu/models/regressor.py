"""KNN regressor: mean / inverse-distance-weighted target over the k nearest
neighbors.  Not in the reference (which only classifies) — a natural
capability extension sharing the same L3 ops."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from knn_tpu.ops.topk import knn_search_tiled

#: ONE home for the inverse-distance weighting convention, shared with
#: models.radius.RadiusNeighborsRegressor (which reimplements the
#: arithmetic in numpy over masked arrays): the l2 family sqrt's its
#: squared ranking values before weighting, and distances floor at
#: DIST_FLOOR so exact duplicates don't divide by zero.
L2_FAMILY = ("l2", "sql2", "euclidean")
DIST_FLOOR = 1e-12


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "weights", "train_tile", "compute_dtype")
)
def knn_regress(
    train: jax.Array,
    train_targets: jax.Array,
    queries: jax.Array,
    *,
    k: int,
    metric: str = "l2",
    weights: str = "uniform",
    train_tile: Optional[int] = None,
    compute_dtype=None,
) -> jax.Array:
    dists, idx = knn_search_tiled(
        queries, train, k, metric, train_tile=train_tile, compute_dtype=compute_dtype
    )
    return _weighted_targets(dists, train_targets[idx], weights, metric,
                             queries=queries)


def _weighted_targets(dists, targets, weights: str, metric: str = "l2",
                      queries=None):
    """Reduce [Q, k] neighbor targets to predictions — the one place the
    uniform/inverse-distance weighting lives (single-device and meshed
    paths share it).

    ``weights="distance"`` is conventional 1/d weighting: the search
    returns SQUARED L2 for ranking speed (the monotone sqrt is dropped,
    knn_mpi.cpp:48), so the l2 metrics sqrt here first — weighting by
    squared distance would silently over-discount far neighbors.

    Exact-hit robustness: the expanded-square distance of a query to its
    own database row cancels to ~eps * ||q||^2 instead of exactly 0, and
    how much of that noise survives depends on the backend's matmul.
    When ``queries`` is provided (l2 family), squared distances within
    the cancellation band ``64 eps ||q||^2`` snap to zero, so exact
    duplicates dominate the weighting on every backend (the sklearn
    zero-distance convention) instead of receiving a finite
    noise-inflated distance."""
    targets = targets.astype(jnp.float32)  # [Q, k] or [Q, k, out]
    if weights == "uniform":
        return jnp.mean(targets, axis=1)
    if weights == "distance":
        if metric.lower() in L2_FAMILY:
            if queries is not None:
                q32 = jnp.asarray(queries).astype(jnp.float32)
                q_norm = jnp.sum(q32 * q32, axis=-1, keepdims=True)
                band = 64.0 * jnp.float32(jnp.finfo(jnp.float32).eps) * q_norm
                dists = jnp.where(dists <= band, 0.0, dists)
            dists = jnp.sqrt(jnp.maximum(dists, 0.0))
        w = 1.0 / jnp.maximum(dists, DIST_FLOOR)  # [Q, k]
        w = w / jnp.sum(w, axis=1, keepdims=True)
        if targets.ndim == 3:
            w = w[..., None]
        return jnp.sum(w * targets, axis=1)
    raise ValueError(f"unknown weights {weights!r}")


class KNNRegressor:
    """fit/predict regressor over the same tiled KNN core as the classifier.

    ``mesh`` places the database across devices once (parallel.ShardedKNN)
    and predicts via the sharded search + a host-side weighted reduction —
    same results as the single-device path.
    """

    def __init__(
        self,
        k: int = 5,
        metric: str = "l2",
        weights: str = "uniform",
        train_tile: Optional[int] = None,
        compute_dtype=None,
        mesh=None,
        merge: str = "allgather",
    ):
        if weights not in ("uniform", "distance"):
            raise ValueError(f"unknown weights {weights!r}")
        self.k = k
        self.metric = metric
        self.weights = weights
        self.train_tile = train_tile
        self.compute_dtype = compute_dtype
        self.mesh = mesh
        self.merge = merge
        self._train = None
        self._targets = None
        self._program = None

    def fit(self, X, y) -> "KNNRegressor":
        X = jnp.asarray(X)
        y = jnp.asarray(y, dtype=jnp.float32)
        if X.shape[0] != y.shape[0]:
            raise ValueError(f"bad shapes: X {X.shape}, y {y.shape}")
        if self.k > X.shape[0]:
            raise ValueError(f"k={self.k} > n_train={X.shape[0]}")
        self._train, self._targets = X, y
        self._program = None  # a refit must never serve the old placement
        if self.mesh is not None:
            from knn_tpu.parallel.sharded import ShardedKNN

            import numpy as np

            self._program = ShardedKNN(
                np.asarray(X), mesh=self.mesh, k=self.k, metric=self.metric,
                merge=self.merge, train_tile=self.train_tile,
                compute_dtype=self.compute_dtype,
            )
        return self

    def predict(self, Q) -> jax.Array:
        if self._train is None:
            raise RuntimeError("call fit() first")
        if self._program is not None:
            dists, idx = self._program.search(jnp.asarray(Q))
            return _weighted_targets(
                dists, self._targets[idx], self.weights, self.metric,
                queries=Q,
            )
        return knn_regress(
            self._train,
            self._targets,
            jnp.asarray(Q),
            k=self.k,
            metric=self.metric,
            weights=self.weights,
            train_tile=self.train_tile,
            compute_dtype=self.compute_dtype,
        )
