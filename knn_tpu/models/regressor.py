"""KNN regressor: mean / inverse-distance-weighted target over the k nearest
neighbors.  Not in the reference (which only classifies) — a natural
capability extension sharing the same L3 ops."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from knn_tpu.ops.topk import knn_search_tiled


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "weights", "train_tile", "compute_dtype")
)
def knn_regress(
    train: jax.Array,
    train_targets: jax.Array,
    queries: jax.Array,
    *,
    k: int,
    metric: str = "l2",
    weights: str = "uniform",
    train_tile: Optional[int] = None,
    compute_dtype=None,
) -> jax.Array:
    dists, idx = knn_search_tiled(
        queries, train, k, metric, train_tile=train_tile, compute_dtype=compute_dtype
    )
    targets = train_targets[idx].astype(jnp.float32)  # [Q, k] or [Q, k, out]
    if weights == "uniform":
        return jnp.mean(targets, axis=1)
    if weights == "distance":
        w = 1.0 / jnp.maximum(dists, 1e-12)  # [Q, k]
        w = w / jnp.sum(w, axis=1, keepdims=True)
        if targets.ndim == 3:
            w = w[..., None]
        return jnp.sum(w * targets, axis=1)
    raise ValueError(f"unknown weights {weights!r}")


class KNNRegressor:
    """fit/predict regressor over the same tiled KNN core as the classifier."""

    def __init__(
        self,
        k: int = 5,
        metric: str = "l2",
        weights: str = "uniform",
        train_tile: Optional[int] = None,
        compute_dtype=None,
    ):
        self.k = k
        self.metric = metric
        self.weights = weights
        self.train_tile = train_tile
        self.compute_dtype = compute_dtype
        self._train = None
        self._targets = None

    def fit(self, X, y) -> "KNNRegressor":
        X = jnp.asarray(X)
        y = jnp.asarray(y, dtype=jnp.float32)
        if X.shape[0] != y.shape[0]:
            raise ValueError(f"bad shapes: X {X.shape}, y {y.shape}")
        if self.k > X.shape[0]:
            raise ValueError(f"k={self.k} > n_train={X.shape[0]}")
        self._train, self._targets = X, y
        return self

    def predict(self, Q) -> jax.Array:
        if self._train is None:
            raise RuntimeError("call fit() first")
        return knn_regress(
            self._train,
            self._targets,
            jnp.asarray(Q),
            k=self.k,
            metric=self.metric,
            weights=self.weights,
            train_tile=self.train_tile,
            compute_dtype=self.compute_dtype,
        )
