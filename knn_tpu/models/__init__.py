"""L4 model layer: user-facing KNN estimators built on the L3 ops."""

from knn_tpu.models.classifier import KNNClassifier, knn_predict
from knn_tpu.models.regressor import KNNRegressor

__all__ = ["KNNClassifier", "knn_predict", "KNNRegressor"]
