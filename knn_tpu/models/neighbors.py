"""Unsupervised nearest-neighbor queries + sparse graph exports.

The estimator surface users of sklearn-style libraries reach for first:
``fit(X)`` then ``kneighbors`` / ``radius_neighbors`` with no labels,
plus CSR adjacency exports (``kneighbors_graph`` /
``radius_neighbors_graph``).  Built on the same tiled/sharded cores as
the classifier (ops.topk, ops.radius, parallel.ShardedKNN); graphs are
returned as raw CSR triples ``(data, indices, indptr)`` so the library
keeps zero scipy dependency — ``scipy.sparse.csr_matrix(triple,
shape=(n_queries, n_fit_rows))`` reconstructs the standard object when
scipy is around.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from knn_tpu.ops.radius import SENTINEL_IDX, radius_search
from knn_tpu.ops.topk import knn_search_tiled


class NearestNeighbors:
    """fit/query container for neighbor searches.

    Args:
      k: default neighbor count for :meth:`kneighbors`.
      radius: default radius for :meth:`radius_neighbors` (metric units,
        ops.radius.radius_threshold).
      max_neighbors: bounded width of radius results (TPU static shapes;
        ops.radius truncation contract).
      metric / train_tile / compute_dtype: as KNNClassifier.
      mesh: place the database across a device mesh once
        (parallel.ShardedKNN); queries then run the sharded programs.
    """

    def __init__(
        self,
        k: int = 5,
        *,
        radius: Optional[float] = None,
        max_neighbors: int = 128,
        metric: str = "l2",
        train_tile: Optional[int] = None,
        compute_dtype=None,
        mesh=None,
        merge: str = "allgather",
    ):
        self.k = k
        self.radius = radius
        self.max_neighbors = max_neighbors
        self.metric = metric
        self.train_tile = train_tile
        self.compute_dtype = compute_dtype
        self.mesh = mesh
        self.merge = merge
        self._fit_X = None
        self._program = None

    @property
    def n_samples_fit(self) -> int:
        self._require_fit()
        return int(self._fit_X.shape[0])

    def fit(self, X) -> "NearestNeighbors":
        # host-resident: meshed fits hand the array to ShardedKNN (which
        # streams shards to their devices); a jnp.asarray here would
        # first commit a SECOND full copy to device 0
        X = np.asarray(X, dtype=np.float32)
        if X.ndim != 2:
            raise ValueError(f"X must be 2-D, got {X.shape}")
        if self.k > X.shape[0]:
            raise ValueError(f"k={self.k} > n_samples={X.shape[0]}")
        self._fit_X = X
        self._program = None
        if self.mesh is not None:
            from knn_tpu.parallel.sharded import ShardedKNN

            self._program = ShardedKNN(
                X, mesh=self.mesh, k=self.k, metric=self.metric,
                merge=self.merge, train_tile=self.train_tile,
                compute_dtype=self.compute_dtype,
            )
        return self

    def _require_fit(self):
        if self._fit_X is None:
            raise RuntimeError("call fit() before querying")

    def _prep(self, Q):
        Q = jnp.asarray(Q)
        if Q.ndim != 2 or Q.shape[1] != self._fit_X.shape[1]:
            raise ValueError(f"queries {Q.shape} vs fit {self._fit_X.shape}")
        return Q

    # -- queries -----------------------------------------------------------
    def kneighbors(self, Q, k: Optional[int] = None, *,
                   return_sqrt: bool = False):
        """(dists [Q, k], idx [Q, k]); squared l2 values unless
        ``return_sqrt`` (ops.topk lexicographic semantics)."""
        self._require_fit()
        k = self.k if k is None else k
        Q = self._prep(Q)
        if self._program is not None:
            d, i = self._program.search(Q, k=k, return_sqrt=return_sqrt)
            return d, i
        d, i = knn_search_tiled(
            Q, self._fit_X, k, self.metric,
            train_tile=self.train_tile, compute_dtype=self.compute_dtype,
        )
        if return_sqrt:
            from knn_tpu.ops.distance import metric_values

            d = metric_values(d, self.metric)
        return d, i

    def radius_neighbors(self, Q, radius: Optional[float] = None):
        """(dists [Q, M], idx [Q, M], counts [Q]) — ops.radius bounded
        formulation; ``counts > max_neighbors`` flags truncation."""
        self._require_fit()
        radius = self.radius if radius is None else radius
        if radius is None:
            raise ValueError("no radius given (constructor or call)")
        Q = self._prep(Q)
        if self._program is not None:
            return self._program.radius_search(
                np.asarray(Q, np.float32), radius,
                max_neighbors=self.max_neighbors)
        return radius_search(
            Q, self._fit_X, radius, max_neighbors=self.max_neighbors,
            metric=self.metric, train_tile=self.train_tile,
            compute_dtype=self.compute_dtype,
        )

    # -- graphs ------------------------------------------------------------
    def kneighbors_graph(
        self, Q=None, k: Optional[int] = None, *, mode: str = "connectivity",
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """CSR triple ``(data, indices, indptr)`` of the k-NN adjacency
        [n_queries, n_samples_fit].  ``mode='connectivity'`` gives 1.0
        entries, ``'distance'`` the ranking-space distances.  ``Q=None``
        builds the fit-set self-graph (each row's neighbors INCLUDE the
        row itself at distance 0, sklearn's include-self-free convention
        differs — drop column j == row i downstream if needed)."""
        self._require_fit()
        if mode not in ("connectivity", "distance"):
            raise ValueError(f"unknown mode {mode!r}")
        Q = self._fit_X if Q is None else Q
        d, i = self.kneighbors(Q, k)
        d, i = np.asarray(d), np.asarray(i)
        n_q, kk = i.shape
        data = (np.ones(n_q * kk, np.float32) if mode == "connectivity"
                else d.ravel().astype(np.float32))
        return data, i.ravel().astype(np.int64), np.arange(
            0, (n_q + 1) * kk, kk, dtype=np.int64)

    def radius_neighbors_graph(
        self, Q=None, radius: Optional[float] = None, *,
        mode: str = "connectivity", strict: bool = True,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """CSR triple of the within-radius adjacency.  Row widths vary
        (true CSR); ``strict=True`` raises when any query's in-radius
        set exceeds ``max_neighbors`` (the graph would silently lose
        edges), ``strict=False`` keeps the nearest ``max_neighbors``."""
        self._require_fit()
        if mode not in ("connectivity", "distance"):
            raise ValueError(f"unknown mode {mode!r}")
        Q = self._fit_X if Q is None else Q
        from knn_tpu.ops.radius import check_truncation

        d, i, counts = self.radius_neighbors(Q, radius)
        d, i, counts = np.asarray(d), np.asarray(i), np.asarray(counts)
        if strict:
            check_truncation(counts, self.max_neighbors,
                             "keep the nearest edges only")
        within = i != SENTINEL_IDX
        row_counts = within.sum(axis=1)
        indptr = np.zeros(i.shape[0] + 1, np.int64)
        np.cumsum(row_counts, out=indptr[1:])
        indices = i[within].astype(np.int64)
        data = (np.ones(indices.shape[0], np.float32)
                if mode == "connectivity"
                else d[within].astype(np.float32))
        return data, indices, indptr
