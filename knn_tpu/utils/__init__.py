"""L5 config + cross-cutting utilities (timing, metrics)."""

from knn_tpu.utils.config import JobConfig
from knn_tpu.utils.timing import PhaseTimer

__all__ = ["JobConfig", "PhaseTimer"]
