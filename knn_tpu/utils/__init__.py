"""L5 config + cross-cutting utilities (timing, metrics).

Lazy exports: importing ``knn_tpu.utils.config`` must not pull JAX (the CLI
parses flags through it), and ``timing`` imports JAX for device fences.
"""

_EXPORTS = {
    "JobConfig": "knn_tpu.utils.config",
    "PhaseTimer": "knn_tpu.utils.timing",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(f"module 'knn_tpu.utils' has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(target), name)
    globals()[name] = value
    return value
