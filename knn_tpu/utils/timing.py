"""Per-phase timing — the observability the reference lacks.

The reference has exactly one timer: a barrier-fenced ``MPI_Wtime`` pair
around the entire job, printed by rank 0 (knn_mpi.cpp:133-134, 395-398), so
its published numbers cannot attribute time to ingest vs communication vs
compute (SURVEY.md §5).  ``PhaseTimer`` gives each phase its own fence:
call :meth:`PhaseTimer.block` on the phase's device outputs before the
phase block closes (JAX dispatch is async — without the fence the timer
measures dispatch latency, not compute).

For deep dives, :func:`trace` wraps ``jax.profiler.trace`` to drop a
TensorBoard-loadable XLA trace.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

import jax


class PhaseTimer:
    """Accumulates named phase durations; total covers first start→last stop
    (the reference's single Wtime pair, knn_mpi.cpp:134,396, recovered as
    the sum)."""

    def __init__(self):
        self.phases: Dict[str, float] = {}
        self._t0: Optional[float] = None
        self._t_end: Optional[float] = None

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time a named phase.  Call :meth:`block` inside the body on any
        device arrays the phase produced — JAX dispatch is async, so the
        fence must come from within, after the work exists."""
        if self._t0 is None:
            self._t0 = time.perf_counter()
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            self.phases[name] = self.phases.get(name, 0.0) + (end - start)
            self._t_end = end

    def block(self, *arrays) -> None:
        """Fence device work into the *current* phase timing."""
        for a in jax.tree_util.tree_leaves(arrays):
            if isinstance(a, jax.Array):
                a.block_until_ready()

    @property
    def total(self) -> float:
        if self._t0 is None or self._t_end is None:
            return 0.0
        return self._t_end - self._t0

    def summary(self) -> Dict[str, float]:
        out = dict(self.phases)
        out["total"] = self.total
        return out


@contextlib.contextmanager
def trace(log_dir: str):
    """XLA profiler trace (TensorBoard format) around a code block."""
    with jax.profiler.trace(log_dir):
        yield
