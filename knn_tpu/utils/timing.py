"""Per-phase timing — the observability the reference lacks.

The reference has exactly one timer: a barrier-fenced ``MPI_Wtime`` pair
around the entire job, printed by rank 0 (knn_mpi.cpp:133-134, 395-398), so
its published numbers cannot attribute time to ingest vs communication vs
compute (SURVEY.md §5).  ``PhaseTimer`` gives each phase its own fence:
call :meth:`PhaseTimer.block` on the phase's device outputs before the
phase block closes (JAX dispatch is async — without the fence the timer
measures dispatch latency, not compute).

Since the telemetry subsystem landed (knn_tpu.obs), ``PhaseTimer`` is a
thin view over it: every phase close also records into the process-wide
``knn_tpu_phase_seconds{phase=...}`` histogram, so pipeline phases show
up in the same Prometheus scrape as serving latencies — the per-run
``summary()`` shape is unchanged.

Concurrency contract: a PhaseTimer may be SHARED across threads (the
serving worker threads and the pipeline do — all mutation is locked),
but phases must not NEST within one thread: the phase sum and the
first-start/last-stop total silently double-count under re-entrant
``phase()`` scopes, so nesting raises instead of corrupting the
numbers.  Distinct threads timing concurrent phases are fine (their
wall intervals legitimately overlap).

For deep dives, :func:`trace` wraps ``jax.profiler.trace`` to drop a
TensorBoard-loadable XLA trace.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

import jax

from knn_tpu import obs
from knn_tpu.obs import names as _mn


class PhaseTimer:
    """Accumulates named phase durations; total covers first start→last stop
    (the reference's single Wtime pair, knn_mpi.cpp:134,396, recovered as
    the sum).  Thread-safety: guarded by ``self._lock`` (machine-checked
    by the ``locked-mutation`` checker, knn_tpu.analysis); re-entrant
    nesting within a thread raises (see module docstring)."""

    def __init__(self):
        self.phases: Dict[str, float] = {}
        self._t0: Optional[float] = None
        self._t_end: Optional[float] = None
        self._lock = threading.Lock()
        #: per-thread open-phase name — nesting detection must not trip
        #: on OTHER threads' concurrently open phases
        self._open = threading.local()

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time a named phase.  Call :meth:`block` inside the body on any
        device arrays the phase produced — JAX dispatch is async, so the
        fence must come from within, after the work exists."""
        already = getattr(self._open, "name", None)
        if already is not None:
            raise RuntimeError(
                f"PhaseTimer.phase({name!r}) opened inside still-open "
                f"phase {already!r}: nested phases double-count the "
                f"phase sum and the total — close the outer phase first "
                f"(or use a second PhaseTimer)")
        self._open.name = name
        start = time.perf_counter()
        with self._lock:
            if self._t0 is None:
                self._t0 = start
        try:
            yield
        finally:
            end = time.perf_counter()
            self._open.name = None
            with self._lock:
                self.phases[name] = self.phases.get(name, 0.0) + (end - start)
                if self._t_end is None or end > self._t_end:
                    self._t_end = end
            obs.histogram(_mn.PHASE_SECONDS, phase=name).observe(end - start)
            obs.emit_event("phase", phase=name,
                           dur_s=round(end - start, 6))

    def block(self, *arrays) -> None:
        """Fence device work into the *current* phase timing."""
        for a in jax.tree_util.tree_leaves(arrays):
            if isinstance(a, jax.Array):
                a.block_until_ready()

    @property
    def total(self) -> float:
        with self._lock:
            if self._t0 is None or self._t_end is None:
                return 0.0
            return self._t_end - self._t0

    def summary(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self.phases)
        out["total"] = self.total
        return out


@contextlib.contextmanager
def trace(log_dir: str):
    """XLA profiler trace (TensorBoard format) around a code block."""
    with jax.profiler.trace(log_dir):
        yield
