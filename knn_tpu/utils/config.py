"""Runtime job configuration — the reference's compile-time constant block
(knn_mpi.cpp:108-119; report PDF p.11 §3.2.2) promoted to a real config.

The reference's documented workflow for changing any of these is *edit the
source and recompile* (PDF p.11 §3.3.1); here they are dataclass fields fed
by the CLI (knn_tpu.cli) — SURVEY.md §5 calls this the single biggest
usability delta.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass
from typing import Optional

from knn_tpu.ops.metrics import METRICS

#: Execution backends: JAX/XLA (TPU-native path) and the C++ CPU parity
#: oracle (knn_tpu.native, SURVEY.md §7 step 3).
BACKENDS = ("jax", "native")


@dataclass
class JobConfig:
    """One KNN classification job.

    Field ↔ reference mapping:
      dim          <- ``dim``                 knn_mpi.cpp:108 (None = infer from file)
      k            <- ``K``                   :109
      num_classes  <- ``class_cnt``           :113 (None = infer from labels)
      metric       <- ``Euclidean_distance``  :114 ('l2' true / 'l1' false, plus cosine/dot)
      normalize    <- ``Normalize``           :115
      validation   <- ``Validation``          :116
      train_file / val_file / test_file      :117-119
      output_file  <- the hard-coded ``Test_label.csv``  :390

    Fields with no reference counterpart configure the TPU execution:
    mesh shape (query_shards × db_shards), merge strategy, HBM train tile,
    query batch size, and matmul dtype.
    """

    train_file: str = "mnist_train.csv"
    test_file: str = "mnist_test.csv"
    val_file: Optional[str] = "mnist_validation.csv"
    output_file: str = "Test_label.csv"
    dim: Optional[int] = None
    k: int = 50
    num_classes: Optional[int] = None
    metric: str = "l2"
    normalize: bool = True
    validation: bool = True
    backend: str = "jax"
    # --- TPU execution knobs (no reference counterpart) ---
    query_shards: Optional[int] = None
    db_shards: int = 1
    merge: str = "allgather"
    train_tile: Optional[int] = None
    batch_size: Optional[int] = None
    compute_dtype: Optional[str] = None
    #: "exact" ranks every candidate in float32; "certified" uses a fast
    #: approximate selector + float64 refinement + the count-below
    #: certificate (ops.certified) — exact results, higher throughput at
    #: scale.  Certified supports the l2 and cosine metrics (cosine runs
    #: the certificate on unit vectors; ShardedKNN.search_certified).
    mode: str = "exact"
    #: local-shard selector for certified mode: "approx" | "pallas" | "exact"
    selector: str = "approx"
    # --- native backend knobs ---
    num_threads: int = 0  # 0 = hardware concurrency

    def __post_init__(self):
        # normalize case ONCE at the boundary: downstream dispatch
        # (ShardedKNN's `metric == "cosine"` placement normalization,
        # selector tables) compares lowercase names
        self.metric = self.metric.lower()
        if self.metric not in METRICS:
            raise ValueError(f"metric {self.metric!r} not in {METRICS}")
        if self.backend not in BACKENDS:
            raise ValueError(f"backend {self.backend!r} not in {BACKENDS}")
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")
        if self.validation and not self.val_file:
            raise ValueError("validation=True requires val_file")
        if self.mode not in ("exact", "certified"):
            raise ValueError(f"mode {self.mode!r} not in ('exact', 'certified')")
        if self.selector not in ("exact", "approx", "pallas"):
            raise ValueError(f"selector {self.selector!r} unknown")
        if self.mode == "certified" and self.metric not in (
            "l2", "sql2", "euclidean", "cosine"
        ):
            raise ValueError(
                "mode='certified' requires the l2 or cosine metric")

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, s: str) -> "JobConfig":
        return cls(**json.loads(s))
