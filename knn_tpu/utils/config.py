"""Runtime job configuration — the reference's compile-time constant block
(knn_mpi.cpp:108-119; report PDF p.11 §3.2.2) promoted to a real config.

The reference's documented workflow for changing any of these is *edit the
source and recompile* (PDF p.11 §3.3.1); here they are dataclass fields fed
by the CLI (knn_tpu.cli) — SURVEY.md §5 calls this the single biggest
usability delta.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass
from typing import Optional

from knn_tpu.ops.metrics import METRICS

#: Execution backends: JAX/XLA (TPU-native path) and the C++ CPU parity
#: oracle (knn_tpu.native, SURVEY.md §7 step 3).
BACKENDS = ("jax", "native")

#: kernel matmul precisions with a certified tolerance model —
#: ops.pallas_knn.PRECISIONS minus the uncertifiable "default".  ONE
#: home (jax-free, so the CLI can build its --help without importing
#: JAX); cli.py's choices, this module's validation, and
#: parallel.sharded's _pallas_setup check all consume it.
CERTIFIED_PRECISIONS = ("bf16x3", "bf16x3f", "highest", "int8", "int4",
                        "pq")


@dataclass
class JobConfig:
    """One KNN classification job.

    Field ↔ reference mapping:
      dim          <- ``dim``                 knn_mpi.cpp:108 (None = infer from file)
      k            <- ``K``                   :109
      num_classes  <- ``class_cnt``           :113 (None = infer from labels)
      metric       <- ``Euclidean_distance``  :114 ('l2' true / 'l1' false, plus cosine/dot)
      normalize    <- ``Normalize``           :115
      validation   <- ``Validation``          :116
      train_file / val_file / test_file      :117-119
      output_file  <- the hard-coded ``Test_label.csv``  :390

    Fields with no reference counterpart configure the TPU execution:
    mesh shape (query_shards × db_shards), merge strategy, HBM train tile,
    query batch size, and matmul dtype.
    """

    train_file: str = "mnist_train.csv"
    test_file: str = "mnist_test.csv"
    val_file: Optional[str] = "mnist_validation.csv"
    output_file: str = "Test_label.csv"
    dim: Optional[int] = None
    k: int = 50
    num_classes: Optional[int] = None
    metric: str = "l2"
    normalize: bool = True
    validation: bool = True
    backend: str = "jax"
    # --- TPU execution knobs (no reference counterpart) ---
    query_shards: Optional[int] = None
    db_shards: int = 1
    merge: str = "allgather"
    train_tile: Optional[int] = None
    batch_size: Optional[int] = None
    compute_dtype: Optional[str] = None
    #: "exact" ranks every candidate in float32; "certified" uses a fast
    #: approximate selector + float64 refinement + the count-below
    #: certificate (ops.certified) — exact results, higher throughput at
    #: scale.  Certified supports the l2 and cosine metrics (cosine runs
    #: the certificate on unit vectors; ShardedKNN.search_certified).
    mode: str = "exact"
    #: local-shard selector for certified mode: "approx" | "pallas" | "exact"
    selector: str = "approx"
    #: shape-bucketed serving (knn_tpu.serving): "auto" for the default
    #: geometric ladder, or an explicit comma list like "64,128,256".
    #: Queries route through precompiled per-bucket executables and the
    #: job metrics gain per-bucket compile counts + latency percentiles.
    #: None (default) = direct dispatch, one compile per batch shape.
    serve_buckets: Optional[str] = None
    #: micro-batching deadline (knn_tpu.serving.QueryQueue): how long a
    #: request may wait to be coalesced with others.  Echoed into the
    #: serving metrics; only a concurrent-request queue consults it.
    max_wait_ms: float = 2.0
    #: autotuner winner-cache file for the certified pallas selector
    #: (knn_tpu.tuning; populate with `python -m knn_tpu.cli tune`).
    #: None = $KNN_TPU_TUNE_CACHE or the user default path; the job's
    #: kernel knobs resolve from it through tuning.resolve, and the
    #: resolved set lands in metrics()["certified_stats"]["pallas_knobs"].
    tune_cache: Optional[str] = None
    #: explicit kernel matmul precision for the certified pallas
    #: selector (ops.pallas_knn.PRECISIONS minus the uncertifiable
    #: "default"): "bf16x3" | "bf16x3f" | "highest" | "int8" | "int4"
    #: (the quantized MXU arms — ops.quantize) | "pq" (product-quantized
    #: codes — ops.pq).  None = resolve through the autotuner cache /
    #: library default; an explicit value beats both.
    pallas_precision: Optional[str] = None
    # --- native backend knobs ---
    num_threads: int = 0  # 0 = hardware concurrency

    def __post_init__(self):
        # normalize case ONCE at the boundary: downstream dispatch
        # (ShardedKNN's `metric == "cosine"` placement normalization,
        # selector tables) compares lowercase names
        self.metric = self.metric.lower()
        if self.metric not in METRICS:
            raise ValueError(f"metric {self.metric!r} not in {METRICS}")
        if self.backend not in BACKENDS:
            raise ValueError(f"backend {self.backend!r} not in {BACKENDS}")
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")
        if self.validation and not self.val_file:
            raise ValueError("validation=True requires val_file")
        if self.mode not in ("exact", "certified"):
            raise ValueError(f"mode {self.mode!r} not in ('exact', 'certified')")
        if self.selector not in ("exact", "approx", "pallas"):
            raise ValueError(f"selector {self.selector!r} unknown")
        if self.pallas_precision is not None and \
                self.pallas_precision not in CERTIFIED_PRECISIONS:
            raise ValueError(
                f"pallas_precision {self.pallas_precision!r} not in "
                f"{CERTIFIED_PRECISIONS}")
        if self.mode == "certified" and self.metric not in (
            "l2", "sql2", "euclidean", "cosine"
        ):
            raise ValueError(
                "mode='certified' requires the l2 or cosine metric")
        if self.serve_buckets is not None:
            # dependency-free ladder validation (knn_tpu.serving.buckets
            # imports no jax/numpy), so bad flags fail at parse time
            from knn_tpu.serving.buckets import parse_buckets

            if parse_buckets(self.serve_buckets) is None:
                self.serve_buckets = None  # empty spec = serving off
            if self.serve_buckets is not None and self.mode == "certified":
                raise ValueError(
                    "serve_buckets routes through the exact bucketed "
                    "programs; mode='certified' has its own batching "
                    "(batch_size) and does not compose with it")
            if self.serve_buckets is not None and self.backend != "jax":
                raise ValueError("serve_buckets requires the jax backend")
        if self.max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {self.max_wait_ms}")

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, s: str) -> "JobConfig":
        return cls(**json.loads(s))
