"""JAX version-compat helpers shared by the entry points.

Kept separate from utils.config (which must stay importable without
JAX) and from parallel.collectives (whose shard_map shim is the other
compat seam): everything here touches ``jax.config`` and must run
BEFORE backend initialization.
"""

from __future__ import annotations

import os


def request_cpu_devices(n: int) -> None:
    """Force the CPU backend with ``n`` virtual devices.

    Must run before any backend use; a ``RuntimeError`` (backend already
    initialized) propagates to the caller, who knows whether a
    preconfigured backend is acceptable.  Newer jax spells the device
    count ``jax_num_cpu_devices``; older versions only honor the XLA
    flag, which this sets as the fallback (same mechanism as
    tests/conftest.py).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}"
        ).strip()
