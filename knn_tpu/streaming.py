"""Resumable query-batch streaming — the checkpoint/resume subsystem the
reference lacks (SURVEY.md §5: its only durable artifact is the final
``Test_label.csv``, knn_mpi.cpp:390-392; a crash loses everything).

Large query sets (SIFT1M/GIST1M-scale, BASELINE.json configs 3/5) run as a
sequence of fixed-size batches; each batch's top-k lands in its own
atomically-written ``.npz`` under a checkpoint directory with a manifest
guarding against resuming onto the wrong database/config.  A re-run skips
finished batches, so a preempted multi-hour run loses at most one batch.

Per-batch retry is the failure-handling unit (SURVEY.md §5 failure row:
the reference is fail-stop only) — transient device errors re-dispatch the
batch up to ``max_retries`` times before surfacing, under the shared
failure classifier (parallel.sharded): deterministic failures propagate
immediately, unknown ones stop retrying once they repeat verbatim.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Callable, Optional, Tuple

import numpy as np


def _fingerprint(db: np.ndarray) -> str:
    """Cheap database identity: shape + dtype + strided sample digest."""
    h = hashlib.sha256()
    h.update(repr((db.shape, str(db.dtype))).encode())
    flat = np.ascontiguousarray(db).reshape(-1)
    step = max(1, flat.size // 4096)
    h.update(np.ascontiguousarray(flat[::step]).tobytes())
    return h.hexdigest()[:32]


@dataclasses.dataclass
class StreamState:
    """Progress snapshot: which batches are done."""

    n_queries: int
    batch_size: int
    n_batches: int
    done: list

    @property
    def complete(self) -> bool:
        return len(self.done) == self.n_batches


class StreamingSearch:
    """Checkpointed batch-streaming KNN search over a placed program.

    ``search_fn(query_batch) -> (dists [B, k], idx [B, k])`` is typically
    ``ShardedKNN.search`` (knn_tpu.parallel), but any callable with that
    contract works — including a composition with ops.refine.
    """

    MANIFEST = "manifest.json"

    def __init__(
        self,
        search_fn: Callable[[np.ndarray], Tuple],
        k: int,
        checkpoint_dir: str,
        *,
        batch_size: int = 512,
        db_fingerprint: Optional[str] = None,
        search_config: Optional[dict] = None,
        max_retries: int = 2,
    ):
        self._fn = search_fn
        self.k = k
        self.dir = checkpoint_dir
        self.batch_size = batch_size
        self.fingerprint = db_fingerprint
        #: JSON-serializable echo of the search configuration (metric,
        #: dtype, merge, ...) — part of the resume guard, because finished
        #: batches computed under a different config are silently wrong
        self.search_config = search_config or {}
        self.max_retries = max_retries
        os.makedirs(self.dir, exist_ok=True)

    # -- manifest ----------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, self.MANIFEST)

    def _expected_manifest(self, queries: np.ndarray) -> dict:
        return {
            "n_queries": int(queries.shape[0]),
            "query_fingerprint": _fingerprint(queries),
            "batch_size": self.batch_size,
            "k": self.k,
            "db_fingerprint": self.fingerprint,
            "search_config": self.search_config,
        }

    def _check_manifest(self, queries: np.ndarray) -> None:
        path = self._manifest_path()
        expected = self._expected_manifest(queries)
        if os.path.exists(path):
            with open(path) as f:
                found = json.load(f)
            if found != expected:
                raise ValueError(
                    f"checkpoint dir {self.dir} belongs to a different run:\n"
                    f"  found    {found}\n  expected {expected}\n"
                    "use a fresh directory or delete the stale checkpoint"
                )
        else:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(expected, f)
            os.replace(tmp, path)

    def _batch_path(self, b: int) -> str:
        return os.path.join(self.dir, f"batch_{b:06d}.npz")

    def state(self, n_queries: int) -> StreamState:
        n_batches = -(-n_queries // self.batch_size)
        done = sorted(
            int(name[len("batch_") : -len(".npz")])
            for name in os.listdir(self.dir)
            if name.startswith("batch_") and name.endswith(".npz")
        )
        return StreamState(n_queries, self.batch_size, n_batches, done)

    # -- execution ---------------------------------------------------------
    #: pad each batch to ``batch_size`` and strip after (one compiled
    #: shape; the reference aborts on non-divisible sizes instead,
    #: knn_mpi.cpp:127-129).  Subclasses whose search fn pads internally
    #: set False and receive the raw tail chunk.
    _pad_batches = True

    def _run_batch(self, chunk: np.ndarray):
        # the per-batch retry delegates to the shared failure classifier
        # (parallel.sharded): known-transient errors get the full backoff
        # window, deterministic ones (compile errors, OOM) propagate
        # immediately, unknown errors stop once they repeat verbatim
        from knn_tpu.parallel.sharded import _retry_transient

        d, i = _retry_transient(
            lambda: self._fn(chunk), "stream batch",
            attempts=self.max_retries + 1)
        return np.asarray(d), np.asarray(i)

    def _strip(self, result, pad: int):
        """Drop the ``pad`` trailing padded rows from a batch result."""
        d, i = result
        return d[:-pad], i[:-pad]

    def _payload(self, result) -> dict:
        """Batch result -> the arrays persisted in its ``.npz``."""
        d, i = result
        return {"d": d, "i": i}

    def run(self, queries: np.ndarray):
        """Stream all batches, skipping finished ones; returns
        :meth:`assemble` of the complete run.  ONE loop for every
        subclass — padding, the atomic tmp+replace write, and the
        done-set skip live here only."""
        queries = np.asarray(queries)
        n = queries.shape[0]
        self._check_manifest(queries)
        st = self.state(n)
        done = set(st.done)
        for b in range(st.n_batches):
            if b in done:
                continue
            lo = b * self.batch_size
            chunk = queries[lo : lo + self.batch_size]
            pad = self.batch_size - chunk.shape[0]
            if pad and self._pad_batches:
                chunk = np.pad(chunk, ((0, pad), (0, 0)))
            result = self._run_batch(chunk)
            if pad and self._pad_batches:
                result = self._strip(result, pad)
            tmp = self._batch_path(b) + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **self._payload(result))
            os.replace(tmp, self._batch_path(b))
        return self.assemble(n)

    def _iter_complete(self, n_queries: int):
        """Yield each finished batch's persisted arrays (dict), after
        verifying the run is complete — the shared read side of
        :meth:`assemble`."""
        st = self.state(n_queries)
        if not st.complete:
            missing = sorted(set(range(st.n_batches)) - set(st.done))
            raise RuntimeError(
                f"stream incomplete; missing batches {missing[:8]}...")
        for b in range(st.n_batches):
            with np.load(self._batch_path(b)) as z:
                yield {key: z[key] for key in z.files}

    def assemble(self, n_queries: int) -> Tuple[np.ndarray, np.ndarray]:
        """Concatenate all finished batches (requires a complete run)."""
        ds, is_ = [], []
        for z in self._iter_complete(n_queries):
            ds.append(z["d"])
            is_.append(z["i"])
        return np.concatenate(ds)[:n_queries], np.concatenate(is_)[:n_queries]


class StreamingCertifiedSearch(StreamingSearch):
    """Checkpointed streaming for certified-exact sweeps — the flagship
    long-running workload (a 1M-query certified run is hours; VERDICT r4:
    ``StreamingSearch`` only composed with plain ``search``, so exactly
    the sweep checkpointing exists for persisted nothing).

    ``search_fn(query_batch) -> (dists | None, idx, stats)`` is typically
    a closure over :meth:`ShardedKNN.search_certified`.  Each checkpoint
    segment persists its results AND its certification ``stats`` dict
    (fallback / genuine-miss / false-alarm / rank-correction outcomes),
    so a resumed run reassembles the full sweep's outcome accounting, not
    just its neighbors.  Segments need no padding here: the certified
    pipeline pads internally to its own compiled batch shape, so the tail
    segment reuses the same device programs.

    ``assemble`` returns ``(dists | None, idx, stats)`` with integer
    stats summed across segments.
    """

    #: search_certified pads each segment internally to its own compiled
    #: batch shape, so the streaming layer hands it the raw tail chunk
    _pad_batches = False

    def _run_batch(self, chunk: np.ndarray):
        # same shared retry policy as StreamingSearch._run_batch — a
        # deterministic failure must not re-run a multi-thousand-query
        # certified segment max_retries extra times
        from knn_tpu.parallel.sharded import _retry_transient

        d, i, stats = _retry_transient(
            lambda: self._fn(chunk), "certified stream batch",
            attempts=self.max_retries + 1)
        return (
            None if d is None else np.asarray(d),
            np.asarray(i),
            dict(stats),
        )

    def _payload(self, result) -> dict:
        d, i, stats = result
        payload = {"i": i, "stats": json.dumps(stats)}
        if d is not None:
            payload["d"] = d
        return payload

    def assemble(self, n_queries: int):
        ds, is_, agg = [], [], {}
        n_batches = 0
        for z in self._iter_complete(n_queries):
            n_batches += 1
            if "d" in z:
                ds.append(z["d"])
            is_.append(z["i"])
            for key, v in json.loads(str(z["stats"])).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    agg[key] = agg.get(key, 0) + v
                else:
                    agg[key] = v
        d = np.concatenate(ds)[:n_queries] if len(ds) == n_batches else None
        return d, np.concatenate(is_)[:n_queries], agg


def streaming_certified_knn(
    db: np.ndarray,
    queries: np.ndarray,
    k: int,
    checkpoint_dir: str,
    *,
    mesh=None,
    segment_size: int = 4096,
    metric: str = "l2",
    merge: str = "allgather",
    train_tile: Optional[int] = None,
    compute_dtype=None,
    max_retries: int = 2,
    selector: str = "pallas",
    margin: int = 28,
    batch_size: Optional[int] = None,
    return_distances: bool = True,
    **certified_kwargs,
):
    """Place ``db`` once, stream ``queries`` through the certified-exact
    pipeline in resumable ``segment_size`` chunks.  ``batch_size`` is the
    pipeline's INNER device batch (``search_certified``'s knob);
    ``segment_size`` is the durable checkpoint unit.  Every certified
    tuning knob (``tile_n``, ``precision``, ``final_select``, ...)
    passes through and is echoed into the resume-guard manifest —
    finished segments computed under different knobs are a different
    run, never silently reused."""
    from knn_tpu.parallel.mesh import make_mesh
    from knn_tpu.parallel.sharded import ShardedKNN

    if mesh is None:
        mesh = make_mesh()
    program = ShardedKNN(
        db, mesh=mesh, k=k, metric=metric, merge=merge,
        train_tile=train_tile, compute_dtype=compute_dtype,
    )
    stream = StreamingCertifiedSearch(
        lambda chunk: program.search_certified(
            chunk, selector=selector, margin=margin, batch_size=batch_size,
            return_distances=return_distances, **certified_kwargs,
        ),
        k, checkpoint_dir,
        batch_size=segment_size, db_fingerprint=_fingerprint(db),
        search_config={
            "certified": True,
            "selector": selector,
            "margin": margin,
            "inner_batch_size": batch_size,
            "return_distances": return_distances,
            "metric": metric,
            "merge": merge,
            "train_tile": train_tile,
            "compute_dtype": (None if compute_dtype is None
                              else str(compute_dtype)),
            **{key: str(v) for key, v in sorted(certified_kwargs.items())},
        },
        max_retries=max_retries,
    )
    return stream.run(queries)


def streaming_knn(
    db: np.ndarray,
    queries: np.ndarray,
    k: int,
    checkpoint_dir: str,
    *,
    mesh=None,
    batch_size: int = 512,
    metric: str = "l2",
    merge: str = "allgather",
    train_tile: Optional[int] = None,
    compute_dtype=None,
    max_retries: int = 2,
) -> Tuple[np.ndarray, np.ndarray]:
    """Convenience wrapper: place ``db`` on the mesh once, stream ``queries``
    through it with checkpointing, resume from ``checkpoint_dir`` if the
    previous run was interrupted."""
    from knn_tpu.parallel.mesh import make_mesh
    from knn_tpu.parallel.sharded import ShardedKNN

    if mesh is None:
        mesh = make_mesh()
    program = ShardedKNN(
        db, mesh=mesh, k=k, metric=metric, merge=merge,
        train_tile=train_tile, compute_dtype=compute_dtype,
    )
    stream = StreamingSearch(
        program.search, k, checkpoint_dir,
        batch_size=batch_size, db_fingerprint=_fingerprint(db),
        search_config={
            "metric": metric,
            "merge": merge,
            "train_tile": train_tile,
            "compute_dtype": None if compute_dtype is None else str(compute_dtype),
        },
        max_retries=max_retries,
    )
    return stream.run(queries)
