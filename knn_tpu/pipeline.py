"""L4 driver: the reference's entire ``main()`` (knn_mpi.cpp:86-399) as a
library function — read CSVs, distribute, transductively normalize, KNN both
query sets, score validation, write ``Test_label.csv``, report time.

Reference flow reproduced (SURVEY.md §1 data-flow):
  ingest        <- rank-specialized CSV readers        knn_mpi.cpp:154-222
  distribute    <- Bcast/Scatter placement             :224-227  (shardings)
  normalize     <- joint extrema + Allreduce + rescale :229-306  (pmin/pmax)
  knn val/test  <- distance/sort/vote per shard        :308-393  (SPMD program)
  score         <- acc_calc on gathered val labels     :342-349
  output        <- Test_label.csv writer               :385-393
  timing        <- barrier-fenced Wtime pair           :133-134,395-398
                   (upgraded to per-phase fences, utils.timing)

Backends: ``jax`` (the TPU-native path, any mesh shape) and ``native`` (the
C++ CPU parity oracle, knn_tpu.native).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from knn_tpu.data.csv_io import read_labeled_csv, read_unlabeled_csv, write_labels
from knn_tpu.utils.config import JobConfig
from knn_tpu.utils.timing import PhaseTimer


@dataclass
class JobResult:
    """Everything the reference prints or writes, plus structured metrics."""

    test_labels: np.ndarray
    val_labels: Optional[np.ndarray]
    val_accuracy: Optional[float]
    phase_times: Dict[str, float]
    total_time: float
    n_train: int
    n_test: int
    n_val: int
    config: JobConfig
    #: ``--mode certified`` observability: how many queries certified exactly
    #: on the fast path vs fell back to the widened re-select (None outside
    #: certified mode).  Keys: "certified", "fallback_queries".
    certified_stats: Optional[Dict[str, int]] = None
    #: ``--serve-buckets`` observability (None outside serving mode): the
    #: bucket ladder, per-bucket compile/dispatch counts, and per-request
    #: latency percentiles (knn_tpu.serving.ServingEngine.stats).
    serving_stats: Optional[dict] = None

    @property
    def queries_per_sec(self) -> float:
        n = self.n_test + self.n_val
        return n / self.total_time if self.total_time > 0 else float("inf")

    def metrics(self) -> dict:
        """Structured per-run JSON — the metrics/observability subsystem the
        reference lacks (SURVEY.md §5: cout only, knn_mpi.cpp:348,398)."""
        out = {
            "val_accuracy": self.val_accuracy,
            "queries_per_sec": self.queries_per_sec,
            "total_time_s": self.total_time,
            "phase_times_s": self.phase_times,
            "n_train": self.n_train,
            "n_test": self.n_test,
            "n_val": self.n_val,
            "config": dataclasses.asdict(self.config),
        }
        if self.certified_stats is not None:
            out["certified_stats"] = self.certified_stats
        if self.serving_stats is not None:
            out["serving"] = self.serving_stats
        # the unified telemetry view (knn_tpu.obs): phase histograms,
        # compile events, certified quality counters, serving series —
        # everything above is a per-run slice; this is the process-wide
        # registry the exporters scrape.  Absent when KNN_TPU_OBS=0, so
        # pre-obs consumers see the exact shape they always did.
        from knn_tpu import obs

        if obs.enabled():
            out["obs"] = obs.compact_snapshot()
            # the judgment layer over the snapshot: one burn-rate
            # evaluation pass per metrics() render (knn_tpu.obs.slo)
            out["slo"] = obs.slo_report()
        return out

    def metrics_json(self) -> str:
        return json.dumps(self.metrics(), indent=2)


def _infer_num_classes(cfg: JobConfig, *label_arrays) -> int:
    if cfg.num_classes is not None:
        return cfg.num_classes
    hi = 0
    for a in label_arrays:
        if a is not None and a.size:
            hi = max(hi, int(a.max()))
    return hi + 1


def _accuracy(pred: np.ndarray, real: np.ndarray) -> float:
    """``acc_calc`` (knn_mpi.cpp:69-84)."""
    return float(np.mean(pred == real))


def _np_minmax_apply(x: np.ndarray, lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """Host-side rescale with the constant-dim passthrough guard
    (knn_mpi.cpp:284) — applied on host so the full arrays never
    materialize on a single device."""
    rng = hi - lo
    safe = np.where(rng != 0, rng, 1.0)
    return np.where(rng != 0, (x - lo) / safe, x).astype(np.float32)


def _run_jax(cfg: JobConfig, timer: PhaseTimer, train, train_labels, test, val,
             val_labels_real, mesh):
    from knn_tpu.parallel.mesh import make_mesh
    from knn_tpu.parallel.sharded import ShardedKNN, sharded_minmax

    if mesh is None:
        mesh = make_mesh(cfg.query_shards, cfg.db_shards)

    if cfg.normalize:
        with timer.phase("normalize"):
            # extrema via the distributed pmin/pmax reduction (the
            # reference's Allreduce pair); the rescale applies on host so
            # no full array ever lands on one device
            present = [a for a in (train, test, val) if a is not None]
            lo, hi = sharded_minmax(present, mesh=mesh)
            lo, hi = np.asarray(lo), np.asarray(hi)
            train = _np_minmax_apply(train, lo, hi)
            test = _np_minmax_apply(test, lo, hi)
            if val is not None:
                val = _np_minmax_apply(val, lo, hi)

    num_classes = _infer_num_classes(cfg, train_labels, val_labels_real)

    with timer.phase("distribute"):
        # Database padded on host, then placed shard-by-shard — once;
        # every query batch reuses the placement and compiled program.
        program = ShardedKNN(
            train,
            mesh=mesh,
            k=cfg.k,
            metric=cfg.metric,
            merge=cfg.merge,
            train_tile=cfg.train_tile,
            compute_dtype=cfg.compute_dtype,
            labels=train_labels,
            num_classes=num_classes,
        )

    certified_stats = {"fallback_queries": 0, "certified": 0}

    engine = None
    if cfg.serve_buckets is not None:
        # shape-bucketed serving (knn_tpu.serving): variable-size chunks
        # route through precompiled per-bucket executables — warmup pays
        # every compile up front, the job loop never compiles again, and
        # per-bucket compile counts + latency percentiles land in
        # JobResult.metrics()["serving"]
        from knn_tpu.serving.buckets import parse_buckets
        from knn_tpu.serving.engine import ServingEngine

        with timer.phase("serving_warmup"):
            engine = ServingEngine(program, buckets=parse_buckets(cfg.serve_buckets))
            engine.warmup(ops=("predict",))

    def classify(queries):
        n = queries.shape[0]
        bs = cfg.batch_size or n
        out = []
        for start in range(0, n, bs):
            chunk = queries[start : start + bs]
            take = min(bs, n - start)
            if cfg.mode == "certified":
                # real rows only: zero-pad queries would pollute the
                # certificate stats (and can spuriously fall back)
                labels_out, stats = program.predict_certified(
                    chunk[:take], selector=cfg.selector,
                    tune_cache=cfg.tune_cache,
                    precision=cfg.pallas_precision,
                )
                for key, v in stats.items():  # incl. host_exact_queries
                    if isinstance(v, (int, np.integer)):
                        certified_stats[key] = certified_stats.get(key, 0) + v
                    else:
                        # non-additive observability (the resolved
                        # pallas_knobs / tuning provenance): keep as-is
                        certified_stats[key] = v
                out.append(np.asarray(labels_out))
            elif engine is not None:
                # the engine pads to its bucket ladder itself; the raw
                # (possibly short tail) chunk hits a precompiled bucket
                out.append(engine.predict(chunk))
            else:
                if chunk.shape[0] < bs:  # pad the tail so XLA sees one shape
                    chunk = np.pad(chunk, ((0, bs - chunk.shape[0]), (0, 0)))
                out.append(np.asarray(program.predict(chunk))[:take])
        return np.concatenate(out)

    val_pred = None
    if val is not None:
        with timer.phase("knn_val"):
            val_pred = classify(val)
    with timer.phase("knn_test"):
        test_pred = classify(test)
    serving_stats = None
    if engine is not None:
        serving_stats = {"max_wait_ms": cfg.max_wait_ms, **engine.stats()}
    return test_pred, val_pred, (
        certified_stats if cfg.mode == "certified" else None
    ), serving_stats


def _run_native(cfg: JobConfig, timer: PhaseTimer, train, train_labels, test, val,
                val_labels_real):
    try:
        from knn_tpu import native
    except ImportError:
        native = None
    if native is None or not native.available():
        raise RuntimeError(
            "native backend requested but the C++ library is not built; "
            "run `make -C knn_tpu/native` (see knn_tpu/native/README.md)"
        )
    num_classes = _infer_num_classes(cfg, train_labels, val_labels_real)
    arrays = [a for a in (train, test, val) if a is not None]
    if cfg.normalize:
        with timer.phase("normalize"):
            lo, hi = native.minmax_stats(arrays)
            train = native.minmax_apply(train, lo, hi)
            test = native.minmax_apply(test, lo, hi)
            if val is not None:
                val = native.minmax_apply(val, lo, hi)
    val_pred = None
    if val is not None:
        with timer.phase("knn_val"):
            val_pred = native.knn_predict(
                train, train_labels, val, k=cfg.k, num_classes=num_classes,
                metric=cfg.metric, num_threads=cfg.num_threads,
            )
    with timer.phase("knn_test"):
        test_pred = native.knn_predict(
            train, train_labels, test, k=cfg.k, num_classes=num_classes,
            metric=cfg.metric, num_threads=cfg.num_threads,
        )
    return test_pred, val_pred


def run_job(cfg: JobConfig, *, mesh=None) -> JobResult:
    """Run the full reference job under ``cfg``; returns what the reference
    prints/writes plus per-phase timings and throughput."""
    from knn_tpu import obs

    obs.install_compile_hook()  # count+seconds of every XLA compile
    timer = PhaseTimer()

    with timer.phase("ingest"):
        train, train_labels = read_labeled_csv(cfg.train_file, cfg.dim)
        test = read_unlabeled_csv(cfg.test_file, cfg.dim or train.shape[1])
        val, val_labels_real = (None, None)
        if cfg.validation:
            val, val_labels_real = read_labeled_csv(cfg.val_file, cfg.dim)
    if cfg.k > train.shape[0]:
        raise ValueError(f"k={cfg.k} > n_train={train.shape[0]}")
    # Label range check, applied identically for both backends (the jax vote
    # would silently drop out-of-range labels, the native one rejects them —
    # the reference OOB-writes its vote array instead, knn_mpi.cpp:330).
    if train_labels.size and train_labels.min() < 0:
        raise ValueError(f"negative train label {int(train_labels.min())}")
    if cfg.num_classes is not None and train_labels.size and (
        train_labels.max() >= cfg.num_classes
    ):
        raise ValueError(
            f"train label {int(train_labels.max())} outside [0, {cfg.num_classes})"
        )

    if cfg.backend == "native":
        test_pred, val_pred = _run_native(
            cfg, timer, train, train_labels, test, val, val_labels_real
        )
        certified_stats = None
        serving_stats = None
    else:
        test_pred, val_pred, certified_stats, serving_stats = _run_jax(
            cfg, timer, train, train_labels, test, val, val_labels_real, mesh
        )

    val_acc = None
    if val_pred is not None:
        val_acc = _accuracy(val_pred, val_labels_real)

    with timer.phase("output"):
        write_labels(cfg.output_file, test_pred)

    return JobResult(
        test_labels=np.asarray(test_pred),
        val_labels=None if val_pred is None else np.asarray(val_pred),
        val_accuracy=val_acc,
        phase_times=timer.phases,
        total_time=timer.total,
        n_train=train.shape[0],
        n_test=test.shape[0],
        n_val=0 if val is None else val.shape[0],
        config=cfg,
        certified_stats=certified_stats,
        serving_stats=serving_stats,
    )
