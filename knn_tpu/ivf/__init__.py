"""Approximate-first IVF tier with a certified escape hatch: k-means
list-major placement probed by the existing streaming machinery, a
per-query residual certificate that DETECTS probe misses, and the
exact fallback that repairs them (docs/PERF.md "IVF tier & certified
recall").  ``knn_tpu.ivf.artifact`` is importable jax-free."""

from knn_tpu.ivf.index import (  # noqa: F401
    IVFIndex,
    IVFServingEngine,
    SELECTORS,
)
from knn_tpu.ivf.kmeans import (  # noqa: F401
    KMeansResult,
    quantize_centroids,
    train_kmeans,
)
