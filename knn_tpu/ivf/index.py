"""The approximate-first IVF tier with a certified escape hatch.

Brute force streams every db byte past every query; the roofline says
the winning configs are hbm_bound, so the only way past the calibrated
ceiling is to stream fewer bytes.  This tier prunes the stream with an
inverted file — and unlike every off-the-shelf IVF, a per-query
certificate DETECTS when the probe missed and repairs it with the
existing exact fallback, so recall@k is measured and gateable, never
silently lost.

How the pieces map onto machinery that already exists:

- **Coarse quantizer** (:mod:`knn_tpu.ivf.kmeans`): seeded Lloyd, SPMD
  assign via the sharded k=1 search, host f64 segment-mean update.
- **List-major placement**: corpus rows permuted into
  centroid-contiguous blocks.  A search gathers ONLY the probed lists'
  extents (plus their delta tails) into one segment, pads it to a fixed
  ladder rung, and feeds the UNMODIFIED host-tier segment program
  (:func:`knn_tpu.parallel.sharded.segment_search_program`) — the
  traced ``n_valid`` operand masks the pad, so probing shrinks
  streamed db bytes with no new kernel and no recompile per probe set.
  ``selector="pallas"`` runs the same gathered block through
  :func:`knn_tpu.ops.pallas_knn.knn_search_pallas` (streaming/fused ×
  f32/bf16x3/int8), equally unmodified.
- **Certificate** (the PR 3 bound extended to centroid residuals): for
  any row ``x`` in an unprobed list ``l`` with centroid ``c_l`` and
  residual radius ``r_l = max ||x - c_l||``, the triangle inequality
  gives ``||q - x|| >= ||q - c_l|| - r_l``.  If the refined k-th
  distance beats that bound for EVERY unprobed non-empty list (and the
  within-block float32 tolerance check passes), the probed answer is
  PROVABLY the exact answer.  Otherwise the query is repaired by an
  exact f64 re-score of all live rows (``ops.refine``) — so the final
  ``(d, i)`` is ALWAYS anchored in :func:`knn_tpu.ops.refine.
  refine_exact` over the canonical corpus, which makes results
  selector-, precision-, and kernel-independent by construction
  (``nprobe == ncentroids`` reproduces exact brute force bitwise).
- **Mutability**: per-list delta tails absorb inserts (PR 13
  discipline: epoch visibility, id-based tombstones, budgeted refusal),
  and compaction re-clusters the survivors on a background thread with
  an atomic snapshot swap (docs/INDEX.md).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from knn_tpu import obs
from knn_tpu.index.artifact import MutationBudgetError
from knn_tpu.ivf.kmeans import train_kmeans
from knn_tpu.ops.certified import certification_tolerance
from knn_tpu.ops.refine import refine_exact, refine_shared_exact

#: coarse selectors this tier accepts: "exact" routes the gathered
#: block through the host-tier segment program (compute-dtype f32, the
#: counted-certificate tolerance below assumes it); "pallas" routes it
#: through knn_search_pallas (which certifies itself over the block,
#: any precision/kernel)
SELECTORS = ("exact", "pallas")

#: relative slack on the unprobed-list lower bound: the certificate
#: compares f64 values computed from exactly-representable f32 inputs,
#: so a sliver of multiplicative headroom dwarfs the f64 rounding while
#: erring ONLY toward extra fallback (never a wrong certification)
_BOUND_SLACK = 1e-9

_ENV_NPROBE = "KNN_TPU_IVF_NPROBE"
_ENV_NCENTROIDS = "KNN_TPU_IVF_NCENTROIDS"
_ENV_TRAIN_ITERS = "KNN_TPU_IVF_TRAIN_ITERS"
_ENV_SEED = "KNN_TPU_IVF_SEED"


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    return default if raw in (None, "") else int(raw)


class _IVFSnapshot:
    """One immutable view of the index: searches pin a snapshot, so
    compaction swaps are atomic from a request's point of view."""

    __slots__ = (
        "epoch", "ncentroids", "centroids", "cent64", "residuals",
        "list_base_pos", "list_sizes", "tail_assign", "n_base",
        "all_rows", "all_ids", "live_mask", "live_positions", "n_live",
        "_pos_cache", "_norm2",
    )

    def __init__(self, **kw):
        for name in self.__slots__:
            setattr(self, name, kw.get(name))
        self._pos_cache = {}
        self._norm2 = None

    @property
    def n_all(self) -> int:
        return self.all_rows.shape[0]

    def norm2(self) -> np.ndarray:
        """[n_all] f64 squared row norms (lazy, shared by every group's
        within-block tolerance)."""
        if self._norm2 is None:
            r = self.all_rows.astype(np.float64)
            self._norm2 = np.einsum("nd,nd->n", r, r)
        return self._norm2

    def positions_for(self, key: Tuple[int, ...]) -> np.ndarray:
        """Sorted canonical positions of every LIVE row in the probed
        lists ``key`` — base extents plus matching delta-tail rows,
        tombstones filtered.  Sorted ascending so block-local
        lexicographic tie order equals canonical tie order."""
        hit = self._pos_cache.get(key)
        if hit is not None:
            return hit
        parts = [self.list_base_pos[l] for l in key]
        if self.tail_assign.size:
            sel = np.isin(self.tail_assign, np.asarray(key, np.int64))
            parts.append(self.n_base + np.flatnonzero(sel))
        pos = (np.concatenate(parts) if parts
               else np.empty(0, np.int64)).astype(np.int64)
        pos = np.sort(pos[self.live_mask[pos]])
        self._pos_cache[key] = pos
        return pos


class IVFIndex:
    """A mutable, certified IVF placement over one canonical corpus.

    ``search_certified`` returns ``(d, ids, stats)`` with ``d`` the
    exact squared-L2 float64 distances (``return_sqrt=True`` for true
    Euclidean) — exact for EVERY query, because certified probes are
    proven exact and flagged probes are repaired.  L2 metric only: the
    residual bound is a Euclidean triangle inequality.
    """

    def __init__(
        self,
        train,
        ids=None,
        *,
        mesh,
        k: int,
        ncentroids: Optional[int] = None,
        nprobe: Optional[int] = None,
        train_iters: Optional[int] = None,
        seed: Optional[int] = None,
        metric: str = "l2",
        margin: int = 8,
        train_tile: Optional[int] = None,
        seg_min_rows: int = 256,
        delta_max_rows: int = 65536,
        compact_tail_rows: Optional[int] = None,
        compact_tombstones: Optional[int] = None,
    ):
        if metric.lower() != "l2":
            raise ValueError(
                f"IVFIndex supports metric='l2' only (the residual "
                f"certificate is a Euclidean triangle inequality), got "
                f"{metric!r}")
        base = np.ascontiguousarray(np.asarray(train, np.float32))
        if base.ndim != 2:
            raise ValueError(f"train must be [N, D], got {base.shape}")
        n = base.shape[0]
        self.mesh = mesh
        self.metric = "l2"
        self.dim = int(base.shape[1])
        self.k = int(k)
        self.margin = int(margin)
        self.train_tile = train_tile
        self.ncentroids = int(ncentroids) if ncentroids is not None else (
            _env_int(_ENV_NCENTROIDS, max(1, int(round(n ** 0.5)))))
        self.ncentroids = max(1, min(self.ncentroids, n))
        self.nprobe = int(nprobe) if nprobe is not None else (
            _env_int(_ENV_NPROBE, max(1, self.ncentroids // 4)))
        self.nprobe = max(1, min(self.nprobe, self.ncentroids))
        self.train_iters = int(train_iters) if train_iters is not None \
            else _env_int(_ENV_TRAIN_ITERS, 5)
        self.seed = int(seed) if seed is not None \
            else _env_int(_ENV_SEED, 0)
        if self.k > n:
            raise ValueError(f"k={self.k} > n={n}")
        ids_arr = (np.arange(n, dtype=np.int64) if ids is None
                   else np.asarray(ids, np.int64).reshape(-1))
        if ids_arr.shape[0] != n:
            raise ValueError(f"{ids_arr.shape[0]} ids for {n} rows")
        if np.unique(ids_arr).shape[0] != n:
            raise ValueError("ids must be unique")
        from knn_tpu.parallel.mesh import db_topology

        hosts, chips = db_topology(mesh)
        self._db_shards = hosts * chips
        self._seg_min = int(seg_min_rows)
        self._delta_max = int(delta_max_rows)
        self._compact_tail_rows = compact_tail_rows
        self._compact_tombstones = compact_tombstones
        self._lock = threading.Condition()
        self._compact_lock = threading.Lock()
        self._closed = False
        self._compactor_t: Optional[threading.Thread] = None
        self._compactions = 0
        self._last_compaction: Optional[dict] = None
        self._last_search: Optional[dict] = None
        self.epoch = 0
        self._tail_parts: list = []
        self._tail_id_parts: list = []
        self._tail_assign_parts: list = []
        self._tail_len = 0
        self._tombstones: set = set()
        self._snap_cache: Optional[_IVFSnapshot] = None
        self._train_base(base, ids_arr)
        self._live = set(ids_arr.tolist())
        # health/statusz registration (weak; no-op when obs disabled):
        # surfaces epoch/tail/tombstone state and the drift sketches
        obs.health.register_index(self)

    # -- placement ---------------------------------------------------------
    def _train_base(self, base: np.ndarray, base_ids: np.ndarray) -> None:
        """(Re)cluster ``base`` and install it as the list-major
        placement.  Caller holds no lock on first build; compaction
        calls this off-path and installs under the lock itself."""
        km = train_kmeans(base, self.ncentroids, mesh=self.mesh,
                          iters=self.train_iters, seed=self.seed,
                          train_tile=self.train_tile)
        # stable sort -> centroid-contiguous extents whose in-extent
        # order preserves canonical (insertion) order, so block-local
        # tie ranking equals canonical tie ranking
        perm = np.argsort(km.assign, kind="stable").astype(np.int64)
        starts = np.zeros(self.ncentroids + 1, np.int64)
        np.cumsum(km.counts, out=starts[1:])
        self._base = base
        self._base_ids = base_ids
        self._centroids = km.centroids
        self._residuals = km.residuals.copy()
        self._base_assign = km.assign
        self._list_base_pos = tuple(
            perm[starts[l]:starts[l + 1]]
            for l in range(self.ncentroids))
        self._base_counts = km.counts.copy()
        # train-time drift baseline (knn_tpu.obs.drift): built ONLY
        # when telemetry is on — KNN_TPU_OBS=0 means no sketches at
        # all, the pinned obs-off contract
        self._drift = None
        if obs.enabled():
            from knn_tpu.obs.drift import QueryDriftMonitor

            norms = np.sqrt(np.einsum(
                "nd,nd->n", base.astype(np.float64),
                base.astype(np.float64)))
            self._drift = QueryDriftMonitor(
                train_norms=norms, assign_baseline=km.counts)

    def _assign_host(self, rows: np.ndarray) -> np.ndarray:
        """Nearest-centroid assignment for delta-tail rows, host f64
        with lexicographic ties — any assignment is VALID for the
        certificate as long as the residual radius covers it, which
        :meth:`insert` maintains."""
        r64 = rows.astype(np.float64)
        c64 = self._centroids.astype(np.float64)
        d = ((r64[:, None, :] - c64[None, :, :]) ** 2).sum(-1)
        return np.argmin(d, axis=1).astype(np.int64)

    def _snapshot(self) -> _IVFSnapshot:
        with self._lock:
            if self._snap_cache is not None:
                return self._snap_cache
            n_base = self._base.shape[0]
            tail = (np.concatenate(self._tail_parts)
                    if self._tail_parts
                    else np.empty((0, self.dim), np.float32))
            tail_ids = (np.concatenate(self._tail_id_parts)
                        if self._tail_id_parts
                        else np.empty(0, np.int64))
            tail_assign = (np.concatenate(self._tail_assign_parts)
                           if self._tail_assign_parts
                           else np.empty(0, np.int64))
            all_rows = np.concatenate([self._base, tail])
            all_ids = np.concatenate([self._base_ids, tail_ids])
            live_mask = np.ones(all_rows.shape[0], bool)
            if self._tombstones:
                dead = np.isin(all_ids,
                               np.fromiter(self._tombstones, np.int64,
                                           len(self._tombstones)))
                live_mask &= ~dead
            live_positions = np.flatnonzero(live_mask).astype(np.int64)
            sizes = self._base_counts + np.bincount(
                tail_assign, minlength=self.ncentroids)
            snap = _IVFSnapshot(
                epoch=self.epoch,
                ncentroids=self.ncentroids,
                centroids=self._centroids,
                cent64=self._centroids.astype(np.float64),
                residuals=self._residuals.copy(),
                list_base_pos=self._list_base_pos,
                list_sizes=sizes,
                tail_assign=tail_assign,
                n_base=n_base,
                all_rows=all_rows,
                all_ids=all_ids,
                live_mask=live_mask,
                live_positions=live_positions,
                n_live=int(live_positions.shape[0]),
            )
            self._snap_cache = snap
            return snap

    # -- rungs -------------------------------------------------------------
    def _seg_rung(self, rows: int, m: int) -> int:
        """Smallest segment ladder rung holding ``rows``: rungs double
        from a floor that guarantees every db shard can rank ``m`` rows
        and divides evenly across shards — so steady-state probing hits
        a handful of compiled shapes, never one per probe set."""
        floor = max(self._seg_min, m * self._db_shards)
        floor = -(-floor // self._db_shards) * self._db_shards
        cap = floor
        while cap < rows:
            cap *= 2
        return cap

    def _q_rung(self, rows: int) -> int:
        from knn_tpu.parallel.mesh import QUERY_AXIS

        cap = int(self.mesh.shape[QUERY_AXIS])
        while cap < rows:
            cap *= 2
        return cap

    # -- search ------------------------------------------------------------
    def _probe(self, q64: np.ndarray, snap: _IVFSnapshot, nprobe: int):
        """(probes [Q, P] sorted list ids, unprobed_lb [Q] f64,
        nearest [Q] int64): the probe pick, each query's lower bound
        over every UNPROBED non-empty list — ``min_l (||q - c_l|| -
        r_l)`` — computed in f64 with the direct-difference form (no
        cancellation), and the nearest centroid (the drift sketch's
        assignment stream)."""
        n_q = q64.shape[0]
        c = snap.ncentroids
        cd = np.empty((n_q, c))
        for lo in range(0, n_q, 128):
            diff = q64[lo:lo + 128, None, :] - snap.cent64[None, :, :]
            cd[lo:lo + 128] = np.sqrt(np.einsum("qcd,qcd->qc", diff, diff))
        order = np.lexsort(
            (np.broadcast_to(np.arange(c), cd.shape), cd), axis=-1)
        probes = np.sort(order[:, :nprobe], axis=-1)
        lb = cd - snap.residuals[None, :]
        np.put_along_axis(lb, order[:, :nprobe], np.inf, axis=-1)
        lb[:, snap.list_sizes == 0] = np.inf
        return probes, lb.min(axis=-1), order[:, 0]

    def _coarse_counted(self, q_grp: np.ndarray, pos: np.ndarray,
                        snap: _IVFSnapshot, kk: int, m: int):
        """Gathered-block coarse pass through the UNMODIFIED host-tier
        segment program (rung-padded, traced n_valid), refined to exact
        f64 finals; returns (d_ref, p_ref, complete) where ``complete``
        certifies the refined top-kk is the exact block top-kk (the
        f32-tolerance exclusion bound of PR 3, applied to the block).

        Queries whose exclusion bound fails (an f32-cancellation
        artifact of the coarse pass, NOT a probe miss) escalate WITHIN
        the block: every gathered row re-scores in f64, which is
        complete by construction and streams no bytes beyond the rows
        the probe already gathered — the full-corpus fallback stays
        reserved for genuine residual-bound failures."""
        import jax.numpy as jnp

        from knn_tpu.ops.pallas_knn import PAD_VAL
        from knn_tpu.parallel.collectives import replicate, shard
        from knn_tpu.parallel.mesh import QUERY_AXIS, db_axes
        from knn_tpu.parallel.sharded import (
            _INT_SENTINEL, segment_search_program)

        real = int(pos.shape[0])
        n_g = q_grp.shape[0]
        rung = self._seg_rung(real, m)
        prog = segment_search_program(
            self.mesh, m, self.metric, train_tile=self.train_tile,
            compute_dtype=jnp.float32)
        seg = np.full((rung, self.dim), PAD_VAL, np.float32)
        seg[:real] = snap.all_rows[pos]
        q_pad = np.zeros((self._q_rung(n_g), self.dim), np.float32)
        q_pad[:n_g] = q_grp
        qp = shard(q_pad, self.mesh, QUERY_AXIS)
        tp = shard(seg, self.mesh, db_axes(self.mesh))
        nv = replicate(np.asarray([real], np.int32), self.mesh)
        d32, i32 = prog(qp, tp, nv)
        d32 = np.asarray(d32)[:n_g]
        i32 = np.asarray(i32)[:n_g]
        valid = i32 != _INT_SENTINEL
        cand = np.where(valid, pos[np.clip(i32, 0, real - 1)], snap.n_all)
        d_ref, p_ref = refine_exact(snap.all_rows, q_grp, cand, kk)
        if real <= m:
            # every block row was a candidate: complete by construction
            return d_ref, p_ref, np.ones(n_g, bool)
        # rows outside the coarse top-m have f32 distance >= d32[:, m-1];
        # the tolerance converts that into an f64 exclusion bound
        tol = certification_tolerance(
            q_grp, snap.all_rows,
            db_norm_max=float(snap.norm2()[pos].max()))
        outsider_lb = d32[:, m - 1].astype(np.float64) - tol
        complete = d_ref[:, kk - 1] < outsider_lb
        bad = np.flatnonzero(~complete)
        if bad.size:
            d_ref[bad], p_ref[bad] = refine_shared_exact(
                snap.all_rows, q_grp[bad], pos, kk)
            complete[bad] = True
        return d_ref, p_ref, complete

    def _coarse_pallas(self, q_grp: np.ndarray, pos: np.ndarray,
                       snap: _IVFSnapshot, kk: int, margin: int,
                       pallas_kw: dict):
        """Gathered-block coarse pass through the UNMODIFIED Pallas
        wrapper (streaming/fused × f32/bf16x3/int8): its own certificate
        + fallback make the block top-kk exact, so the re-refine here
        only re-anchors values/ties to the canonical f64 form."""
        from knn_tpu.ops.pallas_knn import knn_search_pallas

        _, i_c, _stats = knn_search_pallas(
            q_grp, snap.all_rows[pos], kk, margin=margin, **pallas_kw)
        cand = pos[np.asarray(i_c)]
        d_ref, p_ref = refine_exact(snap.all_rows, q_grp, cand, kk)
        return d_ref, p_ref, np.ones(q_grp.shape[0], bool)

    def search_certified(
        self,
        queries,
        *,
        k: Optional[int] = None,
        nprobe: Optional[int] = None,
        selector: str = "exact",
        margin: Optional[int] = None,
        precision: str = "highest",
        kernel: str = "tiled",
        tile_n: Optional[int] = None,
        block_q: Optional[int] = None,
        return_sqrt: bool = False,
    ):
        """(d [Q, k] f64, ids [Q, k] int64, stats): EXACT nearest
        neighbors of the live corpus — probed lists answer, the
        residual certificate checks, flagged queries repair via the
        exact f64 fallback.  See the module docstring for the proof
        obligation each step discharges."""
        if selector not in SELECTORS:
            raise ValueError(
                f"selector {selector!r} not in {SELECTORS}")
        q = np.ascontiguousarray(np.asarray(queries, np.float32))
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(
                f"queries shape {q.shape} incompatible with dim "
                f"{self.dim}")
        k = self.k if k is None else int(k)
        margin = self.margin if margin is None else int(margin)
        snap = self._snapshot()
        if snap.n_live < k:
            raise ValueError(
                f"k={k} exceeds live rows {snap.n_live}")
        nprobe_r = self.nprobe if nprobe is None else int(nprobe)
        nprobe_r = max(1, min(nprobe_r, snap.ncentroids))
        n_q = q.shape[0]
        t0 = time.perf_counter()
        q64 = q.astype(np.float64)
        probes, unprobed_lb, nearest = self._probe(q64, snap, nprobe_r)
        if self._drift is not None:
            self._drift.observe(
                norms=np.sqrt(np.einsum("qd,qd->q", q64, q64)),
                assignments=nearest)
        d_out = np.full((n_q, k), np.inf)
        pos_out = np.full((n_q, k), snap.n_all, np.int64)
        flagged = np.zeros(n_q, bool)
        rows_gathered = 0
        m = k + margin
        pallas_kw = {"precision": precision, "kernel": kernel}
        if tile_n is not None:
            pallas_kw["tile_n"] = tile_n
        if block_q is not None:
            pallas_kw["block_q"] = block_q
        groups: dict = {}
        for qi in range(n_q):
            groups.setdefault(tuple(probes[qi].tolist()), []).append(qi)
        # certificate-margin telemetry: how close each probed answer
        # came to the unprobed-list bound (1.0 = miles of headroom,
        # ~0 = one insert away from fallback, < 0 = the bound failed)
        margins: list = [] if obs.enabled() else None
        for key, members in groups.items():
            qi = np.asarray(members, np.int64)
            pos = snap.positions_for(key)
            rows_gathered += int(pos.shape[0]) * qi.shape[0]
            if pos.shape[0] < k:
                flagged[qi] = True  # probe can't even fill k: repair
                continue
            q_grp = q[qi]
            if selector == "pallas":
                d_ref, p_ref, complete = self._coarse_pallas(
                    q_grp, pos, snap, k, margin, pallas_kw)
            else:
                d_ref, p_ref, complete = self._coarse_counted(
                    q_grp, pos, snap, k, m)
            d_out[qi] = d_ref
            pos_out[qi] = p_ref
            s_k = np.sqrt(d_ref[:, k - 1])
            lb = unprobed_lb[qi]
            bound_ok = s_k < lb * (1.0 - _BOUND_SLACK)
            flagged[qi] = ~(complete & bound_ok)
            if margins is not None:
                fin = np.isfinite(lb)
                if fin.any():
                    margins.extend(
                        ((lb[fin] - s_k[fin])
                         / np.maximum(np.abs(lb[fin]), 1e-30)).tolist())
        if margins:
            obs.histogram(obs.names.CERTIFIED_MARGIN,
                          path="ivf").observe_many(margins)
        n_bad = int(flagged.sum())
        misses = 0
        recall_sum = float(n_q - n_bad)  # certified queries: exactly 1.0
        if n_bad:
            bad = np.flatnonzero(flagged)
            d_fb, p_fb = refine_shared_exact(
                snap.all_rows, q[bad], snap.live_positions, k)
            for row, qi in enumerate(bad):
                before = pos_out[qi][pos_out[qi] < snap.n_all]
                hit = int(np.isin(p_fb[row], before).sum())
                recall_sum += hit / k
                if hit < k:
                    misses += 1
            d_out[bad] = d_fb
            pos_out[bad] = p_fb
        ids_out = snap.all_ids[
            np.clip(pos_out, 0, snap.n_all - 1)]
        wall = time.perf_counter() - t0
        stats = self._search_stats(
            snap, n_q=n_q, k=k, nprobe=nprobe_r, selector=selector,
            precision=precision, n_groups=len(groups),
            rows_gathered=rows_gathered, n_bad=n_bad, misses=misses,
            recall_sum=recall_sum, wall=wall)
        if return_sqrt:
            d_out = np.sqrt(d_out)
        return d_out, ids_out, stats

    def _search_stats(self, snap, *, n_q, k, nprobe, selector, precision,
                      n_groups, rows_gathered, n_bad, misses, recall_sum,
                      wall) -> dict:
        from knn_tpu.obs.roofline import db_operand_nbytes

        prec = precision if precision else "default"
        per_row = sum(db_operand_nbytes(1, self.dim, prec).values())
        brute_b = float(n_q) * snap.n_live * per_row
        probed_b = float(rows_gathered) * per_row
        stats = {
            "epoch": snap.epoch,
            "queries": n_q,
            "k": k,
            "ncentroids": snap.ncentroids,
            "nprobe": nprobe,
            "selector": selector,
            "groups": n_groups,
            "certified_queries": n_q - n_bad,
            "fallback_queries": n_bad,
            "fallback_rate": n_bad / n_q if n_q else 0.0,
            "genuine_misses": misses,
            "recall_at_k": recall_sum / n_q if n_q else 1.0,
            "rows_gathered": rows_gathered,
            "probe_fraction": (rows_gathered / (n_q * snap.n_live)
                               if n_q and snap.n_live else 0.0),
            "bytes_streamed_ratio": (probed_b / brute_b
                                     if brute_b else 0.0),
            "wall_s": round(wall, 6),
        }
        if obs.enabled():
            # the per-search quality stats, as scrapable gauges beside
            # the dict the caller gets (satellite: registry export)
            for name, key in (
                (obs.names.IVF_FALLBACK_RATE, "fallback_rate"),
                (obs.names.IVF_RECALL_AT_K, "recall_at_k"),
                (obs.names.IVF_PROBE_FRACTION, "probe_fraction"),
                (obs.names.IVF_BYTES_STREAMED_RATIO,
                 "bytes_streamed_ratio"),
            ):
                obs.gauge(name, selector=selector).set(stats[key])
            from knn_tpu.obs.drift import index_health

            index_health(snap.list_sizes,
                         int(snap.tail_assign.shape[0]),
                         snap.n_all, snap.n_live)
        with self._lock:
            self._last_search = stats
        return stats

    # -- mutation ----------------------------------------------------------
    def insert(self, vectors, ids) -> dict:
        """Append rows to the probed tier's delta tails (by nearest
        centroid, residual radius widened to keep the certificate
        sound).  Same contract as MutableIndex.insert: epoch
        visibility, unique fresh ids, budgeted refusal."""
        v = np.ascontiguousarray(np.asarray(vectors, np.float32))
        if v.ndim != 2 or v.shape[1] != self.dim:
            raise ValueError(
                f"vectors must be [N, {self.dim}], got {v.shape}")
        ids_arr = np.asarray(ids, dtype=np.int64).reshape(-1)
        if ids_arr.shape[0] != v.shape[0]:
            raise ValueError(
                f"{ids_arr.shape[0]} ids for {v.shape[0]} rows")
        if np.unique(ids_arr).shape[0] != ids_arr.shape[0]:
            raise ValueError("insert ids must be unique")
        with self._lock:
            for i in ids_arr.tolist():
                if i in self._live:
                    raise ValueError(f"id {i} is already live")
                if i in self._tombstones:
                    raise ValueError(
                        f"id {i} was deleted this epoch; compact() "
                        f"before reusing the id")
            if self._tail_len + v.shape[0] > self._delta_max:
                raise MutationBudgetError(
                    f"delta tail full: {self._tail_len} + {v.shape[0]} "
                    f"rows exceeds delta_max_rows={self._delta_max}; "
                    f"compact()")
            assign = self._assign_host(v)
            diff = v.astype(np.float64) - \
                self._centroids.astype(np.float64)[assign]
            dist = np.sqrt(np.einsum("nd,nd->n", diff, diff))
            np.maximum.at(self._residuals, assign, dist)
            self._tail_parts.append(v)
            self._tail_id_parts.append(ids_arr)
            self._tail_assign_parts.append(assign)
            self._tail_len += v.shape[0]
            self._live.update(ids_arr.tolist())
            self._snap_cache = None
            tail_len = self._tail_len
            self._lock.notify_all()
        return {"epoch": self.epoch, "tail_rows": tail_len}

    def delete(self, ids) -> dict:
        """Tombstone live ids: rows stay placed until compaction but
        every gather filters them, so they are exactly invisible (the
        conservative residual radius keeps unprobed-list bounds sound).
        ``KeyError`` on unknown/dead ids, same as MutableIndex."""
        ids_arr = np.asarray(ids, dtype=np.int64).reshape(-1)
        with self._lock:
            for i in ids_arr.tolist():
                if i not in self._live:
                    raise KeyError(f"id {i} is not live")
            n_base = self._base_ids.shape[0]
            live_after = (n_base + self._tail_len
                          - len(self._tombstones) - ids_arr.shape[0])
            if live_after < self.k:
                raise MutationBudgetError(
                    f"delete would leave {live_after} live rows < "
                    f"k={self.k}")
            self._tombstones.update(ids_arr.tolist())
            self._live.difference_update(ids_arr.tolist())
            self._snap_cache = None
            n_tombs = len(self._tombstones)
            self._lock.notify_all()
        return {"epoch": self.epoch, "tombstones": n_tombs}

    # -- compaction --------------------------------------------------------
    def compact(self) -> dict:
        """Re-cluster the surviving rows into a fresh list-major
        placement OFF the serving path, then swap under the lock —
        searches in flight keep their snapshot; post-cut writes carry
        over into the new epoch's delta tails."""
        t0 = time.perf_counter()
        with self._compact_lock:
            with self._lock:
                snap = self._snapshot()
                cut_parts = len(self._tail_parts)
                tomb_cut = set(self._tombstones)
            survivors = np.ascontiguousarray(
                snap.all_rows[snap.live_positions])
            surv_ids = snap.all_ids[snap.live_positions]
            km = train_kmeans(survivors, self.ncentroids, mesh=self.mesh,
                              iters=self.train_iters, seed=self.seed,
                              train_tile=self.train_tile)
            perm = np.argsort(km.assign, kind="stable").astype(np.int64)
            starts = np.zeros(self.ncentroids + 1, np.int64)
            np.cumsum(km.counts, out=starts[1:])
            with self._lock:
                carried_rows = self._tail_parts[cut_parts:]
                carried_ids = self._tail_id_parts[cut_parts:]
                self._base = survivors
                self._base_ids = surv_ids
                self._centroids = km.centroids
                self._residuals = km.residuals.copy()
                self._base_assign = km.assign
                self._base_counts = km.counts.copy()
                self._list_base_pos = tuple(
                    perm[starts[l]:starts[l + 1]]
                    for l in range(self.ncentroids))
                self._tail_parts = list(carried_rows)
                self._tail_id_parts = list(carried_ids)
                self._tail_assign_parts = []
                self._tail_len = 0
                for part in carried_rows:
                    assign = self._assign_host(part)
                    diff = part.astype(np.float64) - \
                        self._centroids.astype(np.float64)[assign]
                    dist = np.sqrt(np.einsum("nd,nd->n", diff, diff))
                    np.maximum.at(self._residuals, assign, dist)
                    self._tail_assign_parts.append(assign)
                    self._tail_len += part.shape[0]
                self._tombstones -= tomb_cut
                self.epoch += 1
                self._compactions += 1
                self._snap_cache = None
                report = {
                    "epoch": self.epoch,
                    "rows": int(survivors.shape[0]),
                    "carried_tail_rows": self._tail_len,
                    "tombstones_dropped": len(tomb_cut),
                    "tombstones_carried": len(self._tombstones),
                    "wall_s": round(time.perf_counter() - t0, 4),
                }
                self._last_compaction = report
        obs.record_span("index.compact", f"ivf-compact-{report['epoch']}",
                        report["wall_s"], rows=report["rows"])
        return report

    def _compact_due(self) -> bool:
        if (self._compact_tail_rows is not None
                and self._tail_len >= self._compact_tail_rows):
            return True
        if (self._compact_tombstones is not None
                and len(self._tombstones) >= self._compact_tombstones):
            return True
        return False

    def start_compactor(self, interval_s: float = 0.05) -> None:
        """Background compaction on the ctor thresholds — the live
        mixed-traffic shape: writes keep landing, the compactor
        re-clusters off-path, snapshots swap atomically."""
        if self._compactor_t is not None and self._compactor_t.is_alive():
            return

        def loop():
            while True:
                with self._lock:
                    while not self._closed and not self._compact_due():
                        self._lock.wait(timeout=interval_s)
                    if self._closed:
                        return
                try:
                    self.compact()
                except Exception:  # pragma: no cover - keep serving
                    pass

        t = threading.Thread(target=loop, name="ivf-compactor",
                             daemon=True)
        self._compactor_t = t
        t.start()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()
        if self._compactor_t is not None:
            self._compactor_t.join(timeout=10.0)

    def __enter__(self) -> "IVFIndex":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- reporting ---------------------------------------------------------
    def serving_engine(self, **kw) -> "IVFServingEngine":
        return IVFServingEngine(self, **kw)

    def stats(self) -> dict:
        with self._lock:
            n_base = self._base_ids.shape[0]
            out = {
                "epoch": self.epoch,
                "ncentroids": self.ncentroids,
                "nprobe": self.nprobe,
                "train_iters": self.train_iters,
                "seed": self.seed,
                "base_rows": int(n_base),
                "tail_rows": self._tail_len,
                "tombstones": len(self._tombstones),
                "live_rows": (n_base + self._tail_len
                              - len(self._tombstones)),
                "compactions": self._compactions,
                "compactor_alive": (
                    self._compactor_t is not None
                    and self._compactor_t.is_alive()),
                "metric": self.metric,
                **({"last_compaction": dict(self._last_compaction)}
                   if self._last_compaction else {}),
                **({"last_search": dict(self._last_search)}
                   if self._last_search else {}),
                **({"drift": self._drift.status()}
                   if self._drift is not None else {}),
            }
            return out


class _IVFPending:
    """A completed IVF serving request (the probed search runs at
    submit time against the pinned snapshot; ``result()`` just hands
    the arrays back — same handle surface the queue drives)."""

    __slots__ = ("trace_id", "tenant", "_result")

    def __init__(self, trace_id, tenant, result):
        self.trace_id = trace_id
        self.tenant = tenant
        self._result = result

    def result(self):
        return self._result


class IVFServingEngine:
    """The serving frontend of an :class:`IVFIndex`: duck-types the
    ``ServingEngine`` surface ``QueryQueue`` drives (``buckets``,
    ``_dim``, ``submit() -> handle``, ``apply_write``, ``stats``),
    pinning every request to one index snapshot so background
    compaction swaps are atomic from a request's view."""

    def __init__(self, index: IVFIndex, *, buckets: Sequence[int] = (8, 16)):
        import itertools

        self.index = index
        self.k = index.k
        self._dim = index.dim
        self._buckets = tuple(int(b) for b in buckets)
        self._seq = itertools.count()

    @property
    def buckets(self):
        return self._buckets

    @property
    def warmed_ops(self):
        return {"search"}

    def warmup(self, ops: Sequence[str] = ("search",)) -> dict:
        """Drive one probed search per bucket so the segment programs
        for the current rungs compile before live traffic arrives."""
        for b in self._buckets:
            q = np.zeros((int(b), self._dim), np.float32)
            self.index.search_certified(q)
        return {"search": len(self._buckets)}

    def submit(self, queries, *, op: str = "search",
               trace_id=None, tenant=None) -> _IVFPending:
        if op != "search":
            raise ValueError(
                f"IVFServingEngine serves op='search' only, got {op!r}")
        q = np.ascontiguousarray(np.asarray(queries, np.float32))
        if q.ndim != 2 or q.shape[1] != self._dim:
            raise ValueError(
                f"queries shape {q.shape} incompatible with database "
                f"dim {self._dim}")
        tid = trace_id if trace_id is not None else f"ivf-{next(self._seq)}"
        # shadow audit sampling (knn_tpu.obs.audit): pin the snapshot
        # BEFORE the search so the replay judges the served answer
        # against the exact corpus state it was served from
        audit_q = q.copy() if obs.audit.sampled(tid) else None
        snap = self.index._snapshot() if audit_q is not None else None
        t0 = time.perf_counter()
        d, ids, _stats = self.index.search_certified(q, k=self.k)
        obs.record_span("serving.request", tid,
                        time.perf_counter() - t0, op="ivf_search")
        if audit_q is not None:
            self._submit_audit(tid, tenant, audit_q, d, ids, snap,
                               _stats.get("epoch"))
        return _IVFPending(tid, tenant, (d, ids))

    def _submit_audit(self, tid, tenant, q_audit, d, ids,
                      snap, search_epoch) -> None:
        """Enqueue one sampled, already-served request for off-path
        exact replay (knn_tpu.obs.audit).  The oracle closure scans
        every live row of the pinned snapshot in f64 — ONLY on the
        audit worker thread.  Failure-proof: the request was served;
        a broken audit layer degrades to a dropped record."""
        try:
            if search_epoch != snap.epoch:
                # a compaction swapped between the snapshot pin and the
                # search: the evidence is unjudgeable — drop it loudly
                obs.counter(obs.names.AUDIT_DROPPED,
                            reason="epoch_moved").inc()
                return
            k = self.k

            def oracle(queries, served_ids):
                from knn_tpu.ops.refine import (
                    _pairwise_f64,
                    refine_shared_exact,
                )

                od, o_pos = refine_shared_exact(
                    snap.all_rows, queries, snap.live_positions, k)
                oi = snap.all_ids[np.clip(o_pos, 0, snap.n_all - 1)]
                order = np.argsort(snap.all_ids, kind="stable")
                sorted_ids = snap.all_ids[order]
                sid = np.asarray(served_ids, np.int64)[:, :k]
                j = np.clip(np.searchsorted(sorted_ids, sid), 0,
                            sorted_ids.shape[0] - 1)
                pos = order[j]
                valid = (sorted_ids[j] == sid) & snap.live_mask[pos]
                se = _pairwise_f64(
                    queries, snap.all_rows[np.where(valid, pos, 0)],
                    "l2")
                return od, oi, np.where(valid, se, np.inf)

            obs.audit.submit(obs.audit.AuditRecord(
                trace_id=tid,
                tenant=tenant,
                k=k,
                queries=q_audit,
                served_d=np.asarray(d),
                served_ids=np.asarray(ids),
                epoch=int(snap.epoch),
                cost_rows=int(q_audit.shape[0]) * int(snap.n_live),
                oracle=oracle,
            ))
        except Exception:  # noqa: BLE001 - audit must not fail serving
            obs.emit_event("audit.submit_error", op="ivf_search",
                           trace_id=tid)

    def search(self, queries, *, return_sqrt: bool = False):
        d, ids = self.submit(queries).result()
        if return_sqrt:
            d = np.sqrt(d)
        return d, ids

    def apply_write(self, kind: str, *, vectors=None, ids=None) -> dict:
        if kind == "insert":
            return self.index.insert(vectors, ids)
        if kind == "delete":
            return self.index.delete(ids)
        raise ValueError(
            f"unknown write kind {kind!r}; expected insert|delete")

    def stats(self, **kw) -> dict:
        return {"index": self.index.stats()}
