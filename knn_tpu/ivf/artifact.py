"""Jax-free pieces of the IVF subsystem: the version token and the
``ivf`` bench-artifact validator.

These live apart from :mod:`knn_tpu.ivf.index` (which imports JAX at
module load) so the artifact refresher and the perf sentinel can import
them without paying — or breaking on — a backend init.  Same split as
``knn_tpu.index.artifact`` over ``knn_tpu.index.mutable``: whatever
validates curated artifacts must run on the box that curates them, not
only the one with the accelerator.
"""

from __future__ import annotations

from typing import List

#: version stamp of the ``ivf`` bench block (bench.py's opt-in ivf
#: mode); bump on any schema change so the refresher refuses
#: half-migrated lines instead of hoisting garbage — the version token
#: the artifact-schema catalog's ``ivf`` entry consumes
IVF_VERSION = 1


def _required_fields():
    from knn_tpu.analysis.artifacts import required_keys

    return required_keys("ivf")


#: fields every valid ivf block must carry (the refusal list the
#: refresher prints) — DERIVED from the artifact-schema catalog
#: (knn_tpu.analysis.artifacts), the one declaration the validator and
#: the lockstep checker both read
IVF_REQUIRED = _required_fields()


def validate_ivf_block(block) -> List[str]:
    """Structural validation the artifact refresher runs before curating
    a line carrying an ``ivf`` block: returns the list of violations
    (empty = valid).  Blocks that recorded their own failure (an
    ``error`` key) are exempt — an honest error field beats a refused
    line.  A shim over the artifact-schema catalog
    (:mod:`knn_tpu.analysis.artifacts`, the ``ivf`` entry)."""
    from knn_tpu.analysis.artifacts import validate

    return validate("ivf", block, style="legacy")
