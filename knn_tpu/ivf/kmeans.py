"""Seeded, deterministic k-means for the IVF coarse quantizer.

Lloyd iterations split exactly the way the rest of the repo splits
work: the **assign** step is the existing sharded matmul machinery — a
:class:`~knn_tpu.parallel.sharded.ShardedKNN` placement of the current
centroids searched with ``k=1`` (the `_knn_program` SPMD distance +
lexicographic select, so assignment ties break by centroid index the
same way every other select in the repo breaks ties) — and the
**update** step is a host float64 segment mean (``np.add.at``), which
is deterministic regardless of device count or reduction order.  Empty
clusters keep their previous centroid (no resampling — reproducibility
beats marginally better inertia here).

Centroids are float32 and int8-quantizable via the existing
``ops.quantize`` row scheme (:func:`quantize_centroids`), so an int8
coarse probe prices centroid bytes the same way the db prices its rows.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


class KMeansResult(NamedTuple):
    #: [C, D] float32 centroids (row c = mean of its members, f64 math)
    centroids: np.ndarray
    #: [N] int64 list assignment of every training row
    assign: np.ndarray
    #: [C] int64 member count per list
    counts: np.ndarray
    #: [C] float64 max residual ``max ||x - c||`` per list (0 for empty
    #: lists) — the radius the probe certificate subtracts
    residuals: np.ndarray
    #: float64 sum of squared residuals (Lloyd objective, for tests)
    inertia: float
    #: Lloyd iterations actually run
    iters: int


def assign_lists(rows: np.ndarray, centroids: np.ndarray, *, mesh,
                 train_tile: Optional[int] = None) -> np.ndarray:
    """[N] nearest-centroid assignment via the sharded k=1 search — the
    SPMD assign step.  Tie order is the lexicographic (distance, index)
    select every device program in the repo uses."""
    from knn_tpu.parallel.sharded import ShardedKNN

    knn = ShardedKNN(np.asarray(centroids, np.float32), mesh=mesh, k=1,
                     metric="l2", train_tile=train_tile)
    _, idx = knn.search(np.asarray(rows, np.float32))
    return np.asarray(idx).reshape(-1).astype(np.int64)


def _residuals(rows64: np.ndarray, centroids: np.ndarray,
               assign: np.ndarray, ncentroids: int):
    """Per-list max residual radius + inertia, float64 throughout.  The
    radius must upper-bound EVERY member's distance to its list
    centroid — conservative is safe (extra fallback), an undercount is
    not — so it is computed host-side in f64, never from device f32."""
    diff = rows64 - centroids.astype(np.float64)[assign]
    sq = np.einsum("nd,nd->n", diff, diff)
    res = np.zeros(ncentroids, np.float64)
    np.maximum.at(res, assign, np.sqrt(sq))
    return res, float(sq.sum())


def _farthest_point_init(rows64: np.ndarray, ncentroids: int,
                         seed: int) -> np.ndarray:
    """Deterministic farthest-point init: the seed picks the first
    centroid row, each next centroid is the row farthest from the
    chosen set (ties → lowest index).  One O(C·N·D) pass — the cost of
    a single assign step — and on separated data it lands one seed per
    blob, which plain random sampling misses with near certainty (a
    split blob forces the certificate to flag every query in it)."""
    n = rows64.shape[0]
    rng = np.random.default_rng(seed)
    picks = [int(rng.integers(n))]
    min_sq = np.einsum("nd,nd->n",
                       rows64 - rows64[picks[0]],
                       rows64 - rows64[picks[0]])
    for _ in range(1, ncentroids):
        picks.append(int(np.argmax(min_sq)))
        diff = rows64 - rows64[picks[-1]]
        np.minimum(min_sq, np.einsum("nd,nd->n", diff, diff),
                   out=min_sq)
    return np.sort(np.asarray(picks))


def train_kmeans(rows: np.ndarray, ncentroids: int, *, mesh,
                 iters: int = 5, seed: int = 0,
                 train_tile: Optional[int] = None) -> KMeansResult:
    """Seeded Lloyd: deterministic farthest-point init
    (:func:`_farthest_point_init`), SPMD assign, host f64 segment-mean
    update."""
    rows = np.ascontiguousarray(np.asarray(rows, np.float32))
    n, d = rows.shape
    ncentroids = int(min(max(1, ncentroids), n))
    rows64 = rows.astype(np.float64)
    init = _farthest_point_init(rows64, ncentroids, seed)
    centroids = rows[init].copy()
    assign = np.zeros(n, np.int64)
    it = 0
    for it in range(1, max(1, int(iters)) + 1):
        assign = assign_lists(rows, centroids, mesh=mesh,
                              train_tile=train_tile)
        sums = np.zeros((ncentroids, d), np.float64)
        np.add.at(sums, assign, rows64)
        counts = np.bincount(assign, minlength=ncentroids)
        new = centroids.astype(np.float64)
        nz = counts > 0
        new[nz] = sums[nz] / counts[nz, None]
        centroids = new.astype(np.float32)
    assign = assign_lists(rows, centroids, mesh=mesh,
                          train_tile=train_tile)
    counts = np.bincount(assign, minlength=ncentroids).astype(np.int64)
    residuals, inertia = _residuals(rows64, centroids, assign, ncentroids)
    return KMeansResult(centroids, assign, counts, residuals, inertia, it)


def quantize_centroids(centroids: np.ndarray):
    """Int8 row quantization of the centroid table via the db scheme
    (``ops.quantize.quantize_rows_np``) — same per-row scale + bound
    discipline as the corpus, so an int8 coarse probe has certified
    error bounds exactly like an int8 db pass."""
    from knn_tpu.ops.quantize import quantize_rows_np

    return quantize_rows_np(np.asarray(centroids, np.float32))
