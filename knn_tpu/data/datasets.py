"""Synthetic dataset generators for tests and benchmarks.

The reference ships no data and no generators — its workload is MNIST CSVs
prepared out of band (report PDF p.11 §3.3.2).  These generators produce
(a) Gaussian-blob classification sets with a controllable difficulty, used
as stand-ins for MNIST in tests/CLI fixtures, and (b) uniform/clustered
float vectors at SIFT1M-like shapes for the benchmark harness.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from knn_tpu.data.csv_io import write_labels


def make_blobs(
    n_samples: int,
    dim: int,
    num_classes: int,
    *,
    cluster_std: float = 1.0,
    center_spread: float = 5.0,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """(features [N, dim] float32, labels [N] int32): isotropic Gaussian
    clusters, one per class, classes cycling so every class is populated."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=center_spread, size=(num_classes, dim))
    labels = (np.arange(n_samples) % num_classes).astype(np.int32)
    rng.shuffle(labels)
    feats = centers[labels] + rng.normal(scale=cluster_std, size=(n_samples, dim))
    return feats.astype(np.float32), labels


def make_mnist_like(
    n_train: int = 60_000,
    n_test: int = 10_000,
    n_val: int = 10_000,
    *,
    dim: int = 784,
    num_classes: int = 10,
    prototypes_per_class: int = 12,
    noise: float = 150.0,
    seed: int = 0,
):
    """MNIST-shaped surrogate at the reference's oracle scale (knn_mpi.cpp
    defaults :108-119: 60000x784 train / 10000 test / 10000 val, 10 integer
    classes, pixel-valued features in [0, 255]).

    Digit-like structure: each class mixes ``prototypes_per_class``
    prototypes built from a shared "stroke" dictionary, with neighbouring
    classes sharing strokes (the 4-vs-9 / 3-vs-8 confusability that gives
    MNIST its KNN error floor).  ``noise`` is calibrated so K=50 L2
    normalized KNN lands in the reference's published accuracy band
    (95.39% = 4.61% error, report PDF p.12 §4.2.1): noise 120 -> ~97%,
    150 -> ~95%, 200 -> ~88% on held-out data.

    Returns ``(train, train_labels, test, test_labels, val, val_labels)``,
    features float32 [*, dim] in [0, 255], labels int32.
    """
    rng = np.random.default_rng(seed)
    n_strokes = 24
    strokes = np.zeros((n_strokes, dim), np.float32)
    # stroke-width bounds scale down with dim so small dims stay valid
    w_lo = min(30, max(2, dim // 4))
    w_hi = max(w_lo + 1, min(120, dim))
    for s in range(n_strokes):
        w = int(rng.integers(w_lo, w_hi))
        lo = int(rng.integers(0, dim - w))
        strokes[s, lo : lo + w] = np.sin(np.linspace(0, np.pi, w)) * rng.uniform(120, 255)
    protos = np.zeros((num_classes, prototypes_per_class, dim), np.float32)
    for c in range(num_classes):
        base = [(2 * c + j) % n_strokes for j in range(4)]  # overlaps c±1
        for p in range(prototypes_per_class):
            extra = rng.choice(n_strokes, size=2, replace=False)
            w = rng.uniform(0.4, 1.0, size=6)[:, None]
            protos[c, p] = np.clip(
                (strokes[np.array(base + list(extra))] * w).sum(0), 0, 255
            )

    def draw(n):
        labels = rng.integers(0, num_classes, size=n).astype(np.int32)
        pi = rng.integers(0, prototypes_per_class, size=n)
        feats = protos[labels, pi] + rng.normal(scale=noise, size=(n, dim))
        return np.clip(feats, 0, 255).astype(np.float32), labels

    train, train_labels = draw(n_train)
    test, test_labels = draw(n_test)
    val, val_labels = draw(n_val)
    return train, train_labels, test, test_labels, val, val_labels


def make_database(
    n: int, dim: int, *, seed: int = 0, scale: float = 128.0
) -> np.ndarray:
    """[n, dim] float32 uniform vectors in [0, scale) — a SIFT-like value
    range for benchmark workloads."""
    rng = np.random.default_rng(seed)
    return (rng.random(size=(n, dim)) * scale).astype(np.float32)


def save_labeled_csv(path: str, feats: np.ndarray, labels: np.ndarray) -> None:
    """Write the reference's labeled format: ``label,f0,...`` per row
    (the shape knn_mpi.cpp:154-175 parses)."""
    with open(path, "w") as f:
        for lab, row in zip(labels, feats):
            f.write(str(int(lab)) + "," + ",".join(repr(float(v)) for v in row) + "\n")


def save_unlabeled_csv(path: str, feats: np.ndarray) -> None:
    """Write the reference's unlabeled test format (knn_mpi.cpp:177-197)."""
    with open(path, "w") as f:
        for row in feats:
            f.write(",".join(repr(float(v)) for v in row) + "\n")


__all__ = [
    "make_blobs",
    "make_mnist_like",
    "make_database",
    "save_labeled_csv",
    "save_unlabeled_csv",
    "write_labels",
]
