"""Synthetic dataset generators for tests and benchmarks.

The reference ships no data and no generators — its workload is MNIST CSVs
prepared out of band (report PDF p.11 §3.3.2).  These generators produce
(a) Gaussian-blob classification sets with a controllable difficulty, used
as stand-ins for MNIST in tests/CLI fixtures, and (b) uniform/clustered
float vectors at SIFT1M-like shapes for the benchmark harness.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from knn_tpu.data.csv_io import write_labels


def make_blobs(
    n_samples: int,
    dim: int,
    num_classes: int,
    *,
    cluster_std: float = 1.0,
    center_spread: float = 5.0,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """(features [N, dim] float32, labels [N] int32): isotropic Gaussian
    clusters, one per class, classes cycling so every class is populated."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=center_spread, size=(num_classes, dim))
    labels = (np.arange(n_samples) % num_classes).astype(np.int32)
    rng.shuffle(labels)
    feats = centers[labels] + rng.normal(scale=cluster_std, size=(n_samples, dim))
    return feats.astype(np.float32), labels


def make_database(
    n: int, dim: int, *, seed: int = 0, scale: float = 128.0
) -> np.ndarray:
    """[n, dim] float32 uniform vectors in [0, scale) — a SIFT-like value
    range for benchmark workloads."""
    rng = np.random.default_rng(seed)
    return (rng.random(size=(n, dim)) * scale).astype(np.float32)


def save_labeled_csv(path: str, feats: np.ndarray, labels: np.ndarray) -> None:
    """Write the reference's labeled format: ``label,f0,...`` per row
    (the shape knn_mpi.cpp:154-175 parses)."""
    with open(path, "w") as f:
        for lab, row in zip(labels, feats):
            f.write(str(int(lab)) + "," + ",".join(repr(float(v)) for v in row) + "\n")


def save_unlabeled_csv(path: str, feats: np.ndarray) -> None:
    """Write the reference's unlabeled test format (knn_mpi.cpp:177-197)."""
    with open(path, "w") as f:
        for row in feats:
            f.write(",".join(repr(float(v)) for v in row) + "\n")


__all__ = [
    "make_blobs",
    "make_database",
    "save_labeled_csv",
    "save_unlabeled_csv",
    "write_labels",
]
