"""Readers/writers for the TexMex .fvecs/.ivecs/.bvecs formats used by the
SIFT1M / GIST1M ANN benchmarks (BASELINE.json configs 3 and 5).

Format: each vector is ``int32 dim`` followed by ``dim`` components
(float32 / int32 / uint8).  Not in the reference — it only speaks CSV —
but the north-star benchmark datasets ship this way.
"""

from __future__ import annotations

import numpy as np


def _read_vecs(path: str, dtype, component_bytes: int) -> np.ndarray:
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size == 0:
        raise ValueError(f"{path}: empty vecs file")
    dim = int(np.frombuffer(raw[:4].tobytes(), dtype=np.int32)[0])
    if dim <= 0:
        raise ValueError(f"{path}: bad leading dim {dim}")
    row_bytes = 4 + dim * component_bytes
    if raw.size % row_bytes:
        raise ValueError(f"{path}: size {raw.size} not a multiple of row size {row_bytes}")
    n = raw.size // row_bytes
    rows = raw.reshape(n, row_bytes)
    dims = rows[:, :4].copy().view(np.int32).ravel()
    if not np.all(dims == dim):
        raise ValueError(f"{path}: inconsistent per-row dims")
    return rows[:, 4:].copy().view(dtype).reshape(n, dim)


def read_fvecs(path: str) -> np.ndarray:
    """[N, dim] float32 (SIFT1M base/query files)."""
    return _read_vecs(path, np.float32, 4)


def read_ivecs(path: str) -> np.ndarray:
    """[N, dim] int32 (ground-truth neighbor-index files)."""
    return _read_vecs(path, np.int32, 4)


def read_bvecs(path: str) -> np.ndarray:
    """[N, dim] uint8 (SIFT1B-style byte vectors)."""
    return _read_vecs(path, np.uint8, 1)


def read_bvecs_quantized(path: str):
    """bvecs payload fed to the int8 coarse pass DIRECTLY
    (ops.quantize.QuantizedRows): the uint8 bytes re-centered by the
    L2-invariant -128 shift land exactly in int8 at UNIT scale — no f32
    quantization round trip, residuals identically zero, so the
    certificate's quantization bound collapses to pure f32 slack.
    ``ShardedKNN`` built from the raw ``read_bvecs`` uint8 array applies
    the same shortcut at placement time; this loader is for callers
    driving ``ops.pallas_knn`` / ``ops.quantize`` themselves."""
    from knn_tpu.ops.quantize import from_uint8

    return from_uint8(read_bvecs(path))


def _write_vecs(path: str, x: np.ndarray, dtype) -> None:
    x = np.ascontiguousarray(x, dtype=dtype)
    n, dim = x.shape
    dims = np.full((n, 1), dim, dtype=np.int32)
    out = np.concatenate([dims.view(np.uint8).reshape(n, 4),
                          x.view(np.uint8).reshape(n, -1)], axis=1)
    out.tofile(path)


def write_fvecs(path: str, x) -> None:
    _write_vecs(path, np.asarray(x), np.float32)


def write_ivecs(path: str, x) -> None:
    _write_vecs(path, np.asarray(x), np.int32)
