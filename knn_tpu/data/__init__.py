"""L1 data / I/O layer: the reference's per-rank CSV readers and label
writer (knn_mpi.cpp:154-222, 385-393) as reusable host-side loaders, plus
the fvecs/ivecs formats of the SIFT1M/GIST1M benchmark suite and synthetic
dataset generators for tests and benchmarks.

I/O stays on host by design (SURVEY.md §7): arrays cross to device once,
as a whole, via the placement collectives in knn_tpu.parallel.
"""

from knn_tpu.data.csv_io import (
    read_labeled_csv,
    read_unlabeled_csv,
    write_labels,
)
from knn_tpu.data.vecs import read_fvecs, read_ivecs, read_bvecs, write_fvecs, write_ivecs
from knn_tpu.data.datasets import make_blobs, save_labeled_csv, save_unlabeled_csv

__all__ = [
    "read_labeled_csv",
    "read_unlabeled_csv",
    "write_labels",
    "read_fvecs",
    "read_ivecs",
    "read_bvecs",
    "write_fvecs",
    "write_ivecs",
    "make_blobs",
    "save_labeled_csv",
    "save_unlabeled_csv",
]
