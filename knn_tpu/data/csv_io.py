"""CSV readers/writers matching the reference's formats exactly.

Reference input contract (knn_mpi.cpp:154-222; report PDF p.11 §3.3.2):
- labeled rows (train/val): ``label,f0,f1,...,f{dim-1}`` — integer label
  first, then ``dim`` float features (the reader at :154-175 peels every
  (dim+1)-th token off as a label);
- unlabeled rows (test): ``f0,...,f{dim-1}`` (:177-197);
- output: one predicted integer label per line, ``Test_label.csv``
  (:385-393).

Unlike the reference, row counts are discovered from the file rather than
required up front (the reference needs N_train/N_test/N_val compiled in,
knn_mpi.cpp:110-112), and malformed rows raise instead of silently
corrupting the flat-array index arithmetic at knn_mpi.cpp:169-170.

A native C++ fast path (knn_tpu.native) accelerates these readers when the
shared library is built; this module is the always-available fallback.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def _parse_rows(path: str, dtype) -> np.ndarray:
    try:
        from knn_tpu import native

        if native.available():
            return native.read_csv(path).astype(dtype, copy=False)
    except ImportError:
        pass
    rows = []
    width = None
    with open(path, "r") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            vals = line.split(",")
            if width is None:
                width = len(vals)
            elif len(vals) != width:
                raise ValueError(
                    f"{path}:{lineno}: expected {width} fields, got {len(vals)}"
                )
            rows.append(vals)
    if not rows:
        raise ValueError(f"{path}: empty CSV")
    return np.asarray(rows, dtype=dtype)


def read_labeled_csv(path: str, dim: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """(features [N, dim] float32, labels [N] int32) from label-first rows —
    the train/val reader (knn_mpi.cpp:154-175, 198-222).

    ``dim`` is validated if given (the reference trusts it blindly)."""
    arr = _parse_rows(path, np.float32)
    if arr.shape[1] < 2:
        raise ValueError(f"{path}: labeled rows need a label and >=1 feature")
    if dim is not None and arr.shape[1] != dim + 1:
        raise ValueError(f"{path}: expected {dim}+1 columns, found {arr.shape[1]}")
    labels = arr[:, 0]
    if not np.all(labels == np.round(labels)):
        raise ValueError(f"{path}: non-integer labels in first column")
    return np.ascontiguousarray(arr[:, 1:]), labels.astype(np.int32)


def read_unlabeled_csv(path: str, dim: Optional[int] = None) -> np.ndarray:
    """Features [N, dim] float32 from unlabeled rows — the test reader
    (knn_mpi.cpp:177-197)."""
    arr = _parse_rows(path, np.float32)
    if dim is not None and arr.shape[1] != dim:
        raise ValueError(f"{path}: expected {dim} columns, found {arr.shape[1]}")
    return arr


def write_labels(path: str, labels) -> None:
    """One integer label per line — the ``Test_label.csv`` writer
    (knn_mpi.cpp:385-393)."""
    labels = np.asarray(labels).astype(np.int64).ravel()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(str(int(x)) for x in labels))
        f.write("\n")
    os.replace(tmp, path)


def read_labels(path: str) -> np.ndarray:
    """Read a one-label-per-line file back (for parity tests against the
    reference's output)."""
    with open(path, "r") as f:
        return np.asarray([int(line) for line in f if line.strip()], dtype=np.int32)
