"""The serving engine: precompiled shape-bucketed executables over a placed
:class:`~knn_tpu.parallel.sharded.ShardedKNN`, with async dispatch-ahead.

Three mechanisms turn the batch library into a throughput engine:

- **Shape bucketing** (serving.buckets): each request pads up to the
  smallest ladder bucket, so any traffic pattern hits O(log) compiled
  programs.  Pad rows are whole zero queries whose outputs are sliced
  away on host — the distance matrix is row-separable and the top-k runs
  per row, so padding is ARITHMETIC-TRANSPARENT: bucketed results are
  bitwise identical to a direct ``search()`` call of the same placed
  batch (asserted in tests/test_serving.py).  Against the *unpadded*
  direct call the guarantee is backend-dependent, exactly as it already
  is between two direct calls of different batch sizes: the TPU MXU's
  K-dim reduction order is batch-shape invariant (bitwise there), while
  CPU XLA's gemm strategy varies with batch shape in the last float
  bits — neighbor IDENTITY and lexicographic tie-break order are
  preserved either way (same pad-and-slice contract
  ``ShardedKNN._place_queries`` already relies on for mesh
  divisibility).
- **Precompiled executables**: :meth:`ServingEngine.warmup` AOT-compiles
  every bucket up front via ``jit(...).lower(...).compile()`` — no
  request ever stalls on an inline XLA compile.  Compiles are counted
  per bucket; a replayed trace of any batch-size mix compiles at most
  ``len(buckets)`` programs (asserted in tests/test_serving.py).
- **Async dispatch-ahead**: :meth:`submit` returns immediately with a
  :class:`PendingSearch` handle — JAX dispatch is asynchronous, so the
  host can pad/place/dispatch request N+1 while the device executes
  request N (double-buffered via :meth:`replay`'s bounded in-flight
  window).  Query placements are DONATED to the program on non-CPU
  backends, so each bucket's input buffer is recycled instead of
  accumulating.
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from knn_tpu import obs
from knn_tpu.analysis.annotations import hot_path
from knn_tpu.obs import names as mn
from knn_tpu.serving.buckets import (
    DEFAULT_MAX_BUCKET,
    DEFAULT_MIN_BUCKET,
    bucket_for,
    bucket_ladder,
    normalize_ladder,
    split_sizes,
)

#: operations the engine can serve; each maps to one cached program family
OPS = ("search", "predict")


def latency_summary(samples_s: Sequence) -> Optional[Dict[str, float]]:
    """p50/p95/p99/mean (milliseconds) of per-request wall latencies —
    the engine feeds its bounded recent-request window (``count`` is the
    window's fill, not the lifetime request total; see stats()).

    Samples may be plain durations or ``(monotonic_ts, duration)``
    pairs; with timestamps the summary also labels WHICH window the
    quantiles cover — ``window_samples`` (the fill, same number as
    ``count``) and ``window_span_s`` (wall span from oldest to newest
    windowed sample) — so a consumer doing burn-rate math can never
    mistake a window quantile for a lifetime one."""
    if not samples_s:
        return None
    first = samples_s[0]
    ts = None
    if isinstance(first, tuple):
        ts = [t for t, _ in samples_s]
        vals = [v for _, v in samples_s]
    else:
        vals = samples_s
    arr = np.asarray(vals, dtype=np.float64) * 1e3
    out = {
        "p50": round(float(np.percentile(arr, 50)), 3),
        "p95": round(float(np.percentile(arr, 95)), 3),
        "p99": round(float(np.percentile(arr, 99)), 3),
        "mean": round(float(arr.mean()), 3),
        "max": round(float(arr.max()), 3),
        "count": int(arr.size),
        "window_samples": int(arr.size),
    }
    if ts is not None:
        out["window_span_s"] = round(max(ts) - min(ts), 3)
    return out


class PendingSearch:
    """An in-flight bucketed request: device work was dispatched
    asynchronously; :meth:`result` blocks on the transfer, slices the pad
    rows away, and records the request's wall latency."""

    def __init__(self, engine: "ServingEngine", op: str, chunks, n: int,
                 t0: float, trace_id: Optional[str] = None,
                 tenant: Optional[str] = None,
                 audit_queries: Optional[np.ndarray] = None):
        self._engine = engine
        self._op = op
        self._chunks = chunks  # [(device outputs, redo, rows)]
        self._n = n
        self._t0 = t0
        self._done = False
        self._error_counted = False
        #: request-scoped trace id (minted in submit; None when obs off)
        self.trace_id = trace_id
        #: tenant tag for per-tenant latency/error attribution (None =
        #: untagged: produces no tenant series at all)
        self.tenant = tenant
        #: query copy pinned at submit when the shadow audit sampler
        #: selected this request (knn_tpu.obs.audit); None = unsampled
        self._audit_queries = audit_queries

    def result(self):
        from knn_tpu.parallel.sharded import _fetch_or_redispatch

        t_join = time.perf_counter()
        try:
            parts = []
            for out, redo, rows in self._chunks:
                if self._op == "search":
                    d = _fetch_or_redispatch(
                        out[0], lambda r=redo: r()[0], "serving fetch (d)")
                    i = _fetch_or_redispatch(
                        out[1], lambda r=redo: r()[1], "serving fetch (i)")
                    parts.append((d[:rows], i[:rows]))
                else:
                    lbl = _fetch_or_redispatch(out, redo, "serving fetch (labels)")
                    parts.append(lbl[:rows])
            if self._op == "search":
                d = np.concatenate([p[0] for p in parts])[: self._n]
                i = np.concatenate([p[1] for p in parts])[: self._n]
                res = (d, i)
            else:
                res = np.concatenate(parts)[: self._n]
        except Exception:
            # errors, like latency, count once per REQUEST: a caller
            # retrying result() after a failure must not inflate
            # errors_total on every attempt
            if not self._error_counted:
                self._error_counted = True
                self._engine._record_error(self._op, tenant=self.tenant)
            raise
        if not self._done:  # latency is per request, not per .result() call
            self._done = True
            done = time.perf_counter()
            # join = time blocked on the device/transfer inside result();
            # the request span is the full submit-to-result wall
            obs.record_span("serving.join", self.trace_id,
                            done - t_join, op=self._op,
                            **({} if self.tenant is None
                               else {"tenant": self.tenant}))
            self._engine._record_latency(done - self._t0, self._op,
                                         trace_id=self.trace_id,
                                         rows=self._n,
                                         tenant=self.tenant)
            if self._audit_queries is not None:
                self._engine._submit_audit(self, res)
        return res


class ServingEngine:
    """Shape-bucketed query-serving frontend over a placed ``ShardedKNN``.

    Construction is cheap (no compiles); call :meth:`warmup` at startup to
    AOT-compile every bucket, or let the first request of each bucket pay
    its compile once.  All compile/dispatch accounting is exposed via
    :meth:`stats`.

    Thread-safety: guarded by ``self._lock`` (machine-checked by the
    ``locked-mutation`` checker, knn_tpu.analysis); the lock is never
    held across an XLA compile or a device dispatch (see
    :meth:`_executable`).

    ``donate_queries=None`` donates the query placement to the program on
    non-CPU backends (buffer reuse; CPU XLA rejects the donation with a
    warning, so it defaults off there).
    """

    def __init__(
        self,
        program,
        *,
        buckets: Optional[Sequence[int]] = None,
        min_bucket: int = DEFAULT_MIN_BUCKET,
        max_bucket: int = DEFAULT_MAX_BUCKET,
        k: Optional[int] = None,
        donate_queries: Optional[bool] = None,
        aot: bool = True,
        latency_window: int = 4096,
    ):
        import jax

        self.program = program
        self.k = program.k if k is None else int(k)
        self.buckets = (
            bucket_ladder(min_bucket, max_bucket) if buckets is None
            else normalize_ladder(buckets)
        )
        if donate_queries is None:
            donate_queries = jax.default_backend() != "cpu"
        self.donate_queries = bool(donate_queries)
        self._aot = bool(aot)
        if getattr(program, "_tp", None) is None:
            # a host-RAM-tier placement has no resident database to
            # AOT-compile against — refuse with the tier's own message
            # instead of a cryptic NoneType AttributeError below
            program._require_resident("ServingEngine")
        #: user-facing request dim (what submit validates/pads against);
        #: dot placements are norm-augmented, so the PLACED width below
        #: is one wider — _place_queries appends the zero column
        self._dim = int(getattr(program, "dim_in", program._tp.shape[1]))
        self._placed_dim = int(program._tp.shape[1])
        self._lock = threading.Lock()
        self._execs: Dict[Tuple[str, int], object] = {}
        #: per-key in-flight compile events (see _executable)
        self._compiling: Dict[Tuple[str, int], threading.Event] = {}
        self._compiles: Counter = Counter()  # bucket -> compile count
        self._dispatches: Counter = Counter()  # bucket -> dispatch count
        #: LIFETIME totals — the bounded latency window below reports
        #: recent-window truth only, so a long-running engine needs these
        #: to report lifetime truth alongside (also mirrored to the obs
        #: registry: knn_tpu_serving_{requests,queries,errors}_total)
        self._requests = 0
        self._queries = 0
        self._errors = 0
        #: bounded sample window of (monotonic ts, seconds) pairs: a
        #: long-running service must not grow a per-request list
        #: forever, and stats() percentiles over the recent window are
        #: the operationally useful number anyway — lifetime counts
        #: live in requests_total/queries_total above; the timestamps
        #: let latency_summary label the window's wall span
        self._latencies_s: deque = deque(maxlen=int(latency_window))
        #: ops whose buckets have all been AOT-compiled (warmup());
        #: the readiness probe (/healthz) gates on this being non-empty
        self.warmed_ops: set = set()
        # every XLA compile this engine triggers lands in the registry
        # (count + seconds), not just the per-bucket tallies above
        obs.install_compile_hook()
        # readiness/self-diagnosis surface (/healthz, /statusz, doctor)
        obs.health.register_engine(self)

    # -- compile cache -----------------------------------------------------
    def _jit_fn(self, op: str):
        from knn_tpu.parallel.sharded import _knn_program, _predict_program

        p = self.program
        if op == "search":
            return _knn_program(
                p.mesh, self.k, p.metric, p.merge, p.n_train, p.train_tile,
                p._dtype_key, donate=self.donate_queries,
                dcn_merge=p.dcn_merge,
            )
        if p._labels is None:
            raise RuntimeError(
                "ServingEngine op='predict' needs a ShardedKNN built with "
                "labels")
        return _predict_program(
            p.mesh, self.k, p.num_classes, p.metric, p.merge, p.n_train,
            p.train_tile, p._dtype_key, donate=self.donate_queries,
            dcn_merge=p.dcn_merge,
        )

    def _placed_rows(self, bucket: int) -> int:
        from knn_tpu.parallel.mesh import QUERY_AXIS

        qs = self.program.mesh.shape[QUERY_AXIS]
        return -(-bucket // qs) * qs

    def _tail_args(self, op: str) -> tuple:
        p = self.program
        return (p._tp,) if op == "search" else (p._tp, p._labels)

    def _executable(self, op: str, bucket: int,
                    trace_id: Optional[str] = None):
        """The compiled executable for ``(op, bucket)``; compiles AOT on
        first use (``lower().compile()`` — no example batch is executed).
        Distinct buckets below the mesh's query-shard count share one
        placed shape and therefore one executable.

        The engine lock is NEVER held across the XLA compile (seconds on
        real hardware): a cold bucket's compile must not freeze
        concurrent dispatches to warm buckets, stats(), or latency
        recording.  Concurrent first requests to the same key wait on a
        per-key event instead."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from knn_tpu.parallel.mesh import QUERY_AXIS

        key = (op, self._placed_rows(bucket))
        while True:
            with self._lock:
                ex = self._execs.get(key)
                if ex is not None:
                    return ex
                ev = self._compiling.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._compiling[key] = ev
                    break  # this thread owns the compile
            ev.wait()  # another thread is compiling this key; re-check
        try:
            # the compile span carries the trace id of the request that
            # triggered it (None for warmup), so a live request's inline
            # compile stall is attributable to that request end-to-end
            with obs.span("serving.compile", trace_id=trace_id, op=op,
                          bucket=int(bucket), placed_rows=int(key[1])):
                fn = self._jit_fn(op)
                if self._aot:
                    q_spec = jax.ShapeDtypeStruct(
                        (key[1], self._placed_dim), np.float32,
                        sharding=NamedSharding(self.program.mesh, P(QUERY_AXIS)),
                    )
                    try:
                        ex = fn.lower(q_spec, *self._tail_args(op)).compile()
                    except Exception:
                        # AOT API drift: fall back to the plain jitted callable
                        # (still exactly one compile per placed shape, paid on
                        # the first dispatch instead of here)
                        ex = fn
                else:
                    ex = fn
            with self._lock:
                self._execs[key] = ex
                self._compiles[bucket] += 1
            obs.counter(mn.SERVING_COMPILES, op=op, bucket=bucket).inc()
            return ex
        finally:
            # waiters re-check _execs; on a raised _jit_fn error they
            # find the key absent and retry (re-raising for themselves)
            with self._lock:
                del self._compiling[key]
            ev.set()

    def warmup(self, ops: Sequence[str] = ("search",)) -> Dict[str, int]:
        """AOT-compile every bucket for each requested op so no live
        request ever pays an inline compile.  Returns per-op executable
        counts (ladder rungs sharing a placed shape share an executable).

        When the autotuner's persisted winner for this placement's shape
        resolves ``precision="int8"``, warmup also builds the quantized
        db placement (ShardedKNN._int8_placement) — a one-time full-db
        quantize + transfer that would otherwise land on the first live
        certified query."""
        counts = {}
        for op in ops:
            if op not in OPS:
                raise ValueError(f"unknown op {op!r}; expected one of {OPS}")
            for b in self.buckets:
                self._executable(op, b)
            with self._lock:  # concurrent cold compiles mutate _execs
                keys = list(self._execs)
            counts[op] = len({k for k in keys if k[0] == op})
            self.warmed_ops.add(op)  # /healthz readiness flips here
        info = self._tuning_info()
        if (info and info.get("resolved_knobs", {}).get("precision")
                == "int8"):
            try:
                self.program._int8_placement()
                counts["int8_placement"] = 1
            except Exception:  # pragma: no cover - placement best-effort
                pass  # a live int8 call will rebuild (and surface) it
        return counts

    # -- dispatch ----------------------------------------------------------
    @hot_path
    def _dispatch_chunk(self, op: str, chunk: np.ndarray,
                        trace_id: Optional[str] = None):
        """Pad one <=max_bucket chunk to its bucket and dispatch (async).
        Returns (device outputs, redo closure, real row count)."""
        from knn_tpu.parallel.sharded import _retry_transient

        n = chunk.shape[0]
        bucket = bucket_for(self.buckets, n)
        assert bucket is not None  # callers split oversize requests first
        if n < bucket:
            padded = np.zeros((bucket, self._dim), dtype=np.float32)
            padded[:n] = chunk
        else:
            padded = chunk

        def go():
            # re-place on every attempt: with donation the previous
            # placement's buffer is consumed by the failed dispatch
            qp, _ = self.program._place_queries(padded)
            return self._executable(op, bucket, trace_id)(
                qp, *self._tail_args(op))

        out = _retry_transient(go, "serving dispatch")
        with self._lock:
            self._dispatches[bucket] += 1
        obs.counter(mn.SERVING_DISPATCHES, op=op, bucket=bucket).inc()
        return out, go, n

    # np.asarray/ascontiguousarray coerce the caller's HOST request
    # array (never a device fetch); int() reads numpy shape tuples
    @hot_path(allow=("np.asarray", "np.ascontiguousarray", "int"))
    def submit(self, queries, *, op: str = "search",
               trace_id: Optional[str] = None,
               tenant: Optional[str] = None) -> PendingSearch:
        """Dispatch ``queries`` (async) and return a handle; oversize
        requests split into max-bucket chunks, each dispatched back to
        back so the device pipeline stays full.  ``trace_id`` scopes the
        request's spans (dispatch / compile / join); None mints a fresh
        one when telemetry is enabled (knn_tpu.obs).  ``tenant`` tags
        the request for per-tenant attribution (requests/errors/latency
        series + the per-tenant SLO objectives); None produces no
        tenant series — a tenant-free caller's telemetry is unchanged."""
        if op not in OPS:
            raise ValueError(f"unknown op {op!r}; expected one of {OPS}")
        q = np.ascontiguousarray(np.asarray(queries, dtype=np.float32))
        if q.ndim != 2 or q.shape[1] != self._dim:
            raise ValueError(
                f"queries shape {q.shape} incompatible with database dim "
                f"{self._dim}")
        if trace_id is None:
            trace_id = obs.new_trace_id()
        # shadow audit sampling (knn_tpu.obs.audit): the only hot-path
        # costs are one trace-id hash plus, on the sampled fraction, one
        # query copy pinned here so a later in-place caller mutation
        # cannot corrupt the replay.  The oracle scan itself runs on the
        # audit worker thread, never here.
        audit_q = (q.copy()
                   if op == "search" and obs.audit.sampled(trace_id)
                   else None)
        t0 = time.perf_counter()
        try:
            with obs.span("serving.dispatch", trace_id=trace_id, op=op,
                          rows=int(q.shape[0]),
                          **({"tenant": tenant}
                             if tenant is not None else {})) as sp:
                chunks = []
                lo = 0
                rungs = []
                for size in split_sizes(q.shape[0], self.buckets[-1]):
                    rungs.append(int(bucket_for(self.buckets, size)))
                    chunks.append(
                        self._dispatch_chunk(op, q[lo : lo + size], trace_id))
                    lo += size
                # which ladder rungs this request rode: the waterfall
                # layer groups its per-bucket attribution off this
                sp.set("buckets", rungs)
        except Exception:
            self._record_error(op, tenant=tenant)
            raise
        with self._lock:
            self._requests += 1
            self._queries += int(q.shape[0])
        obs.counter(mn.SERVING_REQUESTS, op=op).inc()
        obs.counter(mn.SERVING_QUERIES, op=op).inc(int(q.shape[0]))
        if tenant is not None:
            obs.counter(mn.TENANT_REQUESTS, tenant=tenant).inc()
        return PendingSearch(self, op, chunks, q.shape[0], t0, trace_id,
                             tenant, audit_queries=audit_q)

    def search(self, queries, *, return_sqrt: bool = False):
        """Bucketed exact search: (distances [Q, k], indices [Q, k]) as
        numpy arrays, bitwise identical to ``ShardedKNN.search``."""
        d, i = self.submit(queries, op="search").result()
        if return_sqrt:
            from knn_tpu.ops.distance import metric_values

            d = np.asarray(metric_values(d, self.program.metric))
        return d, i

    def predict(self, queries) -> np.ndarray:
        """Bucketed classification: labels [Q] int32 (majority vote on
        device, same program family as ``ShardedKNN.predict``)."""
        return self.submit(queries, op="predict").result()

    # -- trace replay ------------------------------------------------------
    def replay(self, requests: Sequence[np.ndarray], *, depth: int = 2):
        """Replay a request trace with at most ``depth`` requests in
        flight: request N+1 is padded/placed/dispatched while request N
        executes (the double-buffer that overlaps host staging with
        device compute).  Returns ``(results, report)`` where ``report``
        carries sustained q/s and the latency percentiles."""
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        results: List[object] = [None] * len(requests)
        pending: List[Tuple[int, PendingSearch]] = []
        total_rows = 0
        t0 = time.perf_counter()
        for idx, q in enumerate(requests):
            # drain BEFORE submitting so at most ``depth`` requests are
            # ever in flight, the new one included — while the oldest's
            # result() blocks, the depth-1 behind it keep the device busy
            while len(pending) >= depth:
                j, h = pending.pop(0)
                results[j] = h.result()
            total_rows += int(np.shape(q)[0])
            pending.append((idx, self.submit(q)))
        for j, h in pending:
            results[j] = h.result()
        wall = time.perf_counter() - t0
        report = {
            "requests": len(requests),
            "total_queries": total_rows,
            "wall_s": round(wall, 4),
            "sustained_qps": round(total_rows / wall, 2) if wall > 0 else None,
            "depth": depth,
            **self.stats(),
        }
        return results, report

    # -- observability -----------------------------------------------------
    def _record_latency(self, seconds: float, op: str = "search", *,
                        trace_id: Optional[str] = None,
                        rows: Optional[int] = None,
                        tenant: Optional[str] = None) -> None:
        with self._lock:
            self._latencies_s.append((time.monotonic(), seconds))
        # the registry histogram is the machine-scrapable counterpart of
        # stats()["latency_ms"]: every sample feeds both, but each keeps
        # its own bounded percentile window (latency_window here, the
        # registry default there), so quantiles can differ when the
        # engine was built with a non-default window.  The exemplar
        # keeps the worst samples' trace ids joinable back to their
        # spans (the histogram->trace join the waterfall layer reads).
        obs.histogram(mn.SERVING_REQUEST_LATENCY, op=op).observe(
            seconds, exemplar=trace_id)
        if tenant is not None:
            obs.histogram(mn.TENANT_REQUEST_LATENCY,
                          tenant=tenant).observe(seconds,
                                                 exemplar=trace_id)
        obs.record_span("serving.request", trace_id, seconds, op=op,
                        **({} if rows is None else {"rows": int(rows)}),
                        **({} if tenant is None else {"tenant": tenant}))

    def _submit_audit(self, handle: PendingSearch, res) -> None:
        """Enqueue one sampled, already-served request for off-path
        exact replay (knn_tpu.obs.audit).  Cheap here — one bounded
        queue put under the sampler's row budget; the oracle closure
        below (full-database f64 scan via ops.refine) runs ONLY on the
        audit worker thread.  Failure-proof: the request was already
        served, so a broken audit layer degrades to a dropped record,
        never an exception into the caller."""
        try:
            d, i = res
            program = self.program
            k = self.k
            metric = program.metric

            def oracle(queries, served_ids):
                from knn_tpu.ops.refine import (
                    _pairwise_f64,
                    refine_shared_exact,
                )

                db = program._host_train()  # may raise -> loud drop
                # dot placements are norm-augmented one column wider
                # than the request dim; original rows are the first
                # D columns (queries ride with a zero column appended)
                if db.shape[1] != queries.shape[1]:
                    db = db[:, : queries.shape[1]]
                n = db.shape[0]
                od, oi = refine_shared_exact(
                    db, queries, np.arange(n), k, metric=metric)
                ids = np.asarray(served_ids, np.int64)[:, :k]
                valid = (ids >= 0) & (ids < n)
                safe = np.where(valid, ids, 0)
                se = _pairwise_f64(queries, db[safe], metric)
                return od, oi, np.where(valid, se, np.inf)

            q_audit = handle._audit_queries
            obs.audit.submit(obs.audit.AuditRecord(
                trace_id=handle.trace_id,
                tenant=handle.tenant,
                k=k,
                queries=q_audit,
                served_d=np.asarray(d),
                served_ids=np.asarray(i),
                epoch=None,
                cost_rows=int(q_audit.shape[0]) * int(program.n_train),
                oracle=oracle,
            ))
        except Exception:  # noqa: BLE001 - audit must not fail serving
            obs.emit_event("audit.submit_error", op=handle._op,
                           trace_id=handle.trace_id)

    def _record_error(self, op: str, *,
                      tenant: Optional[str] = None) -> None:
        with self._lock:
            self._errors += 1
        obs.counter(mn.SERVING_ERRORS, op=op).inc()
        if tenant is not None:
            obs.counter(mn.TENANT_ERRORS, tenant=tenant).inc()

    def _tuning_info(self) -> Optional[dict]:
        """Resolved kernel knobs + provenance for this placement's shape
        (knn_tpu.tuning — the same resolve call search_certified makes),
        so serving observability shows whether a persisted autotuner
        winner or the library defaults would drive the certified path
        on this engine's placement.  Memoized; never fatal (tuning is
        observability here, not a dispatch dependency)."""
        cached = getattr(self, "_tuning_memo", False)
        if cached is not False:
            return cached
        try:
            from knn_tpu import tuning

            p = self.program
            # the same key search_certified resolves with: the cosine
            # certificate runs on unit vectors and the dot/MIPS one on
            # norm-augmented vectors, both under the l2 kernel, so
            # their winners are keyed (and must be looked up) as l2
            cert_metric = ("l2" if p.metric in ("cosine", "dot")
                           else p.metric)
            knobs, info = tuning.resolve_full(
                p.n_train, self._dim, self.k, metric=cert_metric,
                dtype=p._dtype_key)
            memo = {"resolved_knobs": knobs, **info}
        except Exception:  # pragma: no cover - backend-less stats call
            memo = None
        self._tuning_memo = memo
        return memo

    def stats(self, *, include_slo: bool = True) -> dict:
        """Compile/dispatch accounting + request latency percentiles —
        the serving metrics JobResult/bench surface.  When telemetry is
        enabled, also carries the ``slo`` section: one burn-rate
        evaluation pass over the process-wide objectives
        (knn_tpu.obs.slo) — so every stats() consumer sees breach state
        next to the raw numbers it would otherwise misjudge.
        ``include_slo=False`` skips that pass for callers that already
        ran their own (the health report evaluates once and reads every
        engine's raw stats alongside)."""
        tuning_info = self._tuning_info()
        slo_section = (obs.slo_report()
                       if include_slo and obs.enabled() else None)
        # the slowest-requests exemplar table (trace ids of the worst
        # recent samples, no inline waterfalls at this altitude —
        # /statusz carries those).  Present only while telemetry is on:
        # the disabled stats() shape is part of the obs-off contract.
        slowest = None
        if obs.enabled():
            try:
                from knn_tpu.obs import waterfall

                slowest = waterfall.slowest_table(with_waterfalls=False)
            except Exception:  # pragma: no cover - stats must not die
                slowest = []
        # the placed program's last certified pipeline-overlap run (the
        # two-stage coarse/rescore pipeline, ShardedKNN._certify_pallas
        # overlap=True): absent until one happened on this placement, so
        # the default stats() shape is untouched
        pipeline = getattr(self.program, "_last_pipeline", None)
        # the shadow audit sampler's quality section: present only when
        # the sampler is armed (rate > 0 AND telemetry on), so both the
        # obs-off and the audit-off stats() shapes are unchanged
        quality = None
        if obs.enabled():
            try:
                if obs.audit.audit_rate() > 0:
                    quality = obs.audit.status()
            except Exception:  # pragma: no cover - stats must not die
                quality = None
        with self._lock:
            return {
                **({"tuning": tuning_info} if tuning_info else {}),
                **({"pipeline": dict(pipeline)} if pipeline else {}),
                **({"slo": slo_section} if slo_section else {}),
                **({"quality": quality} if quality else {}),
                **({"slowest_requests": slowest}
                   if slowest is not None else {}),
                "buckets": list(self.buckets),
                "compile_count": int(sum(self._compiles.values())),
                "executables": len(self._execs),
                "per_bucket_compiles": {
                    int(b): int(c) for b, c in sorted(self._compiles.items())
                },
                "per_bucket_dispatches": {
                    int(b): int(c) for b, c in sorted(self._dispatches.items())
                },
                "requests": self._requests,
                # lifetime truth, alongside the window percentiles: the
                # latency deque is bounded, so on a long-running engine
                # latency_ms["count"] is the window fill, NOT the total
                "requests_total": self._requests,
                "queries_total": self._queries,
                "errors_total": self._errors,
                "donate_queries": self.donate_queries,
                "latency_ms": latency_summary(self._latencies_s),
            }
