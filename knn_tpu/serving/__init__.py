"""Shape-bucketed serving engine — the query-traffic subsystem.

The batch library (``ShardedKNN``) compiles one SPMD program per exact
query-batch shape and runs strictly synchronously; a realistic stream of
variable-size requests recompiles repeatedly and leaves the device idle
between dispatches.  This package turns it into a throughput engine:

- :mod:`~knn_tpu.serving.buckets` — the geometric bucket ladder that
  bounds the compile cache at O(log(max/min)) executables;
- :mod:`~knn_tpu.serving.engine` — :class:`ServingEngine`: precompiled
  (AOT) per-bucket executables with ``warmup()``, async dispatch-ahead
  handles, donated query placements, trace replay, and full
  compile/dispatch/latency accounting;
- :mod:`~knn_tpu.serving.queue` — :class:`QueryQueue`: dynamic
  micro-batching of concurrent small requests under a max-wait deadline.

Padding is arithmetic-transparent: pad rows are whole zero queries
whose outputs are sliced away, and every query row's result is
independent of its batchmates — bucketed results are bitwise identical
to a direct ``ShardedKNN.search`` of the same placed batch, and
neighbor identity + tie-break order match the unpadded direct call on
every backend (distances additionally match bitwise on TPU, whose MXU
reduction order is batch-shape invariant; see serving.engine).

Admission control (:mod:`~knn_tpu.serving.admission`) layers onto the
queue and is OFF by default: bounded depth with explicit rejection,
deadline-aware load shedding, per-tenant token-bucket quotas, and
starvation-safe aged-priority ordering — the controls the measured
latency-vs-throughput knee (knn_tpu.loadgen) motivates.

Entry points: ``ShardedKNN.search_bucketed()`` for the one-liner,
``ServingEngine`` + ``QueryQueue`` for a long-running service,
``--serve-buckets`` on the CLI, the ``serving`` mode in bench.py.
"""

from knn_tpu.serving.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionError,
    DeadlineError,
    QueueFullError,
    QuotaExceededError,
)
from knn_tpu.serving.buckets import (
    DEFAULT_MAX_BUCKET,
    DEFAULT_MIN_BUCKET,
    bucket_for,
    bucket_ladder,
    parse_buckets,
    split_sizes,
)
from knn_tpu.serving.engine import ServingEngine, latency_summary
from knn_tpu.serving.queue import QueryQueue

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "AdmissionError",
    "DeadlineError",
    "QueueFullError",
    "QuotaExceededError",
    "DEFAULT_MAX_BUCKET",
    "DEFAULT_MIN_BUCKET",
    "bucket_for",
    "bucket_ladder",
    "parse_buckets",
    "split_sizes",
    "ServingEngine",
    "latency_summary",
    "QueryQueue",
]
