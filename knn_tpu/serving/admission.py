"""Deadline-aware admission control for the serving queue — shed, don't
collapse.

An unbounded :class:`~knn_tpu.serving.queue.QueryQueue` under overload
grows its backlog without limit: every request is eventually served,
long after its caller stopped caring, and the latency distribution
collapses for everyone.  The measured knee curve (knn_tpu.loadgen.knee)
says exactly where that happens; this module supplies the controls the
knee motivates:

- **bounded depth** — past ``max_depth`` OUTSTANDING requests (queued
  plus in flight: dispatch-ahead drains the pending list into the
  device pipeline almost instantly, so a pending-only bound would
  never bind), ``submit()`` raises :class:`QueueFullError` instead of
  growing the backlog: an explicit ``Rejected`` outcome the caller (or
  load balancer) can act on, costing zero device time;
- **deadline-aware shedding** — a request whose deadline cannot be met
  given the current queue-wait estimate is refused at submit
  (:class:`DeadlineError`, reason ``deadline``), and one whose deadline
  expires while queued is shed at dispatch time (reason ``expired``)
  before it wastes a device pass nobody will read;
- **per-tenant token-bucket quotas** — each tenant spends tokens
  (refilled at ``rate_qps``, capped at ``burst``) per request; an
  exhausted bucket rejects with :class:`QuotaExceededError`, so one
  tenant's burst cannot starve the rest of the queue's capacity;
- **starvation-safe priority ordering** — lower ``priority`` dispatches
  first, but every queued request's effective priority decays by one
  level per ``aging_s`` seconds of wait, so a low-priority request can
  be delayed, never starved (tests/test_admission.py pins it).

Everything is **off by default**: a ``QueryQueue`` built without an
:class:`AdmissionConfig` behaves bitwise-identically to the pre-admission
queue — same results, same ``stats()`` shape (pinned by test).  All
decisions surface through the ``knn_tpu_admission_*`` catalog metrics
and the queue's ``stats()["admission"]`` section.

Tenant ids are METRIC LABELS: every distinct string grows per-tenant
state for the process lifetime (token buckets, stats slots, registry
series, per-tenant SLO gauges/breach state).  Use a bounded set of
tenant classes (product tiers, service names), never per-user or
per-request ids — the standard Prometheus label-cardinality
discipline.

Env knobs (``AdmissionConfig.from_env``; tests/conftest.py isolates the
``KNN_TPU_ADMISSION_*`` family):

- ``KNN_TPU_ADMISSION_MAX_DEPTH`` — pending-request bound;
- ``KNN_TPU_ADMISSION_SHED`` — ``1`` enables deadline shedding;
- ``KNN_TPU_ADMISSION_DEFAULT_DEADLINE_MS`` — deadline applied to
  requests that carry none;
- ``KNN_TPU_ADMISSION_QUOTAS`` — ``tenant:rate[:burst],...``;
- ``KNN_TPU_ADMISSION_PRIORITIES`` — ``tenant:level,...`` (lower
  dispatches first);
- ``KNN_TPU_ADMISSION_AGING_MS`` — wait per priority level of decay.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from knn_tpu import obs
from knn_tpu.obs import names as mn

#: tenant label used for untagged traffic in the admission metrics
UNTAGGED = "-"

#: env-knob prefix (conftest isolates everything under it)
ENV_PREFIX = "KNN_TPU_ADMISSION_"


class AdmissionError(RuntimeError):
    """A request the admission controller refused or shed; ``reason``
    is the machine-readable outcome tag the metrics/loadgen record
    (overridable per instance so one exception class can carry both
    the submit-time ``deadline`` and dispatch-time ``expired`` tags
    under the SAME vocabulary the metrics use)."""

    reason = "rejected"

    def __init__(self, message: str, *, tenant: Optional[str] = None,
                 reason: Optional[str] = None):
        super().__init__(message)
        self.tenant = tenant
        if reason is not None:
            self.reason = reason


class QueueFullError(AdmissionError):
    """Pending depth reached ``max_depth`` — explicit rejection instead
    of unbounded backlog growth."""

    reason = "queue_full"


class QuotaExceededError(AdmissionError):
    """The tenant's token bucket is empty."""

    reason = "quota"


class DeadlineError(AdmissionError):
    """The deadline cannot be met (at submit) or already expired (at
    dispatch) — shed before wasting device time."""

    reason = "deadline"


def parse_quotas(text: str) -> Dict[str, Tuple[float, float]]:
    """``tenant:rate[:burst],...`` -> quota dict — ONE grammar for the
    env knob and the CLI flag (burst defaults to max(1, rate))."""
    quotas: Dict[str, Tuple[float, float]] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) not in (2, 3):
            raise ValueError(
                f"quota entry {part!r}: expected tenant:rate[:burst]")
        rate = float(bits[1])
        burst = float(bits[2]) if len(bits) == 3 else max(1.0, rate)
        quotas[bits[0]] = (rate, burst)
    return quotas


class _TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill, ``burst`` cap.
    ``take`` is called under the controller lock (no internal one)."""

    __slots__ = ("rate", "burst", "_tokens", "_t")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)  # start full: cold tenants may burst
        self._t = now

    def take(self, now: float, n: float = 1.0) -> bool:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


@dataclass(frozen=True)
class AdmissionConfig:
    """Declarative admission policy; every field optional and off by
    default — an all-defaults config admits everything FIFO, exactly
    like no config at all (but carries the accounting)."""

    #: outstanding-request bound (queued + in flight); None = unbounded
    #: (pre-admission behavior)
    max_depth: Optional[int] = None
    #: enable deadline-aware shedding (submit-time estimate + queued
    #: expiry); requests without a deadline are never shed
    shed: bool = False
    #: deadline applied to requests submitted without one (ms)
    default_deadline_ms: Optional[float] = None
    #: tenant -> (rate_qps, burst) token-bucket quota
    quotas: Dict[str, Tuple[float, float]] = field(default_factory=dict)
    #: tenant -> priority level (lower dispatches first; default 0)
    priorities: Dict[str, int] = field(default_factory=dict)
    #: seconds of queue wait per priority level of aging decay — the
    #: starvation-safety constant (a level-5 tenant waiting 5*aging_s
    #: competes evenly with a fresh level-0 request)
    aging_s: float = 0.25

    def validate(self) -> None:
        if self.max_depth is not None and self.max_depth < 1:
            raise ValueError(
                f"max_depth must be >= 1, got {self.max_depth}")
        if (self.default_deadline_ms is not None
                and self.default_deadline_ms <= 0):
            raise ValueError(
                f"default_deadline_ms must be > 0, got "
                f"{self.default_deadline_ms}")
        for tenant, (rate, burst) in self.quotas.items():
            if rate <= 0 or burst < 1:
                raise ValueError(
                    f"quota for tenant {tenant!r} must have rate > 0 and "
                    f"burst >= 1, got ({rate}, {burst})")
        if self.aging_s <= 0:
            raise ValueError(f"aging_s must be > 0, got {self.aging_s}")

    @classmethod
    def from_env(cls, environ=None) -> Optional["AdmissionConfig"]:
        """The env-configured policy, or None when no ``KNN_TPU_
        ADMISSION_*`` knob is set (so env-free processes keep the
        bitwise-identical disabled path).  An UNRECOGNIZED name under
        the prefix is an error, not a no-op: a typo'd knob would
        otherwise enable admission with the intended control silently
        absent."""
        env = os.environ if environ is None else environ
        known = {ENV_PREFIX + k for k in
                 ("MAX_DEPTH", "SHED", "DEFAULT_DEADLINE_MS", "QUOTAS",
                  "PRIORITIES", "AGING_MS")}
        present = {k for k in env if k.startswith(ENV_PREFIX)}
        if not present:
            return None
        unknown = present - known
        if unknown:
            raise ValueError(
                f"unrecognized admission env knob(s) "
                f"{sorted(unknown)}; known: {sorted(known)}")
        try:
            quotas = parse_quotas(env.get(ENV_PREFIX + "QUOTAS", ""))
        except ValueError as e:
            raise ValueError(f"{ENV_PREFIX}QUOTAS: {e}") from e
        priorities = {}
        for part in env.get(ENV_PREFIX + "PRIORITIES", "").split(","):
            part = part.strip()
            if not part:
                continue
            tenant, _, level = part.partition(":")
            priorities[tenant] = int(level or 0)
        depth = env.get(ENV_PREFIX + "MAX_DEPTH")
        ddl = env.get(ENV_PREFIX + "DEFAULT_DEADLINE_MS")
        aging = env.get(ENV_PREFIX + "AGING_MS")
        cfg = cls(
            max_depth=int(depth) if depth else None,
            shed=env.get(ENV_PREFIX + "SHED", "").strip().lower()
            in ("1", "true", "on", "yes"),
            default_deadline_ms=float(ddl) if ddl else None,
            quotas=quotas,
            priorities=priorities,
            aging_s=float(aging) / 1e3 if aging else 0.25,
        )
        cfg.validate()
        return cfg


class AdmissionController:
    """The queue-side policy engine: one per admission-enabled
    :class:`QueryQueue`.  All mutation happens under one lock; the
    wait-time estimator is fed by the queue's completer thread."""

    #: EWMA smoothing for the per-row service-time estimate
    _ALPHA = 0.2

    def __init__(self, config: AdmissionConfig, *,
                 base_wait_s: float = 0.0):
        config.validate()
        self.config = config
        self._lock = threading.Lock()
        self._buckets: Dict[str, _TokenBucket] = {}
        #: EWMA seconds of service per query row (None until the first
        #: completion feeds it — submit-time shedding needs an estimate,
        #: and refusing to guess beats shedding on a fabricated one)
        self._row_s: Optional[float] = None
        #: the micro-batching deadline: a floor every wait estimate
        #: carries even when the queue is empty
        self._base_wait_s = float(base_wait_s)
        self._stats = {
            "admitted": 0,
            "rejected": {},  # reason -> count
            "shed": {},  # reason -> count
            "per_tenant": {},  # tenant -> {admitted, rejected, shed}
        }
        self._g_wait = obs.gauge(mn.ADMISSION_WAIT_ESTIMATE)

    # -- estimator ---------------------------------------------------------
    def observe_service(self, rows: int, seconds: float) -> None:
        """Feed one completed batch's (rows, wall seconds) into the
        per-row EWMA the wait estimate extrapolates from."""
        if rows <= 0 or seconds <= 0:
            return
        per_row = seconds / rows
        with self._lock:
            self._row_s = (per_row if self._row_s is None else
                           (1 - self._ALPHA) * self._row_s
                           + self._ALPHA * per_row)

    def wait_estimate_s(self, rows: int) -> Optional[float]:
        """Estimated wait for a request arriving NOW behind ``rows``
        outstanding rows (queued + in flight — dispatch-ahead hides
        the backlog in the device pipeline, so counting only the
        pending list would estimate near-zero under exactly the
        overload that matters); None until a completion has fed the
        estimator."""
        with self._lock:
            row_s = self._row_s
        if row_s is None:
            return None
        est = self._base_wait_s + rows * row_s
        self._g_wait.set(est)
        return est

    # -- admission decision ------------------------------------------------
    def _tenant_slot(self, tenant: str) -> dict:
        return self._stats["per_tenant"].setdefault(
            tenant, {"admitted": 0, "rejected": 0, "shed": 0})

    def _reject(self, exc: AdmissionError, tenant: str):
        with self._lock:
            r = self._stats["rejected"]
            r[exc.reason] = r.get(exc.reason, 0) + 1
            self._tenant_slot(tenant)["rejected"] += 1
        obs.counter(mn.ADMISSION_REJECTED, tenant=tenant,
                    reason=exc.reason).inc()
        raise exc

    def admit(self, *, tenant: Optional[str], depth: int,
              rows: int, deadline_s: Optional[float],
              now: float) -> Optional[float]:
        """Admit or raise.  ``depth``/``rows`` are the OUTSTANDING
        request/row counts (queued + in flight).  Returns the ABSOLUTE
        deadline (monotonic seconds, None = none) the queue should
        track for this request.  Check order: depth (cheapest, protects
        everything downstream), deadline feasibility, then quota LAST —
        a request the deadline check would shed anyway must not spend a
        token, or transient overload would double-punish the tenant
        with spurious quota rejections after the queue drains."""
        cfg = self.config
        label = tenant if tenant is not None else UNTAGGED
        if cfg.max_depth is not None and depth >= cfg.max_depth:
            self._reject(QueueFullError(
                f"{depth} requests outstanding at max_depth "
                f"{cfg.max_depth}", tenant=tenant), label)
        if deadline_s is None and cfg.default_deadline_ms is not None:
            deadline_s = now + cfg.default_deadline_ms / 1e3
        if cfg.shed and deadline_s is not None:
            est = self.wait_estimate_s(rows)
            if est is not None and now + est > deadline_s:
                self._reject(DeadlineError(
                    f"deadline {1e3 * (deadline_s - now):.1f} ms out, "
                    f"queue wait estimate {1e3 * est:.1f} ms",
                    tenant=tenant), label)
        quota = cfg.quotas.get(label)
        if quota is not None:
            with self._lock:
                b = self._buckets.get(label)
                if b is None:
                    b = self._buckets[label] = _TokenBucket(
                        quota[0], quota[1], now)
                ok = b.take(now)
            if not ok:
                self._reject(QuotaExceededError(
                    f"tenant {label!r} over quota "
                    f"({quota[0]:g} q/s, burst {quota[1]:g})",
                    tenant=tenant), label)
        with self._lock:
            self._stats["admitted"] += 1
            self._tenant_slot(label)["admitted"] += 1
        obs.counter(mn.ADMISSION_ADMITTED, tenant=label).inc()
        return deadline_s

    def record_shed(self, tenant: Optional[str],
                    reason: str = "expired") -> None:
        """An admitted-then-expired request dropped at dispatch time."""
        label = tenant if tenant is not None else UNTAGGED
        with self._lock:
            s = self._stats["shed"]
            s[reason] = s.get(reason, 0) + 1
            self._tenant_slot(label)["shed"] += 1
        obs.counter(mn.ADMISSION_SHED, tenant=label, reason=reason).inc()

    # -- ordering ----------------------------------------------------------
    def priority_of(self, tenant: Optional[str]) -> int:
        return self.config.priorities.get(
            tenant if tenant is not None else UNTAGGED, 0)

    def effective_priority(self, priority: int, waited_s: float) -> float:
        """Aged priority: one level of decay per ``aging_s`` of wait —
        the monotone decrease that makes starvation impossible (any
        waiting request eventually outranks every fresh one)."""
        return priority - waited_s / self.config.aging_s

    def stats(self) -> dict:
        with self._lock:
            row_s = self._row_s
            out = {
                "admitted": self._stats["admitted"],
                "rejected": dict(self._stats["rejected"]),
                "shed": dict(self._stats["shed"]),
                "per_tenant": {t: dict(v) for t, v in
                               self._stats["per_tenant"].items()},
            }
        out["config"] = {
            "max_depth": self.config.max_depth,
            "shed": self.config.shed,
            "default_deadline_ms": self.config.default_deadline_ms,
            "quotas": {t: list(q) for t, q in self.config.quotas.items()},
            "priorities": dict(self.config.priorities),
            "aging_s": self.config.aging_s,
        }
        out["row_service_estimate_us"] = (
            None if row_s is None else round(row_s * 1e6, 3))
        return out
