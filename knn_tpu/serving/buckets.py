"""Shape-bucket ladder — the compile-cache contract of the serving engine.

Every distinct query-batch shape JAX sees costs one XLA compile of the
SPMD search program (seconds through the dev relay, and the compile
happens *inline*, stalling the request that triggered it).  A realistic
traffic stream has O(unique batch sizes) shapes; padding each request up
to a small geometric ladder of bucket sizes collapses that to
O(log(max/min)) precompiled executables, after which NO request ever
compiles again.  This is the reference report's design rule #3 (fewer,
larger messages — PDF p.7) applied to the XLA compile cache instead of
the network.

Dependency-free (no numpy/jax) so the CLI/config layers can validate
``--serve-buckets`` flags without paying the JAX import.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

#: default ladder bounds: 8 buckets (32..4096) cover single-query traffic
#: through bench-sized sweeps; requests above the top bucket are split.
DEFAULT_MIN_BUCKET = 32
DEFAULT_MAX_BUCKET = 4096
DEFAULT_GROWTH = 2.0


def bucket_ladder(
    min_bucket: int = DEFAULT_MIN_BUCKET,
    max_bucket: int = DEFAULT_MAX_BUCKET,
    growth: float = DEFAULT_GROWTH,
) -> Tuple[int, ...]:
    """Geometric bucket sizes from ``min_bucket`` up to and including
    ``max_bucket``: each rung is ``ceil(prev * growth)``, and the top rung
    is forced to exactly ``max_bucket`` so the ladder always covers the
    full configured range."""
    if min_bucket < 1:
        raise ValueError(f"min_bucket must be >= 1, got {min_bucket}")
    if max_bucket < min_bucket:
        raise ValueError(
            f"max_bucket={max_bucket} must be >= min_bucket={min_bucket}")
    if growth <= 1.0:
        raise ValueError(f"growth must be > 1, got {growth}")
    sizes: List[int] = []
    b = min_bucket
    while b < max_bucket:
        sizes.append(b)
        b = max(int(b * growth + 0.999999), b + 1)
    sizes.append(max_bucket)
    return tuple(sizes)


def normalize_ladder(buckets: Sequence[int]) -> Tuple[int, ...]:
    """Validate an explicit ladder: positive ints, deduplicated, ascending."""
    sizes = sorted({int(b) for b in buckets})
    if not sizes:
        raise ValueError("bucket ladder is empty")
    if sizes[0] < 1:
        raise ValueError(f"bucket sizes must be >= 1, got {sizes[0]}")
    return tuple(sizes)


def parse_buckets(spec: Union[str, Sequence[int], None]) -> Optional[Tuple[int, ...]]:
    """``--serve-buckets`` flag -> ladder.  ``None``/empty -> None (serving
    disabled); ``"auto"`` -> the default geometric ladder; ``"a,b,c"`` or a
    sequence of ints -> explicit validated ladder."""
    if spec is None:
        return None
    if isinstance(spec, str):
        s = spec.strip().lower()
        if not s:
            return None
        if s == "auto":
            return bucket_ladder()
        try:
            sizes = [int(part) for part in s.split(",") if part.strip()]
        except ValueError:
            raise ValueError(
                f"bad bucket spec {spec!r}; expected 'auto' or a "
                f"comma-separated int list like '64,128,256'"
            ) from None
        return normalize_ladder(sizes)
    return normalize_ladder(spec)


def bucket_for(ladder: Sequence[int], n: int) -> Optional[int]:
    """Smallest bucket >= ``n``, or None when ``n`` exceeds the top bucket
    (callers split such requests via :func:`split_sizes`)."""
    if n < 1:
        raise ValueError(f"request size must be >= 1, got {n}")
    for b in ladder:
        if b >= n:
            return b
    return None


def split_sizes(n: int, max_bucket: int) -> List[int]:
    """Chunk an oversized request into ``max_bucket``-row pieces plus a
    bucketable tail — every piece then hits a precompiled executable."""
    if n < 1:
        raise ValueError(f"request size must be >= 1, got {n}")
    out = [max_bucket] * (n // max_bucket)
    if n % max_bucket:
        out.append(n % max_bucket)
    return out
