"""Dynamic micro-batching: coalesce concurrent small requests into one
bucketed dispatch.

Single-query (or few-query) requests are the worst case for a systolic
accelerator — each dispatch pays full program-launch latency for almost
no math.  :class:`QueryQueue` holds arriving requests for at most
``max_wait_ms`` and concatenates everything that accumulates into ONE
engine dispatch (padded up the bucket ladder), then scatters the result
rows back to each caller's future.  Because every query row's result is
independent of its batchmates (see serving.engine), the scattered
results are bitwise identical to submitting the coalesced batch
directly — coalescing is purely a throughput/latency trade governed by
``max_wait_ms``.

Two threads: the **batcher** collects + dispatches (asynchronously — JAX
returns before the device finishes), the **completer** blocks on
transfers and resolves futures.  The batcher therefore keeps dispatching
batch N+1 while batch N executes: micro-batching and dispatch-ahead
compose.

**Admission control** (knn_tpu.serving.admission) is layered on top and
OFF by default: with ``max_depth``/``admission`` unset the queue's
results and ``stats()`` are bitwise identical to the pre-admission
queue (pinned in tests/test_admission.py).  Enabled, ``submit()`` can
raise an explicit :class:`~knn_tpu.serving.admission.AdmissionError`
(bounded depth, per-tenant quota, unmeetable deadline), queued requests
whose deadline expires are shed at dispatch time instead of wasting a
device pass, and dispatch order becomes aged-priority instead of FIFO —
shed, don't collapse.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.analysis.annotations import hot_path
from knn_tpu.obs import names as mn
from knn_tpu.serving.admission import (
    AdmissionConfig,
    AdmissionController,
    DeadlineError,
)


class _Pending:
    """One queued request: the payload plus the telemetry/admission
    fields that ride with it (arrival keeps the max-wait deadline per
    request; the trace id keeps each request's telemetry its own even
    after coalescing — one trace_id per REQUEST, never per batch)."""

    __slots__ = ("q", "fut", "t_arr", "tid", "tenant", "deadline",
                 "priority")

    def __init__(self, q, fut, t_arr, tid, tenant=None, deadline=None,
                 priority=0):
        self.q = q
        self.fut = fut
        self.t_arr = t_arr
        self.tid = tid
        self.tenant = tenant
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.priority = priority


class QueryQueue:
    """Micro-batching frontend over a :class:`~knn_tpu.serving.engine.
    ServingEngine`.

    ``submit(queries)`` returns a ``concurrent.futures.Future`` resolving
    to ``(distances, indices)`` (op="search") or ``labels`` (op="predict")
    for exactly the submitted rows.  A batch dispatches as soon as
    ``max_rows`` rows accumulate, or when the OLDEST pending request has
    waited ``max_wait_ms`` — the deadline bounds worst-case added latency.

    ``max_depth`` bounds OUTSTANDING work — queued plus in flight
    (`submit` raises :class:`~knn_tpu.serving.admission.QueueFullError`
    past it); ``admission`` is the full policy (quotas, deadline
    shedding, priorities — knn_tpu.serving.admission).  Both default
    off.

    Thread-safety: guarded by ``self._cond`` (a Condition — the same
    ``with``-protocol the ``locked-mutation`` checker reads; the
    completer thread's single-writer service-rate state is the one
    documented exception, carried in the suppression file).

    Use as a context manager, or call :meth:`close` (flushes pending
    requests, then joins both threads).
    """

    def __init__(
        self,
        engine,
        *,
        max_wait_ms: float = 2.0,
        max_rows: Optional[int] = None,
        op: str = "search",
        max_depth: Optional[int] = None,
        admission: Optional[AdmissionConfig] = None,
    ):
        from knn_tpu.serving.engine import OPS

        if op not in OPS:
            raise ValueError(f"unknown op {op!r}; expected one of {OPS}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if max_depth is not None and admission is not None \
                and admission.max_depth is not None \
                and admission.max_depth != max_depth:
            raise ValueError(
                f"conflicting depth bounds: max_depth={max_depth} vs "
                f"admission.max_depth={admission.max_depth}")
        self.engine = engine
        self.op = op
        self.max_wait_s = max_wait_ms / 1e3
        self.max_rows = int(max_rows or engine.buckets[-1])
        if admission is None and max_depth is not None:
            # a bare depth bound is just the smallest possible policy
            admission = AdmissionConfig(max_depth=max_depth)
        elif admission is not None and max_depth is not None \
                and admission.max_depth is None:
            import dataclasses

            admission = dataclasses.replace(admission,
                                            max_depth=max_depth)
        #: None = admission disabled = pre-admission behavior, bitwise
        self._ctrl: Optional[AdmissionController] = (
            None if admission is None else
            AdmissionController(admission, base_wait_s=self.max_wait_s))
        self._cond = threading.Condition()
        self._pending: List[_Pending] = []
        self._pending_rows = 0
        #: OUTSTANDING work = admitted and not yet resolved (queued OR
        #: in flight through the engine's async pipeline).  Admission's
        #: depth bound and wait estimate judge THIS, not the pending
        #: list alone: dispatch-ahead drains pending into the device
        #: pipeline almost instantly, so a pending-only bound would
        #: never bind and overload would hide in flight (exactly the
        #: collapse admission exists to prevent).
        self._out_req = 0
        self._out_rows = 0
        #: previous batch-completion time (completer thread only):
        #: feeds the inter-completion service-rate estimate
        self._last_done_t: Optional[float] = None
        self._closed = False
        self._stats = {"requests": 0, "dispatches": 0, "coalesced_rows": 0,
                       "errors": 0}
        #: queue-depth gauges: scrape-time truth about the backlog the
        #: max-wait deadline is currently holding
        self._g_depth_req = obs.gauge(mn.QUEUE_DEPTH_REQUESTS)
        self._g_depth_rows = obs.gauge(mn.QUEUE_DEPTH_ROWS)
        #: ARRIVAL-to-result latency of queued requests (bounded window
        #: of (monotonic ts, seconds) pairs, so the summary can label
        #: its wall span): the engine's own percentiles start at engine
        #: dispatch and so exclude the micro-batching wait — this one is
        #: what a caller tuning max_wait_ms actually experiences.
        #: deque.append is atomic, so the completer records without
        #: taking the cond.
        self._lat: deque = deque(maxlen=4096)
        self._done: _queue.Queue = _queue.Queue()
        self._batcher_t = threading.Thread(
            target=self._batcher, name="knn-serving-batcher", daemon=True)
        self._completer_t = threading.Thread(
            target=self._completer, name="knn-serving-completer", daemon=True)
        self._batcher_t.start()
        self._completer_t.start()
        # worker-thread liveness feeds the readiness probe (/healthz)
        obs.health.register_queue(self)

    # -- client side -------------------------------------------------------
    # np.asarray/ascontiguousarray coerce the caller's HOST request
    # array (never a device fetch); int() reads numpy shape tuples
    @hot_path(allow=("np.asarray", "np.ascontiguousarray", "int"))
    def submit(self, queries, *, tenant: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               priority: Optional[int] = None) -> Future:
        """Queue ``queries`` for a coalesced dispatch.  ``tenant`` tags
        the request for per-tenant metrics/SLOs and quota accounting;
        ``deadline_ms`` (relative to now) enables deadline-aware
        shedding when the queue's admission policy has it on;
        ``priority`` overrides the tenant's configured level (lower
        dispatches first; ignored without admission).  Raises
        :class:`~knn_tpu.serving.admission.AdmissionError` on an
        explicit rejection — the request costs nothing downstream."""
        q = np.ascontiguousarray(np.asarray(queries, dtype=np.float32))
        if q.ndim != 2 or q.shape[1] != self.engine._dim:
            # reject HERE, not in the batcher: a malformed request that
            # reached the coalescing concatenate would kill the batch it
            # rode in with (and the batcher guards survive, see _batcher)
            raise ValueError(
                f"queries must be [N, {self.engine._dim}], got shape "
                f"{q.shape}")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be > 0, got {deadline_ms}")
        fut: Future = Future()
        tid = obs.new_trace_id()  # THIS request's id, coalescing-proof
        # the loadgen driver (and any caller) can join this request's
        # telemetry by id without reaching into queue internals — the
        # same contract as the dispatch_t stamp below
        fut.trace_id = tid
        # arrival is stamped BEFORE the cond: submit-side lock wait is
        # part of what the caller experiences (it lands in queue_wait,
        # the admission span below, and the request total — not in a
        # blind spot between them)
        now = time.monotonic()
        with self._cond:
            if self._closed:
                raise RuntimeError("QueryQueue is closed")
            deadline = (None if deadline_ms is None
                        else now + deadline_ms / 1e3)
            prio = 0
            if self._ctrl is not None:
                # admission decides INSIDE the lock: depth/row reads and
                # the append must be one atomic judgment, or two racing
                # submits could both pass a max_depth of N-1.  The
                # controller never takes the cond, so lock order is safe.
                deadline = self._ctrl.admit(
                    tenant=tenant, depth=self._out_req,
                    rows=self._out_rows,
                    deadline_s=deadline, now=now)
                prio = (self._ctrl.priority_of(tenant)
                        if priority is None else int(priority))
            self._pending.append(_Pending(
                q, fut, now, tid, tenant, deadline, prio))
            self._pending_rows += q.shape[0]
            self._out_req += 1
            self._out_rows += q.shape[0]
            self._stats["requests"] += 1
            self._g_depth_req.set(len(self._pending))
            self._g_depth_rows.set(self._pending_rows)
            self._cond.notify_all()
        if tid is not None:
            # the admission slice of the request's life (lock wait +
            # the admit decision).  It runs INSIDE the queue_wait
            # window (t_arr is stamped before admit), so the waterfall
            # reconstruction carves it OUT of queue_wait — emitted
            # separately here precisely so that carve is measurable.
            obs.record_span(
                "serving.admission", tid, time.monotonic() - now,
                rows=int(q.shape[0]),
                **({"tenant": tenant} if tenant is not None else {}))
        obs.counter(mn.QUEUE_REQUESTS).inc()
        if tenant is not None:
            obs.counter(mn.TENANT_REQUESTS, tenant=tenant).inc()
        return fut

    def submit_write(self, kind: str, *, vectors=None, ids=None,
                     tenant: Optional[str] = None) -> Future:
        """Writes as a first-class op beside queries: route an
        ``insert``/``delete`` to the engine's mutable index
        (:meth:`~knn_tpu.index.mutable.MutableServingEngine.
        apply_write`) and return a resolved Future carrying the write
        report (or the index's refusal).  Writes apply IMMEDIATELY
        under the index's own lock — snapshot pinning, not queue
        ordering, is what makes them atomic against in-flight
        micro-batches — so they never ride (or stall) a coalesced
        device dispatch.  The queue's ``stats()`` gains a ``writes``
        section once any write passed through (the write-free stats
        shape is part of the pre-index bitwise contract)."""
        apply = getattr(self.engine, "apply_write", None)
        if apply is None:
            raise ValueError(
                f"this queue's engine ({type(self.engine).__name__}) "
                f"serves an immutable placement — writes need a "
                f"MutableServingEngine (knn_tpu.index, docs/INDEX.md)")
        with self._cond:
            if self._closed:
                raise RuntimeError("QueryQueue is closed")
        fut: Future = Future()
        tid = obs.new_trace_id()
        fut.trace_id = tid
        t0 = time.monotonic()
        try:
            out = apply(kind, vectors=vectors, ids=ids)
        except Exception as e:  # noqa: BLE001 — outcome, not crash
            self._count_write(kind, error=True, tenant=tenant)
            fut.set_exception(e)
        else:
            self._count_write(kind, error=False, tenant=tenant)
            fut.set_result(out)
        fut.dispatch_t = time.monotonic()
        obs.record_span(
            "serving.write", tid, time.monotonic() - t0, kind=kind,
            **({"tenant": tenant} if tenant is not None else {}))
        return fut

    def _count_write(self, kind: str, *, error: bool,
                     tenant: Optional[str]) -> None:
        with self._cond:
            w = self._stats.setdefault(
                "writes", {"insert": 0, "delete": 0, "errors": 0})
            if error:
                w["errors"] += 1
            elif kind in ("insert", "delete"):
                w[kind] += 1
        if tenant is not None:
            obs.counter(mn.TENANT_REQUESTS, tenant=tenant).inc()
            if error:
                obs.counter(mn.TENANT_ERRORS, tenant=tenant).inc()

    def close(self) -> None:
        """Flush every pending request, then stop both threads."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._batcher_t.join()
        self._completer_t.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self) -> dict:
        from knn_tpu.serving.engine import latency_summary

        with self._cond:
            out = dict(self._stats)
        out["latency_ms"] = latency_summary(list(self._lat))
        # present ONLY when admission is enabled: the disabled queue's
        # stats() shape is part of the bitwise-identity contract
        if self._ctrl is not None:
            out["admission"] = self._ctrl.stats()
        out["engine"] = self.engine.stats()
        return out

    # -- worker threads ----------------------------------------------------
    @staticmethod
    def _resolve(fut: Future, value=None, exc: Optional[Exception] = None):
        """Resolve a future, tolerating client-side cancellation: a
        caller that gave up (fut.cancel() after a timeout) must never
        crash the worker thread that eventually completes its batch."""
        if fut.cancelled():
            return
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)
        except Exception:  # noqa: BLE001 — cancelled in the race window
            pass

    def _select_indices(self, now: float) -> List[int]:
        """Indices (into ``_pending``) of the next batch, in dispatch
        order.  FIFO without admission; with it, AGED priority — lower
        ``priority - waited/aging_s`` first, arrival-stable among ties —
        so configured priorities reorder under load but every waiting
        request's rank rises monotonically (starvation-safe by
        construction).  Either way requests stay whole and the batch
        stops at the first candidate that would overflow ``max_rows``
        (no skip-scan: size must never become a starvation channel)."""
        if self._ctrl is None or (
                not self._ctrl.config.priorities
                and all(p.priority == 0 for p in self._pending)):
            # no configured tenant levels AND no per-request override
            # in the backlog: pure FIFO (explicit priority= on submit
            # must reorder even without a tenant priority table)
            order = range(len(self._pending))
        else:
            order = sorted(
                range(len(self._pending)),
                key=lambda i: (self._ctrl.effective_priority(
                    self._pending[i].priority,
                    now - self._pending[i].t_arr), i))
        picked: List[int] = []
        rows = 0
        for i in order:
            r = self._pending[i].q.shape[0]
            if picked and rows + r > self.max_rows:
                break
            picked.append(i)
            rows += r
            if rows >= self.max_rows:
                break
        return picked

    def _take_batch(self):
        """Block until a batch is due (rows >= max_rows, deadline hit, or
        closing with work pending); returns ``(batch, shed)`` — ``shed``
        are expired requests to resolve OUTSIDE the lock (a future's
        done-callback may re-enter submit; resolving under the cond
        could deadlock).  ``(None, shed)`` means closed and drained.
        Entries keep their arrival times so the completer can report
        honest arrival-to-result latency."""
        shed: List[_Pending] = []
        with self._cond:
            while True:
                # deadline-aware shedding: sweep requests whose deadline
                # already passed BEFORE judging batch readiness — an
                # expired request must neither ride a batch (wasted
                # device rows) nor hold the max-wait clock
                if (self._ctrl is not None and self._ctrl.config.shed
                        and self._pending):
                    now = time.monotonic()
                    live = []
                    for p in self._pending:
                        if p.deadline is not None and p.deadline < now:
                            shed.append(p)
                            self._pending_rows -= p.q.shape[0]
                        else:
                            live.append(p)
                    if shed and len(live) != len(self._pending):
                        self._pending = live
                        self._g_depth_req.set(len(self._pending))
                        self._g_depth_rows.set(self._pending_rows)
                        # deliver the expired futures NOW (outside the
                        # lock) instead of holding them for up to a full
                        # max-wait; the next call resumes batch-taking
                        return [], shed
                if self._pending:
                    if self._closed or self._pending_rows >= self.max_rows:
                        break
                    # each request keeps its own arrival time, so one
                    # left behind by a full earlier batch retains its
                    # original deadline — max_wait_ms stays a real
                    # worst-case bound, not a restartable clock
                    wake = self._pending[0].t_arr + self.max_wait_s
                    if self._ctrl is not None and self._ctrl.config.shed:
                        # ...and never sleep PAST a request deadline: a
                        # large max-wait must not hold an expired
                        # future until the dispatch clock fires (the
                        # sweep above can only shed while awake)
                        for p in self._pending:
                            if p.deadline is not None and p.deadline < wake:
                                wake = p.deadline
                    wait = wake - time.monotonic()
                    if wait <= 0:
                        if wake < self._pending[0].t_arr + self.max_wait_s:
                            continue  # a deadline fired, not the batch
                            # clock: re-sweep and keep coalescing
                        break
                    self._cond.wait(timeout=wait)
                elif self._closed:
                    return None, shed
                else:
                    self._cond.wait()
            # whole requests only: a request is never split across
            # micro-batches (oversize batches split inside the engine)
            now = time.monotonic()
            batch = [self._pending[i] for i in self._select_indices(now)]
            taken = set(id(p) for p in batch)
            self._pending = [p for p in self._pending
                             if id(p) not in taken]
            self._pending_rows -= sum(p.q.shape[0] for p in batch)
            self._g_depth_req.set(len(self._pending))
            self._g_depth_rows.set(self._pending_rows)
            return batch, shed

    def _retire(self, items: List[_Pending]) -> None:
        """Resolved requests leave the outstanding count — whatever the
        outcome (ok, shed, error), the admission depth frees up."""
        with self._cond:
            for p in items:
                self._out_req -= 1
                self._out_rows -= p.q.shape[0]

    def _shed_expired(self, shed: List[_Pending]) -> None:
        for p in shed:
            self._ctrl.record_shed(p.tenant, "expired")
            # reason "expired" matches the metric label above, so the
            # caller-visible outcome and knn_tpu_admission_shed_total
            # speak one vocabulary
            self._resolve(p.fut, exc=DeadlineError(
                "deadline expired while queued (shed before dispatch)",
                tenant=p.tenant, reason="expired"))
        self._retire(shed)

    # int() reads numpy shape tuples / offset scalars, all host-side
    @hot_path(allow=("int",))
    def _batcher(self) -> None:
        while True:
            batch, shed = self._take_batch()
            if shed:
                self._shed_expired(shed)
            if batch is None:
                break
            if not batch:
                continue
            try:
                # the concatenate sits INSIDE the guard: any surprise in
                # batch assembly must resolve this batch's futures, never
                # kill the batcher thread (a dead batcher hangs every
                # later request and deadlocks close())
                arrays = [p.q for p in batch]
                cat = arrays[0] if len(arrays) == 1 else np.concatenate(arrays)
                offsets = np.cumsum([0] + [a.shape[0] for a in arrays])
                # every member's queue-wait span closes at dispatch time,
                # under its OWN trace id — the coalesced engine request
                # gets a fresh batch-level id, linked below
                t_disp = time.monotonic()
                for p in batch:
                    obs.record_span(
                        "serving.queue_wait", p.tid, t_disp - p.t_arr,
                        rows=int(p.q.shape[0]),
                        **({"tenant": p.tenant}
                           if p.tenant is not None else {}))
                    obs.histogram(mn.QUEUE_WAIT).observe(
                        t_disp - p.t_arr, exemplar=p.tid)
                    # the loadgen driver reads this to record per-request
                    # dispatch time (arrival it already knows)
                    p.fut.dispatch_t = t_disp
                handle = self.engine.submit(cat, op=self.op)
                obs.emit_event(
                    "queue.dispatch", op=self.op,
                    batch_trace_id=handle.trace_id,
                    member_trace_ids=[p.tid for p in batch],
                    rows=int(offsets[-1]), requests=len(batch))
            except Exception as e:  # noqa: BLE001 — resolve, don't kill the loop
                self._record_errors(batch)
                for p in batch:
                    self._resolve(p.fut, exc=e)
                self._retire(batch)
                continue
            with self._cond:
                self._stats["dispatches"] += 1
                self._stats["coalesced_rows"] += int(offsets[-1])
            obs.counter(mn.QUEUE_DISPATCHES).inc()
            obs.counter(mn.QUEUE_COALESCED_ROWS).inc(int(offsets[-1]))
            self._done.put((handle, batch, offsets, t_disp))
        self._done.put(None)

    # -- completer thread --------------------------------------------------
    def _completer(self) -> None:
        while True:
            item = self._done.get()
            if item is None:
                break
            handle, batch, offsets, t_disp = item
            try:
                res = handle.result()
            except Exception as e:  # noqa: BLE001 — per-batch failure isolation
                self._record_errors(batch)
                for p in batch:
                    self._resolve(p.fut, exc=e)
                self._retire(batch)
                continue
            done_t = time.monotonic()
            if self._ctrl is not None:
                # feed the wait estimator: this batch's measured rows/s
                # is what the NEXT submit's shedding decision runs on.
                # Two candidate spans, take the SMALLER: dispatch-to-
                # done includes waiting behind in-flight predecessors
                # (exact when idle, ~pipeline-depth x inflated under
                # load — and the estimate multiplies by outstanding
                # rows, which already count those predecessors), while
                # the inter-completion gap is exact under saturation
                # but includes idle time at low load.  min() is right
                # in both regimes; systematic over-estimation would
                # shed deadlines that were comfortably feasible.
                span = done_t - t_disp
                prev = self._last_done_t
                if prev is not None:
                    span = min(span, done_t - prev)
                self._last_done_t = done_t
                self._ctrl.observe_service(int(offsets[-1]), span)
            for j, p in enumerate(batch):
                lo, hi = int(offsets[j]), int(offsets[j + 1])
                if self.op == "search":
                    d, i = res
                    self._resolve(p.fut, (d[lo:hi], i[lo:hi]))
                else:
                    self._resolve(p.fut, res[lo:hi])
                self._lat.append((done_t, done_t - p.t_arr))
                # arrival-to-result under the request's own trace id —
                # what a caller tuning max_wait_ms actually experiences
                # (the exemplar keeps the tail's ids joinable to traces)
                obs.histogram(mn.QUEUE_REQUEST_LATENCY).observe(
                    done_t - p.t_arr, exemplar=p.tid)
                if p.tenant is not None:
                    obs.histogram(mn.TENANT_REQUEST_LATENCY,
                                  tenant=p.tenant).observe(
                        done_t - p.t_arr, exemplar=p.tid)
                if p.tid is not None:
                    # deliver closes the span chain: batch completion to
                    # THIS member's future resolution (scatter +
                    # head-of-line in this loop), so the request's
                    # segments tile its whole life; the request span
                    # therefore ends HERE, at delivery, while the
                    # histograms above keep their historical
                    # arrival-to-batch-completion semantics
                    t_res = time.monotonic()
                    ten = ({"tenant": p.tenant}
                           if p.tenant is not None else {})
                    obs.record_span("serving.deliver", p.tid,
                                    t_res - done_t, **ten)
                    obs.record_span("serving.queued_request", p.tid,
                                    t_res - p.t_arr, op=self.op,
                                    rows=int(p.q.shape[0]),
                                    batch_trace_id=handle.trace_id,
                                    **ten)
            self._retire(batch)

    def _record_errors(self, batch: List[_Pending]) -> None:
        with self._cond:
            self._stats["errors"] += len(batch)
        obs.counter(mn.QUEUE_ERRORS).inc(len(batch))
        for p in batch:
            if p.tenant is not None:
                obs.counter(mn.TENANT_ERRORS, tenant=p.tenant).inc()
