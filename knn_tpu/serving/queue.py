"""Dynamic micro-batching: coalesce concurrent small requests into one
bucketed dispatch.

Single-query (or few-query) requests are the worst case for a systolic
accelerator — each dispatch pays full program-launch latency for almost
no math.  :class:`QueryQueue` holds arriving requests for at most
``max_wait_ms`` and concatenates everything that accumulates into ONE
engine dispatch (padded up the bucket ladder), then scatters the result
rows back to each caller's future.  Because every query row's result is
independent of its batchmates (see serving.engine), the scattered
results are bitwise identical to submitting the coalesced batch
directly — coalescing is purely a throughput/latency trade governed by
``max_wait_ms``.

Two threads: the **batcher** collects + dispatches (asynchronously — JAX
returns before the device finishes), the **completer** blocks on
transfers and resolves futures.  The batcher therefore keeps dispatching
batch N+1 while batch N executes: micro-batching and dispatch-ahead
compose.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np

from knn_tpu import obs
from knn_tpu.obs import names as mn


class QueryQueue:
    """Micro-batching frontend over a :class:`~knn_tpu.serving.engine.
    ServingEngine`.

    ``submit(queries)`` returns a ``concurrent.futures.Future`` resolving
    to ``(distances, indices)`` (op="search") or ``labels`` (op="predict")
    for exactly the submitted rows.  A batch dispatches as soon as
    ``max_rows`` rows accumulate, or when the OLDEST pending request has
    waited ``max_wait_ms`` — the deadline bounds worst-case added latency.

    Use as a context manager, or call :meth:`close` (flushes pending
    requests, then joins both threads).
    """

    def __init__(
        self,
        engine,
        *,
        max_wait_ms: float = 2.0,
        max_rows: Optional[int] = None,
        op: str = "search",
    ):
        from knn_tpu.serving.engine import OPS

        if op not in OPS:
            raise ValueError(f"unknown op {op!r}; expected one of {OPS}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self.engine = engine
        self.op = op
        self.max_wait_s = max_wait_ms / 1e3
        self.max_rows = int(max_rows or engine.buckets[-1])
        self._cond = threading.Condition()
        #: (queries, future, arrival time, trace id) — arrival rides
        #: along so the max-wait deadline is per request, not per batch
        #: window; the trace id keeps each request's telemetry its own
        #: even after coalescing (one trace_id per REQUEST, never per
        #: batch — knn_tpu.obs.trace)
        self._pending: List[Tuple[np.ndarray, Future, float, object]] = []
        self._pending_rows = 0
        self._closed = False
        self._stats = {"requests": 0, "dispatches": 0, "coalesced_rows": 0,
                       "errors": 0}
        #: queue-depth gauges: scrape-time truth about the backlog the
        #: max-wait deadline is currently holding
        self._g_depth_req = obs.gauge(mn.QUEUE_DEPTH_REQUESTS)
        self._g_depth_rows = obs.gauge(mn.QUEUE_DEPTH_ROWS)
        #: ARRIVAL-to-result latency of queued requests (bounded window
        #: of (monotonic ts, seconds) pairs, so the summary can label
        #: its wall span): the engine's own percentiles start at engine
        #: dispatch and so exclude the micro-batching wait — this one is
        #: what a caller tuning max_wait_ms actually experiences.
        #: deque.append is atomic, so the completer records without
        #: taking the cond.
        self._lat: deque = deque(maxlen=4096)
        self._done: _queue.Queue = _queue.Queue()
        self._batcher_t = threading.Thread(
            target=self._batcher, name="knn-serving-batcher", daemon=True)
        self._completer_t = threading.Thread(
            target=self._completer, name="knn-serving-completer", daemon=True)
        self._batcher_t.start()
        self._completer_t.start()
        # worker-thread liveness feeds the readiness probe (/healthz)
        obs.health.register_queue(self)

    # -- client side -------------------------------------------------------
    def submit(self, queries) -> Future:
        q = np.ascontiguousarray(np.asarray(queries, dtype=np.float32))
        if q.ndim != 2 or q.shape[1] != self.engine._dim:
            # reject HERE, not in the batcher: a malformed request that
            # reached the coalescing concatenate would kill the batch it
            # rode in with (and the batcher guards survive, see _batcher)
            raise ValueError(
                f"queries must be [N, {self.engine._dim}], got shape "
                f"{q.shape}")
        fut: Future = Future()
        tid = obs.new_trace_id()  # THIS request's id, coalescing-proof
        with self._cond:
            if self._closed:
                raise RuntimeError("QueryQueue is closed")
            self._pending.append((q, fut, time.monotonic(), tid))
            self._pending_rows += q.shape[0]
            self._stats["requests"] += 1
            self._g_depth_req.set(len(self._pending))
            self._g_depth_rows.set(self._pending_rows)
            self._cond.notify_all()
        obs.counter(mn.QUEUE_REQUESTS).inc()
        return fut

    def close(self) -> None:
        """Flush every pending request, then stop both threads."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._batcher_t.join()
        self._completer_t.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self) -> dict:
        from knn_tpu.serving.engine import latency_summary

        with self._cond:
            out = dict(self._stats)
        out["latency_ms"] = latency_summary(list(self._lat))
        out["engine"] = self.engine.stats()
        return out

    # -- worker threads ----------------------------------------------------
    @staticmethod
    def _resolve(fut: Future, value=None, exc: Optional[Exception] = None):
        """Resolve a future, tolerating client-side cancellation: a
        caller that gave up (fut.cancel() after a timeout) must never
        crash the worker thread that eventually completes its batch."""
        if fut.cancelled():
            return
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)
        except Exception:  # noqa: BLE001 — cancelled in the race window
            pass

    def _take_batch(self) -> Optional[List[Tuple[np.ndarray, Future, float, object]]]:
        """Block until a batch is due (rows >= max_rows, deadline hit, or
        closing with work pending); None means closed and drained.
        Entries keep their arrival times so the completer can report
        honest arrival-to-result latency."""
        with self._cond:
            while True:
                if self._pending:
                    if self._closed or self._pending_rows >= self.max_rows:
                        break
                    # each request keeps its own arrival time, so one
                    # left behind by a full earlier batch retains its
                    # original deadline — max_wait_ms stays a real
                    # worst-case bound, not a restartable clock
                    wait = self._pending[0][2] + self.max_wait_s - time.monotonic()
                    if wait <= 0:
                        break
                    self._cond.wait(timeout=wait)
                elif self._closed:
                    return None
                else:
                    self._cond.wait()
            # whole requests only: a request is never split across
            # micro-batches (oversize batches split inside the engine)
            batch: List[Tuple[np.ndarray, Future, float, object]] = []
            rows = 0
            while self._pending and (
                not batch or rows + self._pending[0][0].shape[0] <= self.max_rows
            ):
                batch.append(self._pending.pop(0))
                rows += batch[-1][0].shape[0]
            self._pending_rows -= rows
            self._g_depth_req.set(len(self._pending))
            self._g_depth_rows.set(self._pending_rows)
            return batch

    def _batcher(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                break
            try:
                # the concatenate sits INSIDE the guard: any surprise in
                # batch assembly must resolve this batch's futures, never
                # kill the batcher thread (a dead batcher hangs every
                # later request and deadlocks close())
                arrays = [q for q, _, _, _ in batch]
                cat = arrays[0] if len(arrays) == 1 else np.concatenate(arrays)
                offsets = np.cumsum([0] + [a.shape[0] for a in arrays])
                # every member's queue-wait span closes at dispatch time,
                # under its OWN trace id — the coalesced engine request
                # gets a fresh batch-level id, linked below
                t_disp = time.monotonic()
                for q, _, t_arr, tid in batch:
                    obs.record_span("serving.queue_wait", tid,
                                    t_disp - t_arr, rows=int(q.shape[0]))
                    obs.histogram(mn.QUEUE_WAIT).observe(t_disp - t_arr)
                handle = self.engine.submit(cat, op=self.op)
                obs.emit_event(
                    "queue.dispatch", op=self.op,
                    batch_trace_id=handle.trace_id,
                    member_trace_ids=[tid for _, _, _, tid in batch],
                    rows=int(offsets[-1]), requests=len(batch))
            except Exception as e:  # noqa: BLE001 — resolve, don't kill the loop
                self._record_errors(len(batch))
                for _, fut, _, _ in batch:
                    self._resolve(fut, exc=e)
                continue
            with self._cond:
                self._stats["dispatches"] += 1
                self._stats["coalesced_rows"] += int(offsets[-1])
            obs.counter(mn.QUEUE_DISPATCHES).inc()
            obs.counter(mn.QUEUE_COALESCED_ROWS).inc(int(offsets[-1]))
            self._done.put((handle, batch, offsets))
        self._done.put(None)

    # -- completer thread --------------------------------------------------
    def _completer(self) -> None:
        while True:
            item = self._done.get()
            if item is None:
                break
            handle, batch, offsets = item
            try:
                res = handle.result()
            except Exception as e:  # noqa: BLE001 — per-batch failure isolation
                self._record_errors(len(batch))
                for _, fut, _, _ in batch:
                    self._resolve(fut, exc=e)
                continue
            done_t = time.monotonic()
            for j, (q, fut, t_arr, tid) in enumerate(batch):
                lo, hi = int(offsets[j]), int(offsets[j + 1])
                if self.op == "search":
                    d, i = res
                    self._resolve(fut, (d[lo:hi], i[lo:hi]))
                else:
                    self._resolve(fut, res[lo:hi])
                self._lat.append((done_t, done_t - t_arr))
                # arrival-to-result under the request's own trace id —
                # what a caller tuning max_wait_ms actually experiences
                obs.histogram(mn.QUEUE_REQUEST_LATENCY).observe(
                    done_t - t_arr)
                obs.record_span("serving.queued_request", tid,
                                done_t - t_arr, op=self.op,
                                rows=int(q.shape[0]),
                                batch_trace_id=handle.trace_id)

    def _record_errors(self, n: int) -> None:
        with self._cond:
            self._stats["errors"] += n
        obs.counter(mn.QUEUE_ERRORS).inc(n)
