"""Bulk kNN-join: offline top-k of EVERY row of a query set A against
a corpus B, with the db stream amortized over query superblocks.

Latency-bound serving re-streams the whole placed database per request
batch, which is why the winning serving configs sit hbm_bound far under
the calibrated ceiling (the roofline's verdict).  The join engine is
the one regime that can honor the reference's own design principle
("maximize compute-to-communication ratio — fewer, larger messages",
PDF p.7 §3.1): it sweeps A in large superblocks through the EXISTING
streaming/fused kernels and sharded programs unmodified, so db HBM
bytes per query fall as 1/superblock_rows until the bound flips off
hbm_bound (obs.roofline MODEL_VERSION 7's join model prices exactly
this).  Query-side double buffering — superblock i+1's host->device
transfer overlapping block i's device compute under the bounded-depth
drain-oldest discipline, with donated query buffers — turns the
h2d query stream into an amortized cost too.

Entry points: :func:`knn_join` (one call, any ShardedKNN placement —
resident or host-RAM tier — or an IVFIndex), :func:`default_plan`
(the superblock/nesting plan the engine would use, jax-free).
"""

from knn_tpu.join.artifact import JOIN_VERSION, validate_join_block
from knn_tpu.join.engine import JOIN_MODES, default_plan, knn_join

__all__ = [
    "JOIN_MODES",
    "JOIN_VERSION",
    "default_plan",
    "knn_join",
    "validate_join_block",
]
