"""Jax-free pieces of the join subsystem: the version token and the
``join`` bench-artifact validator.

These live apart from :mod:`knn_tpu.join.engine` (whose entry points
import JAX lazily but whose callers usually don't want a backend at
all) for the same reason ``knn_tpu.ivf.artifact`` splits off
``knn_tpu.ivf.index``: whatever validates curated artifacts must run on
the box that curates them, not only the one with the accelerator.
"""

from __future__ import annotations

from typing import List

#: version stamp of the ``join`` bench block (bench.py's opt-in join
#: mode); bump on any schema change so the refresher refuses
#: half-migrated lines instead of hoisting garbage — the version token
#: the artifact-schema catalog's ``join`` entry consumes
JOIN_VERSION = 1


def _required_fields():
    from knn_tpu.analysis.artifacts import required_keys

    return required_keys("join")


#: fields every valid join block must carry (the refusal list the
#: refresher prints) — DERIVED from the artifact-schema catalog
#: (knn_tpu.analysis.artifacts), the one declaration the validator and
#: the lockstep checker both read
JOIN_REQUIRED = _required_fields()


def validate_join_block(block) -> List[str]:
    """Structural validation the artifact refresher runs before curating
    a line carrying a ``join`` block: returns the list of violations
    (empty = valid).  Blocks that recorded their own failure (an
    ``error`` key) are exempt — an honest error field beats a refused
    line.  A shim over the artifact-schema catalog
    (:mod:`knn_tpu.analysis.artifacts`, the ``join`` entry)."""
    from knn_tpu.analysis.artifacts import validate

    return validate("join", block, style="legacy")
