"""The bulk kNN-join engine — query-side double buffering over the
EXISTING kernels and sharded programs (no new kernels).

Two modes (:data:`JOIN_MODES`):

- ``"stream"``: the throughput path.  A splits into fixed-width query
  superblocks (explicit rows > ``KNN_TPU_JOIN_SUPERBLOCK`` env > a
  query-byte budget through :func:`knn_tpu.analysis.hbm.
  plan_superblocks` > the library default); each superblock places
  h2d and dispatches through
  :func:`knn_tpu.parallel.sharded.query_stream_program` (the exact
  search program with the query operand donated off-CPU) under the
  bounded-depth drain-oldest discipline — block i+1's transfer +
  dispatch overlaps block i's fetch, measured by the same
  dispatch-timeline ``overlap_ratio`` the certified pipeline reports.
  When B itself exceeds HBM (a host-RAM-tier placement), the sweep
  nesting order comes from :func:`knn_tpu.analysis.hbm.plan_join`:
  ``db_major`` outer streams each db segment h2d ONCE and serves every
  superblock while it is resident (per-superblock top-k carries merge
  host-side in the device merge's lexicographic order), ``query_major``
  outer streams each superblock once — whichever moves fewer h2d
  bytes.  Results are the exact f32 lexicographic top-k, bitwise equal
  to looping :meth:`ShardedKNN.search` over the same rows.

- ``"certified"``: the exactness anchor.  Each superblock runs the
  UNMODIFIED ``search_certified`` (any selector x precision x kernel,
  kwargs forwarded; an :class:`knn_tpu.ivf.index.IVFIndex` works the
  same way), so the join result is bitwise-equal to the looped
  certified path by construction — the oracle tests pin.

Every run returns ``(d, i, stats)`` with ``stats`` carrying the
executed superblock/segment/dispatch counts (pinned against the
analysis.hbm byte model), ``rows_per_s``, and ``overlap_ratio``.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Tuple

import numpy as np

from knn_tpu.analysis import hbm

#: fallback query-superblock width when neither explicit rows, the env
#: switch, nor a query-byte budget decides — large enough that the db
#: stream amortizes (db bytes/query ~ B_bytes / 4096), small enough to
#: place twice (double buffering) beside any realistic corpus
DEFAULT_SUPERBLOCK_ROWS = 4096

#: bounded in-flight superblock depth of the drain-oldest stream
DEFAULT_DEPTH = 2

JOIN_MODES = ("stream", "certified")

_ENV_SUPERBLOCK = "KNN_TPU_JOIN_SUPERBLOCK"
_ENV_DEPTH = "KNN_TPU_JOIN_DEPTH"
_ENV_QUERY_BUDGET = "KNN_TPU_JOIN_QUERY_BUDGET_BYTES"


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError as e:
        # strict-env discipline (hosttier/admission switches): a typo'd
        # knob raises instead of silently running at the default
        raise ValueError(f"{name}={raw!r} is not an int") from e


def _is_sharded(program) -> bool:
    return hasattr(program, "_place_queries")


def _resolve_superblock(program, n_a: int, superblock_rows: Optional[int],
                        query_budget_bytes: Optional[int]) -> int:
    """Superblock width: explicit rows > env rows > (explicit/env)
    query-byte budget through the hbm model > the library default —
    always clamped to ``n_a`` and at least 1."""
    rows = superblock_rows if superblock_rows is not None \
        else _env_int(_ENV_SUPERBLOCK)
    if rows is None:
        budget = query_budget_bytes if query_budget_bytes is not None \
            else _env_int(_ENV_QUERY_BUDGET)
        if budget is not None:
            dim = _query_dim(program)
            qm = _query_multiple(program)
            segs = hbm.plan_superblocks(n_a, dim, budget,
                                        query_multiple=qm)
            rows = segs[0][1] - segs[0][0]
        else:
            rows = DEFAULT_SUPERBLOCK_ROWS
    rows = int(rows)
    if rows < 1:
        raise ValueError(f"superblock_rows must be >= 1, got {rows}")
    return min(rows, int(n_a))


def _resolve_depth(depth: Optional[int]) -> int:
    d = depth if depth is not None else _env_int(_ENV_DEPTH)
    return max(1, int(d)) if d is not None else DEFAULT_DEPTH


def _query_dim(program) -> int:
    if _is_sharded(program):
        return int(getattr(program, "dim_in", None)
                   or program._tp.shape[1])
    return int(program.dim)  # IVFIndex


def _query_multiple(program) -> int:
    from knn_tpu.parallel.mesh import QUERY_AXIS

    try:
        return int(program.mesh.shape[QUERY_AXIS])
    except Exception:
        return 1


def default_plan(program, n_a: int, *,
                 superblock_rows: Optional[int] = None,
                 query_budget_bytes: Optional[int] = None) -> dict:
    """The jax-free plan :func:`knn_join` would execute for ``n_a``
    query rows against ``program``'s corpus: superblock width, sweep
    nesting order, and h2d byte totals (analysis.hbm.plan_join)."""
    sb = _resolve_superblock(program, n_a, superblock_rows,
                             query_budget_bytes)
    dim = _query_dim(program)
    if _is_sharded(program) and program._host_tier is not None:
        seg_rows = int(program._host_tier["segment_rows"])
        n_b = int(program.n_train)
    else:
        seg_rows = 0
        n_b = int(program.n_train if _is_sharded(program)
                  else program.stats()["live_rows"])
    plan = hbm.plan_join(n_a, n_b, dim, superblock_rows=sb,
                         db_segment_rows=seg_rows)
    plan["superblock_rows"] = sb
    plan["db_segment_rows"] = seg_rows
    return plan


def _pad_block(q: np.ndarray, lo: int, hi: int, rows: int) -> np.ndarray:
    """One fixed-width query block (ragged tail zero-pads up, so every
    superblock dispatch shares ONE compiled program shape; pad rows are
    ordinary queries whose outputs are sliced away)."""
    blk = q[lo:hi]
    if blk.shape[0] < rows:
        blk = np.pad(blk, ((0, rows - blk.shape[0]), (0, 0)))
    return blk


def _stream_resident(program, q: np.ndarray, k: int, sb_rows: int,
                     depth: int, d_out, i_out) -> dict:
    """Resident-B stream: double-buffer query superblocks through the
    donated-query search program, drain-oldest at ``depth``."""
    import jax

    from knn_tpu.parallel.sharded import (
        _fetch_or_redispatch, _overlap_ratio, _retry_transient,
        query_stream_program)

    donate = jax.default_backend() != "cpu"
    prog = query_stream_program(
        program.mesh, k, program.n_train, program.metric, program.merge,
        train_tile=program.train_tile, compute_dtype=program._dtype_key,
        dcn_merge=program.dcn_merge, donate=donate)
    n_a = q.shape[0]
    blocks = [(lo, min(lo + sb_rows, n_a))
              for lo in range(0, n_a, sb_rows)]

    def launch(lo: int, hi: int):
        # h2d placement + async dispatch: with donation the device
        # recycles the previous superblock's query buffer, so at most
        # ``depth`` placements coexist
        qp, _ = program._place_queries(_pad_block(q, lo, hi, sb_rows))
        return prog(qp, program._tp)

    pending: list = []
    intervals: list = []

    def collect() -> None:
        lo, hi, t0, out = pending.pop(0)
        cur = {"out": out}

        def redo():
            # d and i MUST come from the same execution (the host-tier
            # paired-output discipline): relaunch rebinds BOTH outputs
            cur["out"] = launch(lo, hi)
            return cur["out"][0]

        d = _fetch_or_redispatch(out[0], redo, "join fetch")
        i = np.asarray(cur["out"][1])
        intervals.append((t0, time.perf_counter()))
        d_out[lo:hi] = d[: hi - lo]
        i_out[lo:hi] = i[: hi - lo]

    for lo, hi in blocks:
        while len(pending) >= depth:
            collect()
        t0 = time.perf_counter()
        out = _retry_transient(lambda lo=lo, hi=hi: launch(lo, hi),
                               "join dispatch")
        pending.append((lo, hi, t0, out))
    while pending:
        collect()
    return {
        "superblocks": len(blocks),
        "db_segments": 1,
        "dispatches": len(blocks),
        "overlap_ratio": round(_overlap_ratio(intervals), 4),
    }


def _stream_tiered(program, q: np.ndarray, k: int, sb_rows: int,
                   depth: int, order: str, d_out, i_out) -> dict:
    """Super-HBM-B stream: both A and B sweep through the host-tier
    SEGMENT program in the byte-model-chosen nesting order, with
    per-superblock top-k carries merged host-side in the device merge's
    lexicographic order.  ``db_major`` places each db segment h2d ONCE
    (it stays resident for every superblock's dispatch); ``query_major``
    places each superblock once."""
    from knn_tpu.ops.pallas_knn import PAD_VAL
    from knn_tpu.parallel.collectives import replicate, shard
    from knn_tpu.parallel.mesh import db_axes
    from knn_tpu.parallel.multihost import merge_topk_host
    from knn_tpu.parallel.sharded import (
        _INT_SENTINEL, _fetch_or_redispatch, _overlap_ratio,
        _retry_transient, segment_search_program)

    import jax.numpy as jnp

    ht = program._host_tier
    host = program._train_host
    seg_rows = ht["segment_rows"]
    dtype = (None if program._dtype_key is None
             else jnp.dtype(program._dtype_key))
    prog = segment_search_program(
        program.mesh, k, program.metric, program.merge,
        train_tile=program.train_tile, compute_dtype=dtype,
        dcn_merge=program.dcn_merge)
    n_a = q.shape[0]
    blocks = [(lo, min(lo + sb_rows, n_a))
              for lo in range(0, n_a, sb_rows)]
    segments = ht["segments"]
    carry_d: List[Optional[np.ndarray]] = [None] * len(blocks)
    carry_i: List[Optional[np.ndarray]] = [None] * len(blocks)

    def place_seg(slo: int, shi: int):
        seg = host[slo:shi]
        if seg.shape[0] < seg_rows:
            seg = np.pad(seg, ((0, seg_rows - seg.shape[0]), (0, 0)),
                         constant_values=PAD_VAL)
        tp = shard(seg, program.mesh, db_axes(program.mesh))
        nv = replicate(np.asarray([shi - slo], np.int32), program.mesh)
        return tp, nv

    def place_q(lo: int, hi: int):
        qp, _ = program._place_queries(_pad_block(q, lo, hi, sb_rows))
        return qp

    pending: list = []
    intervals: list = []

    def collect() -> None:
        bi, (lo, hi), slo, t0, out, relaunch = pending.pop(0)
        cur = {"out": out}

        def redo():
            cur["out"] = relaunch()
            return cur["out"][0]

        d = _fetch_or_redispatch(out[0], redo, "join fetch")
        i = np.asarray(cur["out"][1])
        intervals.append((t0, time.perf_counter()))
        pad = i == _INT_SENTINEL
        gi = np.where(pad, _INT_SENTINEL, i.astype(np.int64) + slo)
        d = np.asarray(d)
        if carry_d[bi] is None:
            carry_d[bi], carry_i[bi] = d, gi
        else:
            carry_d[bi], carry_i[bi] = merge_topk_host(
                [carry_d[bi], d], [carry_i[bi], gi], k)

    dispatches = 0
    if order == "db_major":
        outer = [((slo, shi), None) for slo, shi in segments]
        for (slo, shi), _ in outer:
            tp, nv = place_seg(slo, shi)
            for bi, (lo, hi) in enumerate(blocks):
                while len(pending) >= depth:
                    collect()
                t0 = time.perf_counter()

                def relaunch(lo=lo, hi=hi, tp=tp, nv=nv):
                    return prog(place_q(lo, hi), tp, nv)

                out = _retry_transient(relaunch, "join dispatch")
                pending.append((bi, (lo, hi), slo, t0, out, relaunch))
                dispatches += 1
            # drain before the NEXT segment placement replaces tp: at
            # most one db segment is device-resident at a time (the
            # byte budget the tier exists to honor)
            while pending:
                collect()
    else:  # query_major
        for bi, (lo, hi) in enumerate(blocks):
            qp = place_q(lo, hi)
            for slo, shi in segments:
                while len(pending) >= depth:
                    collect()
                t0 = time.perf_counter()

                def relaunch(qp=qp, slo=slo, shi=shi):
                    tp, nv = place_seg(slo, shi)
                    return prog(qp, tp, nv)

                out = _retry_transient(relaunch, "join dispatch")
                pending.append((bi, (lo, hi), slo, t0, out, relaunch))
                dispatches += 1
        while pending:
            collect()
    for bi, (lo, hi) in enumerate(blocks):
        d_out[lo:hi] = carry_d[bi][: hi - lo]
        i_out[lo:hi] = carry_i[bi][: hi - lo]
    return {
        "superblocks": len(blocks),
        "db_segments": len(segments),
        "dispatches": dispatches,
        "overlap_ratio": round(_overlap_ratio(intervals), 4),
    }


def _certified_loop(program, q: np.ndarray, k: int, sb_rows: int,
                    d_out, i_out, kw: dict) -> dict:
    """The exactness anchor: the UNMODIFIED certified path per
    superblock (ragged tail included as-is — search_certified batches
    internally), so the join equals the looped certified path bitwise
    by construction."""
    n_a = q.shape[0]
    blocks = [(lo, min(lo + sb_rows, n_a))
              for lo in range(0, n_a, sb_rows)]
    fallbacks = 0
    for lo, hi in blocks:
        if _is_sharded(program):
            d, i, st = program.search_certified(q[lo:hi], **kw)
        else:  # IVFIndex — same surface, k rides as a kwarg
            d, i, st = program.search_certified(q[lo:hi], k=k, **kw)
        d_out[lo:hi] = d
        i_out[lo:hi] = i
        fallbacks += int(st.get("fallback_queries", 0))
    return {
        "superblocks": len(blocks),
        "db_segments": 1,
        "dispatches": len(blocks),
        "fallback_queries": fallbacks,
        "overlap_ratio": None,  # the certified loop has no pipeline
    }


def knn_join(
    program,
    queries,
    *,
    k: Optional[int] = None,
    mode: str = "stream",
    superblock_rows: Optional[int] = None,
    depth: Optional[int] = None,
    query_budget_bytes: Optional[int] = None,
    return_sqrt: bool = False,
    **certified_kw,
) -> Tuple[np.ndarray, np.ndarray, dict]:
    """Top-k of every row of ``queries`` (A) against ``program``'s
    corpus (B): ``(d [N_A, k], i [N_A, k], stats)`` host arrays.

    ``program`` is a placed :class:`knn_tpu.parallel.ShardedKNN`
    (resident or host-RAM tier) or an :class:`knn_tpu.ivf.index.
    IVFIndex` (certified mode only).  ``mode="stream"`` is the
    double-buffered throughput path (module docstring);
    ``mode="certified"`` loops the unmodified certified path per
    superblock and forwards ``certified_kw`` (selector, precision,
    kernel, margin, ...) to it.  ``superblock_rows`` / ``depth`` /
    ``query_budget_bytes`` default through the ``KNN_TPU_JOIN_*`` env
    switches.  ``stats`` reports executed superblock / db-segment /
    dispatch counts (pinned against analysis.hbm), ``rows_per_s``,
    ``overlap_ratio`` (stream mode), and the byte-model ``plan``."""
    from knn_tpu import obs

    if mode not in JOIN_MODES:
        raise ValueError(f"unknown join mode {mode!r}; expected one of "
                         f"{JOIN_MODES}")
    sharded = _is_sharded(program)
    if not sharded and mode != "certified":
        raise ValueError(
            "IVF joins run mode='certified' only (the probed tier has "
            "no resident placement to stream queries against)")
    q = np.ascontiguousarray(np.asarray(queries, np.float32))
    dim = _query_dim(program)
    if q.ndim != 2 or q.shape[1] != dim:
        raise ValueError(
            f"queries shape {q.shape} incompatible with corpus dim {dim}")
    k = int(k) if k is not None else int(program.k)
    if sharded:
        if mode == "certified" and k != int(program.k):
            raise ValueError(
                f"certified joins run the program's own certified path: "
                f"k={k} != program.k={program.k}; construct the "
                f"placement with the join k")
        if mode == "stream":
            from knn_tpu.parallel.mesh import db_topology

            hosts, chips = db_topology(program.mesh)
            db_shards = hosts * chips
            placed = (program._host_tier["segment_rows"]
                      if program._host_tier is not None
                      else int(program._tp.shape[0]))
            if k > placed // db_shards:
                raise ValueError(
                    f"k={k} exceeds db shard size "
                    f"{placed // db_shards}; use fewer db shards")
    n_a = q.shape[0]
    if n_a < 1:
        raise ValueError("knn_join needs at least one query row")
    sb_rows = _resolve_superblock(program, n_a, superblock_rows,
                                  query_budget_bytes)
    dep = _resolve_depth(depth)
    plan = default_plan(program, n_a, superblock_rows=sb_rows)
    i_out = np.empty((n_a, k), np.int64)
    d_out = np.empty((n_a, k),
                     np.float64 if mode == "certified" else np.float32)
    t0 = time.perf_counter()
    if mode == "certified":
        # the certified path owns its own metric->value mapping; let it
        # apply return_sqrt so joined values equal the looped call's
        if return_sqrt:
            certified_kw = {**certified_kw, "return_sqrt": True}
        executed = _certified_loop(program, q, k, sb_rows, d_out, i_out,
                                   certified_kw)
    elif program._host_tier is not None:
        executed = _stream_tiered(program, q, k, sb_rows, dep,
                                  plan["order"], d_out, i_out)
    else:
        executed = _stream_resident(program, q, k, sb_rows, dep,
                                    d_out, i_out)
    wall = time.perf_counter() - t0
    # the executed sweep counts must MATCH the plan — a drift here means
    # the engine and the byte model disagree about what ran
    for key in ("superblocks", "db_segments", "dispatches"):
        if mode == "stream" and executed[key] != plan[key]:
            raise RuntimeError(
                f"join executed {key}={executed[key]} but the byte model "
                f"planned {plan[key]} — engine/model drift")
    stats = {
        "mode": mode,
        "k": k,
        "rows": n_a,
        "superblock_rows": sb_rows,
        "depth": dep,
        "order": plan["order"] if mode == "stream" else "query_major",
        "wall_s": round(wall, 6),
        "rows_per_s": round(n_a / wall, 3) if wall > 0 else float("inf"),
        "plan": plan,
        **executed,
    }
    obs.record_span("join.bulk", f"join-{id(program):x}", wall,
                    rows=n_a, mode=mode)
    if return_sqrt and mode == "stream":
        # the same post-map ShardedKNN.search applies for return_sqrt
        import jax.numpy as jnp

        from knn_tpu.ops.distance import metric_values

        d_out = np.asarray(metric_values(jnp.asarray(d_out),
                                         program.metric))
    return d_out, i_out, stats
