"""Measured-ceiling campaign harness — ROADMAP open item 1 as a
push-button, regression-gated loop (``cli campaign`` /
``scripts/measured_ceiling_campaign.py``).

One campaign ARM = one kernel configuration (precision × db-streaming
strategy).  Per arm the harness runs the same seven stages the roadmap
describes by hand, in order, each one recorded in the arm's artifact:

1. **gates** — arm the on-hardware env gates (bench mode/knob
   overrides, live ``KNN_TPU_TUNE_PRUNE`` roofline pruning, the
   ``KNN_TPU_PROFILE_DIR`` trace capture, the ``KNN_TPU_CALIBRATION``
   store).  Rehearse mode records the gate set without flipping
   hardware-only ones.
2. **tune** — autotune the arm's pinned knobs (roofline + VMEM pruning
   live) and persist the winner.
3. **bench** — a fenced timed sweep at the winner knobs; the
   host-phase ``device_s`` measurement every later stage reconciles
   against.
4. **capture** — one extra traced run under the profiler
   (:mod:`knn_tpu.obs.profiler`), parsed back by
   :mod:`knn_tpu.obs.traceread`; rehearse additionally parses the
   checked-in trace fixture so the device-trace path is exercised
   deterministically on CPU.
5. **reconcile** — decompose the measured device time against the
   analytic roofline terms (:func:`knn_tpu.obs.calibrate.reconcile`).
6. **calibrate** — persist the per-term factors to the calibration
   store; re-render the roofline block and require
   ``calibration.applied`` with the calibrated ceiling reproducing the
   measured q/s inside the stated tolerance.
7. **curate** — validate the arm's artifact (roofline block,
   calibration field, campaign block — the same validators
   ``refresh_bench_artifacts.py`` refuses on), stamp provenance
   (commit, round), attach the sentinel verdict, and write ONE JSONL
   artifact per arm (atomic tmp+rename).

``--rehearse`` runs the identical loop on CPU against tiny synthetic
shapes and host-phase timings — tier-1 exercises every stage without a
TPU (tests/test_calibrate.py pins the loop end-to-end).  The real mode
shells out to ``bench.py`` per arm with the gates flipped, so a
hardware session is ``cli campaign --round N`` and nothing else.

Env knobs (``KNN_TPU_CAMPAIGN_*``; declared in the switch catalog):
``KNN_TPU_CAMPAIGN_DIR`` (artifact directory), ``KNN_TPU_CAMPAIGN_ARMS``
(comma list of arm names), ``KNN_TPU_CAMPAIGN_ROUND`` (round stamp).
Campaign runbook: docs/PERF.md "Calibration & measured ceilings".
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence

from knn_tpu.obs import calibrate, names, profiler, registry
from knn_tpu.obs import roofline as _rl
from knn_tpu.obs import traceread

#: artifact output directory (default: artifacts/campaign under cwd)
DIR_ENV = "KNN_TPU_CAMPAIGN_DIR"
#: comma list of arm names overriding the default ladder
ARMS_ENV = "KNN_TPU_CAMPAIGN_ARMS"
#: measurement-round stamp carried into artifact provenance
ROUND_ENV = "KNN_TPU_CAMPAIGN_ROUND"

#: campaign artifact schema version (calibrate.validate_campaign_block)
CAMPAIGN_VERSION = 1

#: stage names, in execution order (the stage counter's label values)
STAGES = ("gates", "tune", "bench", "capture", "reconcile",
          "calibrate", "curate")

#: named arms: the knob pins a campaign sweeps.  The default hardware
#: ladder is the roadmap's r06 target list; rehearse defaults to the
#: cheapest arm so tier-1 stays fast.
ARM_KNOBS: Dict[str, Dict[str, object]] = {
    "bf16x3_tiled": {"precision": "bf16x3", "kernel": "tiled"},
    "bf16x3_streaming": {"precision": "bf16x3", "kernel": "streaming"},
    "int8_streaming": {"precision": "int8", "kernel": "streaming"},
    "int8_fused": {"precision": "int8", "kernel": "fused"},
    # the bulk-join throughput regime (knn_tpu.join / PERF.md "Bulk
    # kNN-join"): the tuning profile's block_q-512 ladder point, tiled
    # because the deeper query blocks fit no other kernel's VMEM
    # (tuning.knob_grid(profile="throughput"))
    "join_bq512": {"precision": "bf16x3", "kernel": "tiled",
                   "block_q": 512},
}
DEFAULT_ARMS = ("bf16x3_tiled", "bf16x3_streaming", "int8_streaming",
                "int8_fused", "join_bq512")
DEFAULT_REHEARSE_ARMS = ("bf16x3_tiled",)

#: rehearse problem shape: big enough for a non-degenerate kernel
#: geometry, small enough for tier-1
REHEARSE_SHAPE = dict(n=2048, d=32, k=5, nq=64)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def campaign_dir() -> str:
    return os.environ.get(DIR_ENV) or os.path.join(
        "artifacts", "campaign")


def arms_from_env() -> Optional[List[str]]:
    spec = os.environ.get(ARMS_ENV)
    if not spec:
        return None
    arms = [a.strip() for a in spec.split(",") if a.strip()]
    for a in arms:
        if a not in ARM_KNOBS:
            raise ValueError(f"{ARMS_ENV} names unknown arm {a!r}; "
                             f"expected one of {sorted(ARM_KNOBS)}")
    return arms or None


def round_from_env() -> Optional[int]:
    raw = os.environ.get(ROUND_ENV)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError as e:
        raise ValueError(f"{ROUND_ENV}={raw!r} is not an int") from e


def _head_commit(repo: str) -> str:
    try:
        r = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                           cwd=repo, capture_output=True, text=True,
                           timeout=10)
        return r.stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 — provenance, not a gate
        return "unknown"


def _stage(log: List[dict], name: str, status: str, **detail) -> dict:
    """Record one stage outcome (and count it) — every stage of every
    arm lands in the artifact, errors included."""
    rec = {"stage": name, "status": status, **detail}
    log.append(rec)
    if registry.enabled():
        registry.counter(names.CAMPAIGN_STAGES, stage=name).inc()
    return rec


def _knobs_for_model(knobs: Dict[str, object]) -> Dict[str, object]:
    """The cost-model-relevant subset of a resolved knob dict."""
    return {
        "precision": knobs.get("precision"),
        "kernel": knobs.get("kernel"),
        "grid_order": knobs.get("grid_order"),
        "binning": knobs.get("binning"),
        "tile_n": knobs.get("tile_n"),
        "block_q": knobs.get("block_q"),
        "survivors": knobs.get("survivors"),
    }


def _write_artifact(out_dir: str, fname: str, line: dict) -> str:
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, fname)
    fd, tmp = tempfile.mkstemp(dir=out_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps(line, sort_keys=True) + "\n")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def default_trace_fixture() -> Optional[str]:
    """The checked-in minimal device trace rehearse parses so the
    trace-reader path runs deterministically on CPU."""
    path = os.path.join(_REPO, "tests", "fixtures",
                        "minimal.trace.json.gz")
    return path if os.path.exists(path) else None


def _rehearse_arm(arm: str, *, out_dir: str, shape: Dict[str, int],
                  seed: int, round_no: Optional[int],
                  trace_fixture: Optional[str], grid_level: str,
                  verbose: bool) -> dict:
    """One rehearse arm: the full stage loop on CPU with host-phase
    timings (module docstring)."""
    import numpy as np

    from knn_tpu import tuning
    from knn_tpu.ops.pallas_knn import knn_search_pallas

    n, d, k, nq = (shape[f] for f in ("n", "d", "k", "nq"))
    stages: List[dict] = []
    log = (lambda msg: print(f"[{arm}] {msg}", file=sys.stderr)) \
        if verbose else (lambda msg: None)

    # 1. gates — rehearse records the gate set without flipping the
    # hardware-only ones (there is no hardware to flip)
    store = calibrate.store_path() or os.path.join(
        out_dir, "calibration.json")
    _stage(stages, "gates", "ok", rehearse_note=(
        "CPU rehearsal: on-hardware bench gates stay down; tune "
        "pruning, trace capture, and the calibration store are live"),
        calibration_store=store)

    # 2. tune — the arm's pinned knobs through the real autotuner
    # (bitwise gate, fenced timing, roofline attribution, VMEM refusal,
    # roofline pruning all live), tiny grid so tier-1 stays fast
    log("tune ...")
    rng = np.random.default_rng(seed)
    db = (rng.random((n, d)) * 128.0).astype(np.float32)
    queries = (rng.random((max(nq, 8), d)) * 128.0).astype(np.float32)
    arm_knobs = dict(ARM_KNOBS[arm])
    tile = max(128, (n // 8) // 128 * 128)
    grid = [dict(arm_knobs, tile_n=tile),
            dict(arm_knobs, tile_n=tile * 2)]
    tune_cache = os.path.join(out_dir, "tune_cache.json")
    try:
        entry = tuning.autotune(
            db, queries[:8], k, grid=grid, runs=1,
            cache_path=tune_cache, prune=0.25)
        knobs = {**tuning.DEFAULT_KNOBS, **arm_knobs,
                 **{kk: v for kk, v in entry["knobs"].items()
                    if kk in tuning.DEFAULT_KNOBS}}
        _stage(stages, "tune", "ok", winner=entry.get("winner"),
               winner_ms=entry.get("winner_ms"),
               candidates=len(entry.get("timings_ms") or {}),
               pruned=len(entry.get("pruning") or {}),
               cache_path=tune_cache)
    except Exception as e:  # noqa: BLE001 — recorded, arm continues on pins
        knobs = {**tuning.DEFAULT_KNOBS, **arm_knobs, "tile_n": tile}
        _stage(stages, "tune", "error",
               error=f"{type(e).__name__}: {e}")

    # 3. bench — fenced timed sweep at the winner knobs: the host-phase
    # device_s sample the reconciler consumes
    log("bench ...")
    kw = dict(
        precision=knobs["precision"], kernel=knobs["kernel"],
        tile_n=knobs["tile_n"] or tile, bin_w=knobs["bin_w"],
        survivors=knobs["survivors"], block_q=knobs["block_q"],
        final_select=knobs["final_select"], binning=knobs["binning"],
        final_recall_target=knobs["final_recall_target"],
        grid_order=knobs["grid_order"])
    q = queries[:nq]
    knn_search_pallas(q, db, k, **kw)  # warm/compile
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        knn_search_pallas(q, db, k, **kw)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    phase = {"device_s": round(best, 6),
             "device_qps": round(nq / best, 2)}
    _stage(stages, "bench", "ok", **phase)

    # 4. capture — a real (CPU) profiler capture of one extra run,
    # plus the checked-in fixture parse proving the device-trace path
    log("capture ...")
    section = f"campaign_{arm}"
    capture_detail: Dict[str, object] = {}
    try:
        with profiler.device_trace(
                section, base_dir=os.path.join(out_dir, "traces")):
            knn_search_pallas(q, db, k, **kw)
        parsed = traceread.read_section(
            os.path.join(out_dir, "traces"), section)
        capture_detail["live_capture"] = {
            "kernel_events": parsed["kernel_events"],
            "device_busy_s": parsed["device_busy_s"],
            "device_tracks_matched": parsed["device_tracks_matched"],
        }
        cap_status = "ok"
    except Exception as e:  # noqa: BLE001 — a CPU runtime may write no trace
        capture_detail["live_capture_error"] = \
            f"{type(e).__name__}: {e}"
        cap_status = "error"
    if trace_fixture:
        fx = traceread.summarize_events(
            traceread.read_trace_events(trace_fixture))
        capture_detail["fixture"] = {
            "path": trace_fixture,
            "kernel_events": fx["kernel_events"],
            "device_busy_s": fx["device_busy_s"],
            "device_tracks_matched": fx["device_tracks_matched"],
        }
        cap_status = "ok"
    _stage(stages, "capture", cap_status, **capture_detail)

    # 5. reconcile — decompose the measured device time against the
    # analytic terms
    log("reconcile ...")
    model_kw = _knobs_for_model(knobs)
    model_kw["tile_n"] = model_kw["tile_n"] or tile
    block = _rl.pallas_cost_model(n=n, d=d, k=k, nq=nq,
                                  backend="cpu", **model_kw)
    measured = traceread.sample_from_phases(phase, nq=nq)
    entry = calibrate.reconcile(block, measured, provenance={
        "config_label": _rl.config_label(n, d, k),
        "commit": _head_commit(_REPO),
        "round": round_no, "arm": arm, "rehearse": True})
    _stage(stages, "reconcile", "ok",
           factors=entry["factors"], method=entry["method"],
           model_residual_pct=entry["model_residual_pct"],
           source=entry["source"])

    # 6. calibrate — persist, re-render, and require the calibrated
    # ceiling to reproduce the measured qps inside the stated tolerance
    log("calibrate ...")
    key = calibrate.key_for_block(block)
    calibrate.put(key, entry, path=store)
    prev = os.environ.get(calibrate.CAL_ENV)
    os.environ[calibrate.CAL_ENV] = store
    try:
        block2 = _rl.pallas_cost_model(n=n, d=d, k=k, nq=nq,
                                       backend="cpu", **model_kw)
        att = _rl.attribute(block2, phase["device_qps"])
    finally:
        if prev is None:
            os.environ.pop(calibrate.CAL_ENV, None)
        else:
            os.environ[calibrate.CAL_ENV] = prev
    applied = bool(att.get("calibration", {}).get("applied"))
    resid = (abs(att["ceiling_qps"] - phase["device_qps"])
             / phase["device_qps"] * 100.0
             if att.get("ceiling_qps") else None)
    within = (applied and resid is not None
              and resid <= calibrate.RESIDUAL_TOLERANCE_PCT)
    _stage(stages, "calibrate", "ok" if within else "error",
           store=store, key=key, applied=applied,
           ceiling_qps=att.get("ceiling_qps"),
           measured_qps=phase["device_qps"],
           reconstruction_residual_pct=(round(resid, 3)
                                        if resid is not None else None),
           tolerance_pct=calibrate.RESIDUAL_TOLERANCE_PCT)

    # 7. curate — validate with the refresher's own validators and
    # write one artifact line per arm
    log("curate ...")
    campaign_block = {
        "campaign_version": CAMPAIGN_VERSION, "arm": arm,
        "round": round_no, "rehearse": True, "stages": stages,
    }
    line = {
        "metric": f"knn_qps_rehearse_n{n}_d{d}_k{k}",
        "value": phase["device_qps"],
        "unit": "queries/s",
        "mode": "campaign_rehearse",
        "backend": "cpu",
        "device_kind": None,
        "device_phase_qps": phase["device_qps"],
        "pallas_knobs": knobs,
        "roofline": att,
        "roofline_pct": att.get("roofline_pct"),
        "bound_class": att.get("bound_class"),
        "model_residual_pct": entry["model_residual_pct"],
        "campaign": campaign_block,
        "measured_round": round_no if round_no is not None else 0,
        "measured_at_commit": _head_commit(_REPO),
    }
    errors = (_rl.validate_block(att)
              + calibrate.validate_calibration(att.get("calibration"))
              + calibrate.validate_campaign_block(campaign_block))
    try:
        from knn_tpu.obs import sentinel

        line["sentinel"] = sentinel.verdict_for_line(
            line, repo_dir=_REPO)
    except Exception as e:  # noqa: BLE001 — verdict must not kill the arm
        line["sentinel"] = {"verdict": "error",
                            "error": f"{type(e).__name__}: {e}"}
    fname = (f"campaign_r{round_no:02d}_{arm}.jsonl"
             if round_no is not None else f"campaign_{arm}.jsonl")
    path = os.path.join(out_dir, fname)
    ok = not errors and within
    # the curate record rides INSIDE the artifact (stages is the same
    # list campaign_block holds), so it must land before the write
    _stage(stages, "curate", "ok" if ok else "error",
           artifact=path, validation_errors=errors)
    _write_artifact(out_dir, fname, line)
    if registry.enabled():
        registry.counter(names.CAMPAIGN_ARMS,
                         status="ok" if ok else "error").inc()
    return {"arm": arm, "ok": ok, "artifact": path, "line": line,
            "errors": errors}


def _bench_shape(env: Dict[str, str]) -> Dict[str, object]:
    """The (n, dim, k, metric, dtype) the ``bench.py`` subprocess will
    sweep, derived exactly the way bench derives it (its CONFIGS table
    + the KNN_BENCH_{CONFIG,N,DIM,K,METRIC} overrides in ``env``) — the
    tune stage must pin the SAME shape, or its persisted winner lands
    under a cache key the bench's resolve never reads."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import bench  # light import: env parsing only, no backend init

    cfg = dict(bench.CONFIGS[env.get("KNN_BENCH_CONFIG", "sift1m")])
    return {
        "n": int(env.get("KNN_BENCH_N", cfg["n"])),
        "dim": int(env.get("KNN_BENCH_DIM", cfg["dim"])),
        "k": int(env.get("KNN_BENCH_K", cfg["k"])),
        "metric": env.get("KNN_BENCH_METRIC", cfg["metric"]),
        "dtype": cfg["dtype"],
    }


def _hardware_arm(arm: str, *, out_dir: str, round_no: Optional[int],
                  grid_level: str, verbose: bool) -> dict:
    """One hardware arm: gates flipped via env, `cli tune` + `bench.py`
    as subprocesses, the captured device trace (preferred) or the
    line's phase breakdown reconciled, factors persisted, the emitted
    bench line (now carrying a calibrated roofline block) appended to
    tpu_bench_lines.jsonl for refresh_bench_artifacts.py to curate."""
    stages: List[dict] = []
    store = calibrate.store_path() or os.path.join(
        out_dir, "calibration.json")
    traces = os.path.join(out_dir, "traces")
    knobs = ARM_KNOBS[arm]
    env = {
        **os.environ,
        "KNN_BENCH_MODES": "certified_pallas",
        "KNN_BENCH_PALLAS_PRECISION": str(knobs["precision"]),
        "KNN_BENCH_PALLAS_KERNEL": str(knobs["kernel"]),
        "KNN_TPU_TUNE_PRUNE": os.environ.get(
            "KNN_TPU_TUNE_PRUNE", "0.5"),
        "KNN_TPU_PROFILE_DIR": traces,
        "KNN_TPU_CALIBRATION": store,
    }
    _stage(stages, "gates", "ok", arm_env={
        k: env[k] for k in ("KNN_BENCH_MODES",
                            "KNN_BENCH_PALLAS_PRECISION",
                            "KNN_BENCH_PALLAS_KERNEL",
                            "KNN_TPU_TUNE_PRUNE", "KNN_TPU_PROFILE_DIR",
                            "KNN_TPU_CALIBRATION")})

    def run(cmd, stage_name, timeout):
        t0 = time.perf_counter()
        r = subprocess.run(cmd, cwd=_REPO, env=env,
                           capture_output=True, text=True,
                           timeout=timeout)
        dur = round(time.perf_counter() - t0, 1)
        if r.returncode != 0:
            _stage(stages, stage_name, "error", cmd=cmd, dur_s=dur,
                   stderr_tail=r.stderr.splitlines()[-5:])
            raise RuntimeError(f"{stage_name} failed (rc "
                               f"{r.returncode})")
        return r, dur

    line = None
    try:
        # tune the shape the bench will sweep — any other shape's
        # winner lands under a cache key bench's resolve never reads.
        # The grid spans every arm's precision/kernel (the bench env
        # pins the arm as explicit overrides; tile/block resolve from
        # the winner), and the warm cache makes arms 2..N zero-retime.
        shape = _bench_shape(env)
        if shape["metric"] in ("l2", "sql2", "euclidean"):
            r, dur = run(
                [sys.executable, "-m", "knn_tpu.cli", "tune",
                 "--n", str(shape["n"]), "--dim", str(shape["dim"]),
                 "--k", str(shape["k"]), "--metric",
                 str(shape["metric"]), "--grid", grid_level,
                 "--dtype", str(shape["dtype"])], "tune", 3600)
            _stage(stages, "tune", "ok", dur_s=dur, **shape)
        else:
            # cli tune has no arm for this metric (e.g. cosine rides
            # the l2 unit-vector equivalence at placement) — bench
            # resolves defaults; recorded, never silently dropped
            _stage(stages, "tune", "skipped",
                   reason=f"cli tune does not take metric "
                          f"{shape['metric']!r}", **shape)
        r, dur = run([sys.executable, "bench.py"], "bench", 7200)
        for out_line in reversed(r.stdout.splitlines()):
            out_line = out_line.strip()
            if out_line.startswith("{"):
                line = json.loads(out_line)
                break
        if line is None:
            raise RuntimeError("bench emitted no JSON line")
        _stage(stages, "bench", "ok", dur_s=dur,
               value=line.get("value"),
               device_phase_qps=line.get("device_phase_qps"))

        sel = (line.get("selectors") or {}).get(
            "certified_pallas") or {}
        pb = sel.get("phase_breakdown") or {}
        nq = int(line.get("batch") or 4096)
        measured = None
        try:
            measured = traceread.sample_from_trace(
                traces, "certified_pallas", nq=nq)
            _stage(stages, "capture", "ok", **{
                k: measured[k] for k in ("device_s", "kernel_events",
                                         "device_tracks_matched")})
        except Exception as e:  # noqa: BLE001 — host phases are the fallback source
            _stage(stages, "capture", "error",
                   error=f"{type(e).__name__}: {e}")
        if measured is None or not measured.get("device_tracks_matched"):
            measured = traceread.sample_from_phases(pb, nq=nq)
        model_kw = _knobs_for_model(line.get("pallas_knobs") or knobs)
        cfg = line.get("metric", "")
        m = _rl._METRIC_RE.match(cfg)
        if not m:
            raise RuntimeError(f"bench line metric {cfg!r} unparseable")
        n, d, k = (int(m.group(g)) for g in ("n", "d", "k"))
        block = _rl.pallas_cost_model(
            n=n, d=d, k=k, nq=nq, device_kind=line.get("device_kind"),
            backend=line.get("backend"),
            num_devices=int(line.get("devices") or 1), **model_kw)
        entry = calibrate.reconcile(block, measured, provenance={
            "config_label": _rl.config_label(
                n, d, k, device_kind=line.get("device_kind")),
            "commit": line.get("measured_at_commit")
            or _head_commit(_REPO),
            "round": round_no, "arm": arm, "rehearse": False})
        _stage(stages, "reconcile", "ok", factors=entry["factors"],
               method=entry["method"],
               model_residual_pct=entry["model_residual_pct"],
               source=entry["source"])
        calibrate.put(calibrate.key_for_block(block), entry, path=store)
        prev = os.environ.get(calibrate.CAL_ENV)
        os.environ[calibrate.CAL_ENV] = store
        try:
            block2 = _rl.pallas_cost_model(
                n=n, d=d, k=k, nq=nq,
                device_kind=line.get("device_kind"),
                backend=line.get("backend"),
                num_devices=int(line.get("devices") or 1), **model_kw)
            att = _rl.attribute(block2, measured["qps"])
        finally:
            if prev is None:
                os.environ.pop(calibrate.CAL_ENV, None)
            else:
                os.environ[calibrate.CAL_ENV] = prev
        applied = bool(att.get("calibration", {}).get("applied"))
        _stage(stages, "calibrate", "ok" if applied else "error",
               store=store, applied=applied,
               ceiling_qps=att.get("ceiling_qps"))
    except Exception as e:  # noqa: BLE001 — arm aborts, campaign continues
        # any stage can fail on hardware (no trace written AND no
        # phase device_s -> TraceReadError; a torn measurement ->
        # reconcile's sane-clamp ValueError); record it on the arm and
        # let the remaining arms run
        if registry.enabled():
            registry.counter(names.CAMPAIGN_ARMS, status="error").inc()
        return {"arm": arm, "ok": False, "line": line,
                "errors": [f"{type(e).__name__}: {e}"],
                "stages": stages}
    campaign_block = {
        "campaign_version": CAMPAIGN_VERSION, "arm": arm,
        "round": round_no, "rehearse": False, "stages": stages,
    }
    line = dict(line, roofline=att,
                roofline_pct=att.get("roofline_pct"),
                bound_class=att.get("bound_class"),
                model_residual_pct=entry["model_residual_pct"],
                campaign=campaign_block)
    errors = (_rl.validate_block(att)
              + calibrate.validate_calibration(att.get("calibration"))
              + calibrate.validate_campaign_block(campaign_block))
    fname = (f"campaign_r{round_no:02d}_{arm}.jsonl"
             if round_no is not None else f"campaign_{arm}.jsonl")
    path = os.path.join(out_dir, fname)
    ok = applied and not errors
    # the curate record rides INSIDE the artifact (stages is the same
    # list campaign_block holds), so it must land before the write
    _stage(stages, "curate", "ok" if ok else "error", artifact=path,
           validation_errors=errors)
    _write_artifact(out_dir, fname, line)
    if not errors:
        # feed the curated pipeline: refresh_bench_artifacts.py reads
        # session lines from tpu_bench_lines.jsonl (and validates the
        # calibration/campaign blocks before curating them)
        with open(os.path.join(_REPO, "tpu_bench_lines.jsonl"),
                  "a") as f:
            f.write(json.dumps(line, sort_keys=True) + "\n")
    if registry.enabled():
        registry.counter(names.CAMPAIGN_ARMS,
                         status="ok" if ok else "error").inc()
    return {"arm": arm, "ok": ok, "artifact": path, "line": line,
            "errors": errors}


def run_campaign(
    *, rehearse: bool = False, arms: Optional[Sequence[str]] = None,
    out_dir: Optional[str] = None, round_no: Optional[int] = None,
    seed: int = 0, shape: Optional[Dict[str, int]] = None,
    trace_fixture: Optional[str] = None, grid_level: str = "quick",
    verbose: bool = False,
) -> dict:
    """Run the campaign over ``arms`` and return the summary artifact
    (per-arm outcomes + where each JSONL landed).  See module
    docstring for the stage loop."""
    arms = list(arms or arms_from_env()
                or (DEFAULT_REHEARSE_ARMS if rehearse
                    else DEFAULT_ARMS))
    for a in arms:
        if a not in ARM_KNOBS:
            raise ValueError(f"unknown arm {a!r}; expected one of "
                             f"{sorted(ARM_KNOBS)}")
    out_dir = out_dir or campaign_dir()
    os.makedirs(out_dir, exist_ok=True)
    if round_no is None:
        round_no = round_from_env()
    results = []
    for arm in arms:
        if rehearse:
            results.append(_rehearse_arm(
                arm, out_dir=out_dir,
                shape=dict(REHEARSE_SHAPE, **(shape or {})),
                seed=seed, round_no=round_no,
                trace_fixture=(trace_fixture
                               or default_trace_fixture()),
                grid_level=grid_level, verbose=verbose))
        else:
            results.append(_hardware_arm(
                arm, out_dir=out_dir, round_no=round_no,
                grid_level=grid_level, verbose=verbose))
    return {
        "campaign_version": CAMPAIGN_VERSION,
        "rehearse": bool(rehearse),
        "round": round_no,
        "out_dir": out_dir,
        "arms": [{"arm": r["arm"], "ok": r["ok"],
                  "errors": r.get("errors"),
                  "artifact": r.get("artifact")} for r in results],
        "ok": all(r["ok"] for r in results),
        "results": results,
    }
