"""Mutable index: delta-shard inserts, tombstone deletes, and
snapshot-swap compaction over the immutable placement machinery.

Every ``ShardedKNN`` placement is immutable by design — the database is
padded, sharded, and transferred once, and every compiled program bakes
the row count in.  TPU-KNN's thesis (arXiv:2206.14286) is that brute
force at peak FLOP/s needs no tree to rebuild, which reduces mutability
to pure **delta management**:

- **Delta shard** — :meth:`MutableIndex.insert` appends rows to a small
  device-resident TAIL placement searched alongside the main placement
  on every query.  The tail pads up a geometric capacity ladder (the
  PR 1 bucket-ladder discipline) and its search program takes the valid
  row count as a TRACED operand (``parallel.sharded._hosttier_program``
  — the host-tier sweep program reused verbatim), so inserts never
  trigger a recompile while the tail stays on its ladder rung.
- **Tombstone deletes** — :meth:`MutableIndex.delete` marks ids dead.
  Searches run WIDENED by a fixed certify reserve (the main placement
  is built at ``k_eff = k + reserve``), so after dead rows are masked
  out of the merged candidate list the surviving top-k is provably the
  exact top-k of the live rows: at most ``reserve`` tombstones can
  precede them, and the widened select already ranked past that many.
  This is the PR 3 bound discipline applied to masking — the certify
  width covers the mask, so exactness claims survive deletion; delete
  refuses LOUDLY past the reserve (compaction resets it).
- **Snapshot-swap compaction** — :meth:`MutableIndex.compact` builds a
  fresh placement from the surviving rows (re-quantizing on demand —
  the int8 placement is per-``ShardedKNN`` and rebuilds lazily), warms
  a replacement serving engine OFF the serving path, and swaps it in
  atomically under the index lock between serving micro-batches: the
  epoch counter bumps, in-flight batches finish on the snapshot they
  pinned at submit, and no search ever observes a half-swapped state.

Exactness contract (the pinned mutation oracle, tests/test_index.py):
after ANY interleaving of inserts, deletes, and compactions,
:meth:`MutableIndex.search_certified` results are bitwise-identical to
a fresh index built from the surviving rows — across coarse precisions
(f32/bf16x3/int8) and kernels (tiled/streaming/fused).  The mechanism:
the certified machinery proves each part's candidate list exact, final
distances are float64-refined per pair (``ops.refine`` — per-pair
deterministic arithmetic, placement-invariant), and the cross-part
merge is the same lexicographic (distance, position) order the device
merge tree runs, under a monotone position map.

Unsupported placements refuse loudly instead of serving stale results:
host-RAM-tier and multi-host placements raise
:class:`~knn_tpu.index.artifact.MutationUnsupportedError` on
``insert``/``delete`` (docs/INDEX.md).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from knn_tpu import obs
from knn_tpu.index.artifact import (
    MutationBudgetError,
    MutationUnsupportedError,
)
from knn_tpu.obs import names as _mn

#: delta-tail capacity ladder defaults (rows); overridable per index or
#: via KNN_TPU_DELTA_MIN_ROWS / KNN_TPU_DELTA_MAX_ROWS
DELTA_MIN_ROWS = 256
DELTA_MAX_ROWS = 65536
#: certify-widening reserve: the main placement selects k + reserve so
#: up to ``reserve`` tombstones can be masked without losing exactness
#: (KNN_TPU_DELTA_RESERVE)
DELTA_RESERVE = 32

#: int64 sentinel for "no candidate" positions in the merged list —
#: larger than any real global position, so it sorts last and maps to
#: id -1 (dead) in the filter
_SENT64 = np.int64(1) << 62


def _env_int(name: str, default: Optional[int]) -> Optional[int]:
    """Strict env parse (the admission-switch discipline: a typo'd knob
    raises instead of silently running at the default)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError as e:
        raise ValueError(f"{name}={raw!r} is not an int") from e


def _env_float(name: str, default: Optional[float]) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError as e:
        raise ValueError(f"{name}={raw!r} is not a number") from e


class _Snapshot:
    """One immutable, search-consistent view of the index: everything a
    query needs, pinned at :meth:`MutableIndex._snapshot` time.  Swaps
    replace the index's CURRENT snapshot; in-flight searches keep
    theirs (and, through it, the old placement and engine) alive until
    they finish — the epoch visibility rule."""

    __slots__ = ("epoch", "main", "base_ids", "tail", "tail_ids",
                 "tail_len", "tail_parts_count", "tomb_ids", "engine",
                 "n_base", "all_ids", "k_eff")

    def __init__(self, epoch, main, base_ids, tail, tail_ids,
                 tail_parts_count, tomb_ids, engine, k_eff):
        self.epoch = epoch
        self.main = main
        self.base_ids = base_ids
        self.tail = tail  # [T, D] f32 or None
        self.tail_ids = tail_ids
        self.tail_len = 0 if tail is None else tail.shape[0]
        self.tail_parts_count = tail_parts_count
        self.tomb_ids = tomb_ids  # sorted int64 array
        self.engine = engine
        self.n_base = base_ids.shape[0]
        self.all_ids = (base_ids if tail is None
                        else np.concatenate([base_ids, tail_ids]))
        self.k_eff = k_eff

    def live_rows(self) -> int:
        return self.n_base + self.tail_len - self.tomb_ids.shape[0]

    def ids_of(self, pos: np.ndarray) -> np.ndarray:
        """External ids for global positions; sentinel / out-of-range
        positions map to -1 (dead)."""
        n_total = self.all_ids.shape[0]
        valid = (pos >= 0) & (pos < n_total)
        safe = np.clip(pos, 0, n_total - 1)
        return np.where(valid, self.all_ids[safe], np.int64(-1))


class _TailHandle:
    """An in-flight tail dispatch: device outputs + the redo closure the
    transient-retry fetch discipline needs (parallel.sharded)."""

    __slots__ = ("out", "redo", "rows", "n_base")

    def __init__(self, out, redo, rows: int, n_base: int):
        self.out = out
        self.redo = redo
        self.rows = rows
        self.n_base = n_base

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        """(d [rows, k_t] f32, pos [rows, k_t] int64 global positions;
        masked slots carry +inf / the int64 sentinel).  d and pos come
        from the SAME execution — a transient fetch failure relaunches
        and rebinds both (the host-tier collect discipline)."""
        from knn_tpu.parallel.sharded import (
            _INT_SENTINEL,
            _fetch_or_redispatch,
        )

        cur = {"out": self.out}

        def redo0():
            cur["out"] = self.redo()
            return cur["out"][0]

        d = _fetch_or_redispatch(self.out[0], redo0, "delta-tail fetch")
        i = np.asarray(cur["out"][1])
        d = np.asarray(d)[: self.rows]
        i = i[: self.rows].astype(np.int64)
        pad = i == _INT_SENTINEL
        pos = np.where(pad, _SENT64, i + self.n_base)
        return d, pos


class MutableIndex:
    """A mutable KNN index over an immutable main placement plus a
    device-resident delta tail and an id tombstone set (see the module
    docstring for the design).  ``search``/``search_certified`` return
    ``(distances, ids)`` in EXTERNAL id space (``ids`` at construction,
    ``insert``'s ids afterwards), never raw placement positions.

    Thread-safety: guarded by ``self._lock`` (a Condition: writers
    notify the background compactor).  Searches pin a consistent
    snapshot under the lock and then run lock-free on it; the lock is
    never held across a device dispatch or an XLA compile.
    """

    def __init__(
        self,
        train,
        ids: Optional[Sequence[int]] = None,
        *,
        mesh,
        k: int,
        metric: str = "l2",
        merge: Optional[str] = None,
        train_tile: Optional[int] = None,
        compute_dtype=None,
        reserve: Optional[int] = None,
        delta_min_rows: Optional[int] = None,
        delta_max_rows: Optional[int] = None,
        compact_tail_rows: Optional[int] = None,
        compact_tombstones: Optional[int] = None,
        hbm_budget_bytes: Optional[int] = None,
    ):
        from knn_tpu.parallel.mesh import db_topology
        from knn_tpu.parallel.sharded import ShardedKNN

        if metric.lower() not in ("l2", "sql2", "euclidean"):
            raise MutationUnsupportedError(
                f"MutableIndex supports the l2 metric family only, got "
                f"{metric!r} (cosine re-normalizes rows at placement "
                f"and L1 has no certified bound; docs/INDEX.md)")
        train = np.ascontiguousarray(np.asarray(train, np.float32))
        if train.ndim != 2:
            raise ValueError(f"train must be 2-D, got {train.shape}")
        n, dim = train.shape
        if ids is None:
            ids_arr = np.arange(n, dtype=np.int64)
        else:
            ids_arr = np.asarray(ids, dtype=np.int64).reshape(-1)
            if ids_arr.shape[0] != n:
                raise ValueError(
                    f"ids length {ids_arr.shape[0]} != rows {n}")
            if np.unique(ids_arr).shape[0] != n:
                raise ValueError("ids must be unique")
        self.k = int(k)
        self.dim = int(dim)
        self.mesh = mesh
        self.metric = metric.lower()
        if reserve is None:
            reserve = _env_int("KNN_TPU_DELTA_RESERVE", DELTA_RESERVE)
        self._reserve = int(reserve)
        if self._reserve < 1:
            raise ValueError(
                f"reserve must be >= 1, got {self._reserve}")
        self._delta_min = int(delta_min_rows
                              if delta_min_rows is not None else
                              _env_int("KNN_TPU_DELTA_MIN_ROWS",
                                       DELTA_MIN_ROWS))
        self._delta_max = int(delta_max_rows
                              if delta_max_rows is not None else
                              _env_int("KNN_TPU_DELTA_MAX_ROWS",
                                       DELTA_MAX_ROWS))
        self._compact_tail_rows = (
            compact_tail_rows if compact_tail_rows is not None else
            _env_int("KNN_TPU_COMPACT_TAIL_ROWS", None))
        self._compact_tombstones = (
            compact_tombstones if compact_tombstones is not None else
            _env_int("KNN_TPU_COMPACT_TOMBSTONES", None))
        hosts, chips = db_topology(mesh)
        self._db_shards = hosts * chips
        self._multihost = hosts > 1
        #: constructor args replayed by compaction when it builds the
        #: fresh placement — ONE home, so a compacted placement can
        #: never silently differ from the original's configuration
        self._ctor = dict(metric=self.metric, merge=merge,
                          train_tile=train_tile,
                          compute_dtype=compute_dtype,
                          hbm_budget_bytes=hbm_budget_bytes)
        k_eff = self._k_eff_for(n)
        if k_eff < self.k:
            if self.k > n:
                raise ValueError(f"k={k} > {n} database rows")
            raise ValueError(
                f"k={k} exceeds the per-shard row count "
                f"({-(-n // self._db_shards)} rows over "
                f"{self._db_shards} db shards); use fewer db shards")
        self._main = ShardedKNN(train, mesh=mesh, k=k_eff, **self._ctor)
        #: tail searches always select k + reserve (constant across
        #: epochs -> one compiled tail program per capacity rung)
        self._k_tail = self.k + self._reserve
        if self._delta_min < 1 or self._delta_max < self._delta_min:
            raise ValueError(
                f"delta ladder [{self._delta_min}, {self._delta_max}] "
                f"is not a valid range")
        self._lock = threading.Condition()
        self._epoch = 0
        self._base_ids = ids_arr
        self._tail_parts: List[np.ndarray] = []
        self._tail_id_parts: List[np.ndarray] = []
        self._tail_len = 0
        self._tombstones: set = set()
        self._live: set = set(ids_arr.tolist())
        self._snap_cache: Optional[_Snapshot] = None
        self._tail_place: Optional[dict] = None
        self._inner_engine = None
        self._engine_kwargs: Optional[dict] = None
        self._compactions = 0
        self._last_compaction: Optional[dict] = None
        self._closed = False
        self._compactor_t: Optional[threading.Thread] = None
        #: serializes compactions (never held together with _lock on
        #: the same thread EXCEPT in the documented compact() order:
        #: _compact_lock first, _lock only for the brief swap)
        self._compact_lock = threading.Lock()
        obs.gauge(_mn.INDEX_EPOCH).set(0.0)
        obs.gauge(_mn.INDEX_TAIL_ROWS).set(0.0)
        obs.gauge(_mn.INDEX_TOMBSTONES).set(0.0)
        obs.health.register_index(self)

    # -- construction helpers ---------------------------------------------
    def _k_eff_for(self, n_rows: int) -> int:
        """The widened select width for an ``n_rows`` main placement:
        k + reserve, capped by the rows a shard can actually rank."""
        padded = -(-n_rows // self._db_shards) * self._db_shards
        return min(self.k + self._reserve, n_rows,
                   padded // self._db_shards)

    @property
    def budget(self) -> int:
        """Tombstones the CURRENT epoch can absorb before exactness
        would need a wider select than the placement compiled —
        delete() refuses past it, compaction resets it."""
        return self._main.k - self.k

    # -- refusals ----------------------------------------------------------
    def _require_mutable(self, what: str) -> None:
        if self._main._host_tier is not None:
            raise MutationUnsupportedError(
                f"{what}: this placement runs the host-RAM shard tier "
                f"(corpus exceeds the per-host HBM budget); the delta "
                f"tail has no resident placement to merge against — "
                f"compact offline and rebuild, or raise the budget "
                f"(docs/INDEX.md)")
        if self._multihost:
            raise MutationUnsupportedError(
                f"{what}: multi-host placements have no write "
                f"replication protocol yet — a single-host write would "
                f"silently serve stale results from the other hosts "
                f"(docs/INDEX.md)")

    # -- snapshots ---------------------------------------------------------
    def _snapshot(self) -> _Snapshot:
        """The current consistent view (cached; invalidated by every
        mutation and swap).  Cheap on the serving path: one lock hop
        when the cache is warm."""
        with self._lock:
            snap = self._snap_cache
            if snap is not None:
                return snap
            tail = (None if self._tail_len == 0 else
                    np.concatenate(self._tail_parts))
            tail_ids = (None if self._tail_len == 0 else
                        np.concatenate(self._tail_id_parts))
            snap = _Snapshot(
                self._epoch, self._main, self._base_ids, tail, tail_ids,
                len(self._tail_parts),
                np.asarray(sorted(self._tombstones), np.int64),
                self._inner_engine, self._main.k)
            self._snap_cache = snap
            return snap

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    # -- writes ------------------------------------------------------------
    def insert(self, vectors, ids) -> dict:
        """Append rows to the delta tail under fresh unique ids.
        Visible to every search submitted after this returns (epoch
        visibility: searches already in flight keep their snapshot).
        Raises :class:`MutationBudgetError` past the tail's top ladder
        rung and ``ValueError`` on id reuse — including ids tombstoned
        this epoch (their mask would shadow the new row; compaction
        frees the id)."""
        self._require_mutable("insert")
        v = np.ascontiguousarray(np.asarray(vectors, np.float32))
        if v.ndim != 2 or v.shape[1] != self.dim:
            raise ValueError(
                f"vectors must be [N, {self.dim}], got {v.shape}")
        ids_arr = np.asarray(ids, dtype=np.int64).reshape(-1)
        if ids_arr.shape[0] != v.shape[0]:
            raise ValueError(
                f"{ids_arr.shape[0]} ids for {v.shape[0]} rows")
        if np.unique(ids_arr).shape[0] != ids_arr.shape[0]:
            raise ValueError("insert ids must be unique")
        with self._lock:
            for i in ids_arr.tolist():
                if i in self._live:
                    raise ValueError(f"id {i} is already live")
                if i in self._tombstones:
                    raise ValueError(
                        f"id {i} was deleted this epoch; compact() "
                        f"before reusing the id")
            if self._tail_len + v.shape[0] > self._delta_max:
                raise MutationBudgetError(
                    f"delta tail full: {self._tail_len} + {v.shape[0]} "
                    f"rows exceeds the {self._delta_max}-row top ladder "
                    f"rung; compact() (or raise delta_max_rows / "
                    f"KNN_TPU_DELTA_MAX_ROWS)")
            self._tail_parts.append(v)
            self._tail_id_parts.append(ids_arr)
            self._tail_len += v.shape[0]
            self._live.update(ids_arr.tolist())
            self._snap_cache = None
            tail_len = self._tail_len
            self._lock.notify_all()  # wake the compactor
        obs.gauge(_mn.INDEX_TAIL_ROWS).set(float(tail_len))
        return {"epoch": self.epoch, "tail_rows": tail_len}

    def delete(self, ids) -> dict:
        """Tombstone live ids.  The rows stay physically placed until
        compaction; every search masks them out of the merged candidate
        list, with the certify reserve guaranteeing the masked select
        is still the exact live top-k.  Refuses past the reserve budget
        (:class:`MutationBudgetError`) and on unknown/dead ids
        (``KeyError``)."""
        self._require_mutable("delete")
        ids_arr = np.asarray(ids, dtype=np.int64).reshape(-1)
        with self._lock:
            for i in ids_arr.tolist():
                if i not in self._live:
                    raise KeyError(f"id {i} is not live")
            if len(self._tombstones) + ids_arr.shape[0] > self.budget:
                raise MutationBudgetError(
                    f"tombstone budget exhausted: "
                    f"{len(self._tombstones)} + {ids_arr.shape[0]} "
                    f"exceeds the certify reserve {self.budget} "
                    f"(k_eff={self._main.k} - k={self.k}); compact() "
                    f"to drop the dead rows")
            live_after = (self._base_ids.shape[0] + self._tail_len
                          - len(self._tombstones) - ids_arr.shape[0])
            if live_after < self.k:
                raise MutationBudgetError(
                    f"delete would leave {live_after} live rows < "
                    f"k={self.k}")
            self._tombstones.update(ids_arr.tolist())
            self._live.difference_update(ids_arr.tolist())
            self._snap_cache = None
            n_tombs = len(self._tombstones)
            self._lock.notify_all()
        obs.gauge(_mn.INDEX_TOMBSTONES).set(float(n_tombs))
        return {"epoch": self.epoch, "tombstones": n_tombs}

    # -- delta-tail device search -----------------------------------------
    def _capacity_for(self, tail_len: int) -> int:
        """Smallest ladder rung holding ``tail_len`` rows.  Rungs
        double from a floor that guarantees every shard can rank
        k + reserve rows, and every rung is a db-shard multiple."""
        floor = max(self._delta_min, self._k_tail * self._db_shards)
        floor = -(-floor // self._db_shards) * self._db_shards
        cap = floor
        while cap < tail_len:
            cap *= 2
        return cap

    def _tail_device(self, snap: _Snapshot) -> dict:
        """The snapshot's tail placed on device at its ladder-rung
        capacity (cached per (epoch, tail_len) — inserts re-place, a
        stable tail is transferred once)."""
        from knn_tpu.ops.pallas_knn import PAD_VAL
        from knn_tpu.parallel.collectives import replicate, shard
        from knn_tpu.parallel.mesh import db_axes

        key = (snap.epoch, snap.tail_len)
        with self._lock:
            tp = self._tail_place
            if tp is not None and tp["key"] == key:
                return tp
        capacity = self._capacity_for(snap.tail_len)
        arr = np.full((capacity, self.dim), PAD_VAL, np.float32)
        if snap.tail_len:
            arr[: snap.tail_len] = snap.tail
        placed = {
            "key": key,
            "capacity": capacity,
            "tp": shard(arr, self.mesh, db_axes(self.mesh)),
            "nv": replicate(np.asarray([snap.tail_len], np.int32),
                            self.mesh),
        }
        with self._lock:
            self._tail_place = placed
        return placed

    def _dispatch_tail(self, snap: _Snapshot, q_np: np.ndarray
                       ) -> _TailHandle:
        """Async tail search: the host-tier per-sweep program (traced
        valid-row count — ONE compiled executable per (query shape,
        capacity rung), never per tail size) over the snapshot's placed
        tail.  Returns a handle; fetch merges on host."""
        from knn_tpu.parallel.sharded import (
            _hosttier_program,
            _retry_transient,
        )

        dev = self._tail_device(snap)
        prog = _hosttier_program(
            self.mesh, self._k_tail, snap.main.metric, snap.main.merge,
            self._ctor["train_tile"], snap.main._dtype_key,
            dcn_merge=snap.main.dcn_merge, donate=False)
        qp, n_q = snap.main._place_queries(q_np)
        out = _retry_transient(
            lambda: prog(qp, dev["tp"], dev["nv"]),
            "delta-tail dispatch")
        return _TailHandle(
            out, lambda: prog(qp, dev["tp"], dev["nv"]), n_q,
            snap.n_base)

    # -- merged, masked selection -----------------------------------------
    @staticmethod
    def _merge_filter(snap: _Snapshot, d_parts, p_parts, k: int):
        """Lexicographic (distance, global position) merge of per-part
        candidate lists, tombstones and sentinels masked out, first k
        survivors kept — the same associative order the device merge
        tree runs, so a monotone position remap (compaction, the fresh
        oracle) preserves it."""
        cd = (d_parts[0] if len(d_parts) == 1
              else np.concatenate(d_parts, axis=1))
        cp = (p_parts[0] if len(p_parts) == 1
              else np.concatenate(p_parts, axis=1))
        order = np.lexsort((cp, cd), axis=-1)
        cd = np.take_along_axis(cd, order, axis=-1)
        cp = np.take_along_axis(cp, order, axis=-1)
        ids = snap.ids_of(cp)
        dead = ids < 0
        if snap.tomb_ids.size:
            dead |= np.isin(ids, snap.tomb_ids)
        # stable partition: live candidates keep their merged order
        sel = np.argsort(dead, kind="stable", axis=-1)[:, :k]
        if bool(np.take_along_axis(dead, sel, axis=-1).any()):
            raise RuntimeError(
                "masked merge ran out of live candidates — the certify "
                "reserve no longer covers the tombstone count (index "
                "invariant violated; please report)")
        return (np.take_along_axis(cd, sel, axis=-1),
                np.take_along_axis(ids, sel, axis=-1))

    def search(self, queries, *, k: Optional[int] = None,
               return_sqrt: bool = False):
        """(distances [Q, k] f32, ids [Q, k] int64) of the k nearest
        LIVE rows: the widened main select merged with the delta-tail
        select, tombstones masked at merge time.  ``k`` may only
        shrink below the construction k (the reserve was sized for
        it)."""
        k = self.k if k is None else int(k)
        if not 0 < k <= self.k:
            raise ValueError(
                f"k={k} outside (0, {self.k}] — the certify reserve "
                f"was sized for the construction k")
        snap = self._snapshot()
        if k > snap.live_rows():
            raise ValueError(
                f"k={k} > {snap.live_rows()} live rows")
        q = np.ascontiguousarray(np.asarray(queries, np.float32))
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(
                f"queries must be [N, {self.dim}], got {q.shape}")
        tail_h = (self._dispatch_tail(snap, q)
                  if snap.tail_len else None)
        d_m, i_m = snap.main.search(q)
        d_parts = [np.asarray(d_m)]
        p_parts = [np.asarray(i_m).astype(np.int64)]
        if tail_h is not None:
            d_t, p_t = tail_h.fetch()
            d_parts.append(d_t)
            p_parts.append(p_t)
        d, ids = self._merge_filter(snap, d_parts, p_parts, k)
        if return_sqrt:
            d = np.sqrt(d)
        return d, ids

    def search_certified(self, queries, *, margin: int = 28,
                         selector: str = "approx", **knobs):
        """Certified-exact live top-k: ``(distances_f64, ids, stats)``.

        The main part runs the full PR 3 certified pipeline at the
        widened ``k_eff`` (coarse precision/kernel knobs pass through —
        ``precision=\"int8\"``, ``kernel=\"fused\"``, ...), so its
        candidate list is PROVABLY the exact top-k_eff; the delta tail
        is float64-scanned on host (the tail is small by construction —
        O(Q*T*D) next to the O(Q*N*D) device sweep).  Both parts'
        final distances are float64-refined per pair (ops.refine), the
        merge is lexicographic (distance, position), and tombstones
        mask after it under the reserve guarantee — which is what makes
        the result bitwise-identical to a fresh index built from the
        surviving rows (the pinned mutation oracle)."""
        from knn_tpu.ops.refine import refine_exact

        snap = self._snapshot()
        if self.k > snap.live_rows():
            raise ValueError(
                f"k={self.k} > {snap.live_rows()} live rows")
        q = np.ascontiguousarray(np.asarray(queries, np.float32))
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(
                f"queries must be [N, {self.dim}], got {q.shape}")
        knobs.pop("return_distances", None)
        return_sqrt = bool(knobs.pop("return_sqrt", False))
        _, i_m, stats = snap.main.search_certified(
            q, margin=margin, selector=selector,
            return_distances=False, **knobs)
        # float64 per-pair refine of the PROVEN-exact candidate set:
        # deterministic arithmetic, independent of placement shape,
        # coarse precision, and kernel — the oracle anchor
        d64_m, i64_m = refine_exact(
            snap.main._host_train(), q, np.asarray(i_m), snap.k_eff)
        d_parts = [d64_m]
        p_parts = [i64_m]
        if snap.tail_len:
            k_t = min(self._k_tail, snap.tail_len)
            cand = np.broadcast_to(
                np.arange(snap.tail_len, dtype=np.int64),
                (q.shape[0], snap.tail_len))
            d64_t, i64_t = refine_exact(snap.tail, q, cand, k_t)
            d_parts.append(d64_t)
            p_parts.append(i64_t + snap.n_base)
        d, ids = self._merge_filter(snap, d_parts, p_parts, self.k)
        if return_sqrt:
            d = np.sqrt(d)
        stats = dict(stats)
        stats["index"] = {
            "epoch": snap.epoch,
            "k_eff": snap.k_eff,
            "tail_rows": snap.tail_len,
            "tombstones": int(snap.tomb_ids.shape[0]),
            "tail_certified": "host_f64",
        }
        return d, ids, stats

    # -- compaction --------------------------------------------------------
    def compact(self) -> dict:
        """Merge the tail and drop tombstoned rows into a fresh
        placement, then swap it in snapshot-consistently.  The build
        (re-quantize, re-place, re-warm the serving engine) runs OFF
        the serving path; only the final pointer swap takes the index
        lock, so in-flight searches finish on the old epoch and no
        micro-batch ever stalls on the swap (the pinned live-traffic
        proof).  Writes that landed DURING the build carry over: rows
        inserted after the cut stay in the new tail, ids deleted after
        the cut stay tombstoned against the new placement."""
        from knn_tpu.parallel.sharded import ShardedKNN
        from knn_tpu.serving.engine import ServingEngine

        self._require_mutable("compact")
        t0 = time.perf_counter()
        with self._compact_lock:
            snap = self._snapshot()
            tomb_snap = set(snap.tomb_ids.tolist())
            base_host = snap.main._host_train()
            keep_b = (~np.isin(snap.base_ids, snap.tomb_ids)
                      if snap.tomb_ids.size
                      else np.ones(snap.n_base, bool))
            parts = [base_host[keep_b]]
            id_parts = [snap.base_ids[keep_b]]
            dropped = int(snap.n_base - parts[0].shape[0])
            merged = 0
            if snap.tail_len:
                keep_t = (~np.isin(snap.tail_ids, snap.tomb_ids)
                          if snap.tomb_ids.size
                          else np.ones(snap.tail_len, bool))
                parts.append(snap.tail[keep_t])
                id_parts.append(snap.tail_ids[keep_t])
                dropped += int(snap.tail_len - parts[1].shape[0])
                merged = int(parts[1].shape[0])
            new_base = (parts[0] if len(parts) == 1
                        else np.concatenate(parts))
            new_ids = (id_parts[0] if len(id_parts) == 1
                       else np.concatenate(id_parts))
            if new_base.shape[0] < self.k:
                raise MutationBudgetError(
                    f"compaction would leave {new_base.shape[0]} rows "
                    f"< k={self.k}")
            k_eff = self._k_eff_for(new_base.shape[0])
            new_main = ShardedKNN(new_base, mesh=self.mesh, k=k_eff,
                                  **self._ctor)
            new_engine = None
            with self._lock:
                kw = self._engine_kwargs
                old_engine = self._inner_engine
            if kw is not None:
                # pre-warm the replacement engine OFF the serving path:
                # the first post-swap micro-batch must hit a compiled
                # executable, never an inline XLA compile
                new_engine = ServingEngine(new_main, **kw)
                new_engine.warmup(tuple(
                    sorted(getattr(old_engine, "warmed_ops", ()))
                    or ("search",)))
            t_swap = time.perf_counter()
            with self._lock:
                self._main = new_main
                self._base_ids = new_ids
                self._tail_parts = self._tail_parts[
                    snap.tail_parts_count:]
                self._tail_id_parts = self._tail_id_parts[
                    snap.tail_parts_count:]
                self._tail_len = int(sum(p.shape[0]
                                         for p in self._tail_parts))
                self._tombstones = {t for t in self._tombstones
                                    if t not in tomb_snap}
                self._epoch += 1
                if new_engine is not None:
                    self._inner_engine = new_engine
                self._snap_cache = None
                self._tail_place = None
                self._compactions += 1
                epoch = self._epoch
                tail_len = self._tail_len
                n_tombs = len(self._tombstones)
                report = self._last_compaction = {
                    "epoch": epoch,
                    "rows": int(new_base.shape[0]),
                    "rows_dropped": dropped,
                    "tail_rows_merged": merged,
                    "carry_tail_rows": tail_len,
                    "carry_tombstones": n_tombs,
                    "wall_s": round(time.perf_counter() - t0, 4),
                    "swap_s": round(time.perf_counter() - t_swap, 6),
                }
        obs.counter(_mn.INDEX_COMPACTIONS).inc()
        obs.histogram(_mn.INDEX_SWAP_SECONDS).observe(
            report["swap_s"])
        obs.gauge(_mn.INDEX_EPOCH).set(float(epoch))
        obs.gauge(_mn.INDEX_TAIL_ROWS).set(float(tail_len))
        obs.gauge(_mn.INDEX_TOMBSTONES).set(float(n_tombs))
        obs.record_span("index.compact", None, report["wall_s"],
                        epoch=epoch, rows=report["rows"],
                        rows_dropped=dropped, tail_rows_merged=merged,
                        swap_s=report["swap_s"])
        return dict(report)

    def _compact_due(self) -> bool:
        """Caller holds ``self._lock``."""
        if self._compact_tail_rows is not None \
                and self._tail_len >= self._compact_tail_rows:
            return True
        if self._compact_tombstones is not None \
                and len(self._tombstones) >= self._compact_tombstones:
            return True
        return False

    def start_compactor(self, interval_s: Optional[float] = None
                        ) -> None:
        """Start the background compaction thread: compacts whenever a
        threshold (``compact_tail_rows`` / ``compact_tombstones``)
        trips, or every ``interval_s`` (KNN_TPU_COMPACT_INTERVAL_S)
        while there is anything to fold in.  Idempotent; ``close()``
        stops it."""
        interval = (interval_s if interval_s is not None else
                    _env_float("KNN_TPU_COMPACT_INTERVAL_S", None))

        def loop():
            deadline = (None if interval is None
                        else time.monotonic() + interval)
            while True:
                with self._lock:
                    if self._closed:
                        return
                    due = self._compact_due()
                    if not due and deadline is not None \
                            and time.monotonic() >= deadline \
                            and (self._tail_len or self._tombstones):
                        due = True
                    if not due:
                        if deadline is None:
                            # threshold-only config: every state change
                            # notifies the condition, so a bare wait is
                            # free (no idle 20 Hz poll on a long-lived
                            # replica)
                            self._lock.wait()
                        else:
                            self._lock.wait(timeout=max(
                                0.01, min(0.05,
                                          deadline - time.monotonic())))
                        continue
                if deadline is not None:
                    deadline = time.monotonic() + interval
                try:
                    self.compact()
                except Exception as e:  # noqa: BLE001 — keep compacting
                    obs.emit_event("index.compact_error",
                                   error=f"{type(e).__name__}: {e}")
                    with self._lock:
                        # a failing compaction must not spin hot
                        self._lock.wait(timeout=0.25)

        with self._lock:
            if self._compactor_t is not None \
                    and self._compactor_t.is_alive():
                return
            self._closed = False
            self._compactor_t = threading.Thread(
                target=loop, name="knn-index-compactor", daemon=True)
            self._compactor_t.start()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()
            t = self._compactor_t
        if t is not None:
            t.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- serving -----------------------------------------------------------
    def serving_engine(self, **engine_kwargs) -> "MutableServingEngine":
        """A :class:`MutableServingEngine` over this index — the
        QueryQueue-compatible frontend that searches the delta tail
        alongside every bucketed main dispatch and applies writes as a
        first-class op.  Engine kwargs (buckets/min_bucket/max_bucket/
        ...) are remembered so compaction can rebuild and pre-warm the
        replacement engine off the serving path."""
        from knn_tpu.serving.engine import ServingEngine

        with self._lock:
            if self._engine_kwargs is not None:
                raise RuntimeError(
                    "serving_engine() was already called for this "
                    "index")
        inner = ServingEngine(self._main, **engine_kwargs)
        with self._lock:
            self._engine_kwargs = dict(engine_kwargs)
            self._inner_engine = inner
            self._snap_cache = None
        return MutableServingEngine(self)

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "epoch": self._epoch,
                "k": self.k,
                "k_eff": self._main.k,
                "reserve": self._reserve,
                "budget": self._main.k - self.k,
                "rows": int(self._base_ids.shape[0]),
                "tail_rows": self._tail_len,
                "tail_capacity": self._capacity_for(self._tail_len),
                "tombstones": len(self._tombstones),
                "live_rows": (self._base_ids.shape[0] + self._tail_len
                              - len(self._tombstones)),
                "compactions": self._compactions,
                "compact_tail_rows": self._compact_tail_rows,
                "compact_tombstones": self._compact_tombstones,
                "compactor_alive": (
                    self._compactor_t is not None
                    and self._compactor_t.is_alive()),
                "metric": self.metric,
                **({"last_compaction": dict(self._last_compaction)}
                   if self._last_compaction else {}),
            }


class _MutablePending:
    """An in-flight index-serving request: the inner engine's bucketed
    main dispatch plus the delta-tail dispatch, merged and masked at
    result time.  The tail outputs are fetched FIRST so the extra
    transfer lands before the inner join span, keeping the request's
    waterfall segments tiling within tolerance."""

    __slots__ = ("_snap", "_pending", "_tail", "_k", "_result")

    def __init__(self, snap: _Snapshot, pending, tail: Optional[
            _TailHandle], k: int):
        self._snap = snap
        self._pending = pending
        self._tail = tail
        self._k = k
        self._result = None

    @property
    def trace_id(self):
        return self._pending.trace_id

    @property
    def tenant(self):
        return self._pending.tenant

    def result(self):
        if self._result is not None:
            return self._result
        tail_parts = None
        if self._tail is not None:
            # fetched BEFORE the inner result so the transfer lands
            # inside the engine request span's wall (the waterfall's
            # attributed device window), never after it
            tail_parts = self._tail.fetch()
        d_m, i_m = self._pending.result()
        t0 = time.perf_counter()
        d_parts = [np.asarray(d_m)]
        p_parts = [np.asarray(i_m).astype(np.int64)]
        if tail_parts is not None:
            d_parts.append(tail_parts[0])
            p_parts.append(tail_parts[1])
        self._result = MutableIndex._merge_filter(
            self._snap, d_parts, p_parts, self._k)
        # the merge/mask happens after the engine request span closed;
        # an extra request-span slice keeps the waterfall segments
        # tiling the member's measured latency (any GIL stall here
        # would otherwise read as an unattributed gap)
        obs.record_span("serving.request", self._pending.trace_id,
                        time.perf_counter() - t0, op="index_merge")
        return self._result


class MutableServingEngine:
    """The serving frontend of a :class:`MutableIndex`: duck-types the
    ``ServingEngine`` surface ``QueryQueue`` drives (``buckets``,
    ``_dim``, ``submit() -> handle``, ``stats()``) while pinning every
    request to one index snapshot — swaps are atomic from a request's
    view — and searching the delta tail alongside each bucketed main
    dispatch (padded to the SAME bucket rung, so tail programs ride the
    ladder too).  Writes enter as a first-class op via
    :meth:`apply_write` (``QueryQueue.submit_write`` routes here)."""

    def __init__(self, index: MutableIndex):
        self.index = index
        self.k = index.k
        self._dim = index.dim

    @property
    def buckets(self):
        return self.index._snapshot().engine.buckets

    @property
    def warmed_ops(self):
        eng = self.index._snapshot().engine
        return getattr(eng, "warmed_ops", set())

    def warmup(self, ops: Sequence[str] = ("search",)) -> dict:
        """AOT-compile the inner engine's buckets AND the delta-tail
        program for every bucket's placed shape at the first ladder
        rung — so neither the first live request nor the first
        post-insert request pays an inline compile."""
        snap = self.index._snapshot()
        counts = snap.engine.warmup(ops)
        warmed = 0
        for b in snap.engine.buckets:
            q = np.zeros((int(b), self._dim), np.float32)
            self.index._dispatch_tail(snap, q).fetch()
            warmed += 1
        counts["tail_buckets"] = warmed
        return counts

    def submit(self, queries, *, op: str = "search",
               trace_id=None, tenant=None) -> _MutablePending:
        if op != "search":
            raise ValueError(
                f"MutableServingEngine serves op='search' only, got "
                f"{op!r} (predict over a mutating corpus is not "
                f"supported yet)")
        t_ent = time.perf_counter()
        q = np.ascontiguousarray(np.asarray(queries, np.float32))
        if q.ndim != 2 or q.shape[1] != self._dim:
            raise ValueError(
                f"queries shape {q.shape} incompatible with database "
                f"dim {self._dim}")
        snap = self.index._snapshot()
        t_pre = time.perf_counter()
        pending = snap.engine.submit(q, op="search",
                                     trace_id=trace_id, tenant=tenant)
        # the wrapper prologue (coerce + snapshot pin) runs BEFORE the
        # inner engine's request clock starts; recorded as an extra
        # request-span slice so a stall here (e.g. GIL pressure from a
        # background compaction compile) stays attributed in the
        # request's waterfall instead of reading as an unattributed gap
        obs.record_span("serving.request", pending.trace_id,
                        t_pre - t_ent, op="index_snapshot")
        tail_h = None
        if snap.tail_len:
            from knn_tpu.serving.buckets import bucket_for

            b = bucket_for(snap.engine.buckets, q.shape[0])
            rows = int(b) if b is not None else q.shape[0]
            if rows > q.shape[0]:
                padded = np.zeros((rows, self._dim), np.float32)
                padded[: q.shape[0]] = q
            else:
                padded = q
            tail_h = self.index._dispatch_tail(snap, padded)
            tail_h.rows = q.shape[0]
        return _MutablePending(snap, pending, tail_h, self.k)

    def search(self, queries, *, return_sqrt: bool = False):
        d, ids = self.submit(queries).result()
        if return_sqrt:
            d = np.sqrt(d)
        return d, ids

    def apply_write(self, kind: str, *, vectors=None, ids=None) -> dict:
        """The write-path op the queue routes (insert / delete)."""
        if kind == "insert":
            return self.index.insert(vectors, ids)
        if kind == "delete":
            return self.index.delete(ids)
        raise ValueError(
            f"unknown write kind {kind!r}; expected insert|delete")

    def stats(self, **kw) -> dict:
        snap = self.index._snapshot()
        try:
            out = snap.engine.stats(**kw)
        except TypeError:
            out = snap.engine.stats()
        out["index"] = self.index.stats()
        return out
