"""Jax-free pieces of the mutable-index subsystem: the error vocabulary
and the ``mutation`` bench-artifact validator.

These live apart from :mod:`knn_tpu.index.mutable` (which imports JAX at
module load) so the artifact refresher, the perf sentinel, and the
multi-host refusal path can import them without paying — or breaking on
— a backend init.  Same split as ``loadgen.knee`` / ``obs.roofline``:
whatever validates curated artifacts must run on the box that curates
them, not only the one with the accelerator.
"""

from __future__ import annotations

from typing import List

#: version stamp of the ``mutation`` bench block (bench.py's opt-in
#: mutation mode); bump on any schema change so the refresher refuses
#: half-migrated lines instead of hoisting garbage — the version token
#: the artifact-schema catalog's ``mutation`` entry consumes
MUTATION_VERSION = 1


def _required_fields():
    from knn_tpu.analysis.artifacts import required_keys

    return required_keys("mutation")


#: fields every valid mutation block must carry (the refusal list the
#: refresher prints); ``admitted_p99_ms`` may be null (an honest "no
#: admitted reads completed" beats a fabricated number) — DERIVED from
#: the artifact-schema catalog (knn_tpu.analysis.artifacts), the one
#: declaration the validator and the lockstep checker both read
MUTATION_REQUIRED = _required_fields()


class MutationUnsupportedError(ValueError):
    """Raised by ``insert``/``delete`` on placements that cannot be
    mutated yet — host-RAM-tier (the database is not resident to search
    a delta against) and multi-host (no cross-process write replication
    protocol exists).  A LOUD refusal: the alternative is silently
    serving stale results from a replica that believes it applied the
    write (docs/INDEX.md)."""


class MutationBudgetError(RuntimeError):
    """Raised when a write exceeds the index's delta budget — the tail
    past its top ladder rung, or tombstones past the certify-widening
    reserve.  The fix is always :meth:`~knn_tpu.index.mutable.
    MutableIndex.compact` (or auto-compaction thresholds that fire
    before the budget fills; docs/INDEX.md)."""


def validate_mutation_block(block) -> List[str]:
    """Structural validation the artifact refresher runs before curating
    a line carrying a ``mutation`` block: returns the list of
    violations (empty = valid).  Blocks that recorded their own failure
    (an ``error`` key) are exempt — an honest error field beats a
    refused line (the loadgen_knee discipline).  A shim over the
    artifact-schema catalog (:mod:`knn_tpu.analysis.artifacts`, the
    ``mutation`` entry) with the legacy error strings byte-identical."""
    from knn_tpu.analysis.artifacts import validate

    return validate("mutation", block, style="legacy")
