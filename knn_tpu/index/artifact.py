"""Jax-free pieces of the mutable-index subsystem: the error vocabulary
and the ``mutation`` bench-artifact validator.

These live apart from :mod:`knn_tpu.index.mutable` (which imports JAX at
module load) so the artifact refresher, the perf sentinel, and the
multi-host refusal path can import them without paying — or breaking on
— a backend init.  Same split as ``loadgen.knee`` / ``obs.roofline``:
whatever validates curated artifacts must run on the box that curates
them, not only the one with the accelerator.
"""

from __future__ import annotations

from typing import List

#: version stamp of the ``mutation`` bench block (bench.py's opt-in
#: mutation mode); bump on any schema change so the refresher refuses
#: half-migrated lines instead of hoisting garbage
MUTATION_VERSION = 1

#: fields every valid mutation block must carry (the refusal list the
#: refresher prints); ``admitted_p99_ms`` may be null (an honest "no
#: admitted reads completed" beats a fabricated number)
MUTATION_REQUIRED = (
    "mutation_version", "write_mix", "rate_qps", "duration_s",
    "admitted_p99_ms", "compactions", "epoch", "reads", "writes",
    "slo_breach_transitions",
)


class MutationUnsupportedError(ValueError):
    """Raised by ``insert``/``delete`` on placements that cannot be
    mutated yet — host-RAM-tier (the database is not resident to search
    a delta against) and multi-host (no cross-process write replication
    protocol exists).  A LOUD refusal: the alternative is silently
    serving stale results from a replica that believes it applied the
    write (docs/INDEX.md)."""


class MutationBudgetError(RuntimeError):
    """Raised when a write exceeds the index's delta budget — the tail
    past its top ladder rung, or tombstones past the certify-widening
    reserve.  The fix is always :meth:`~knn_tpu.index.mutable.
    MutableIndex.compact` (or auto-compaction thresholds that fire
    before the budget fills; docs/INDEX.md)."""


def validate_mutation_block(block) -> List[str]:
    """Structural validation the artifact refresher runs before curating
    a line carrying a ``mutation`` block: returns the list of
    violations (empty = valid).  Blocks that recorded their own failure
    (an ``error`` key) are exempt — an honest error field beats a
    refused line (the loadgen_knee discipline)."""
    errs: List[str] = []
    if not isinstance(block, dict):
        return [f"mutation block must be a dict, got "
                f"{type(block).__name__}"]
    if "error" in block:
        return errs
    for fld in MUTATION_REQUIRED:
        if fld not in block:
            errs.append(f"missing {fld!r}")
    if errs:
        return errs
    if block["mutation_version"] != MUTATION_VERSION:
        errs.append(f"mutation_version must be {MUTATION_VERSION}, got "
                    f"{block['mutation_version']!r}")
    mix = block["write_mix"]
    if not isinstance(mix, dict):
        errs.append(f"write_mix must be a dict, got {mix!r}")
    else:
        for fld in ("insert_fraction", "delete_fraction"):
            v = mix.get(fld)
            if not isinstance(v, (int, float)) or not 0.0 <= v <= 1.0:
                errs.append(f"write_mix.{fld} must be a number in "
                            f"[0, 1], got {v!r}")
    for fld in ("rate_qps", "duration_s"):
        v = block[fld]
        if not isinstance(v, (int, float)) or v <= 0:
            errs.append(f"{fld} must be a positive number, got {v!r}")
    p99 = block["admitted_p99_ms"]
    if p99 is not None and (not isinstance(p99, (int, float))
                            or p99 < 0):
        errs.append(f"admitted_p99_ms must be a non-negative number or "
                    f"null, got {p99!r}")
    for fld in ("compactions", "epoch", "slo_breach_transitions"):
        v = block[fld]
        if not isinstance(v, int) or v < 0:
            errs.append(f"{fld} must be a non-negative int, got {v!r}")
    # the acceptance bar the block exists to pin: a mixed-traffic line
    # that never swapped proves nothing about swap behavior
    if isinstance(block.get("compactions"), int) \
            and block["compactions"] < 1 and "compactions_waived" \
            not in block:
        errs.append("compactions must be >= 1 (a mutation line that "
                    "never compacted measured nothing; set "
                    "compactions_waived to curate one anyway)")
    for fld in ("reads", "writes"):
        if not isinstance(block[fld], dict):
            errs.append(f"{fld} must be a dict, got {block[fld]!r}")
    return errs
