"""knn_tpu.index — the mutable-index subsystem: delta-shard inserts,
tombstone deletes, and snapshot-swap compaction over the immutable
placement machinery (docs/INDEX.md).

Two layers:

- :mod:`~knn_tpu.index.artifact` — jax-free: the error vocabulary
  (:class:`MutationUnsupportedError`, :class:`MutationBudgetError`) and
  the ``mutation`` bench-artifact validator the refresher/sentinel run;
- :mod:`~knn_tpu.index.mutable` — :class:`MutableIndex` (insert /
  delete / compact / search / search_certified over a ``ShardedKNN``
  placement + a bucket-laddered delta tail) and
  :class:`MutableServingEngine` (the QueryQueue-compatible serving
  frontend with writes as a first-class op).

``MutableIndex``/``MutableServingEngine`` import JAX, so they resolve
LAZILY here: the artifact refresher and the doctor CLI can import
``knn_tpu.index`` without paying (or breaking on) a backend init.
"""

from knn_tpu.index.artifact import (  # noqa: F401
    MUTATION_VERSION,
    MutationBudgetError,
    MutationUnsupportedError,
    validate_mutation_block,
)

__all__ = [
    "MUTATION_VERSION",
    "MutableIndex",
    "MutableServingEngine",
    "MutationBudgetError",
    "MutationUnsupportedError",
    "validate_mutation_block",
]


def __getattr__(name):
    if name in ("MutableIndex", "MutableServingEngine"):
        from knn_tpu.index import mutable

        return getattr(mutable, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
