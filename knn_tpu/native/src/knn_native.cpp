// Native CPU backend: the parity oracle for the JAX/TPU path.
//
// A fresh implementation of the reference program's semantics
// (brute-force KNN classification: distance fill -> top-k select ->
// majority vote -> accuracy; cf. knn_mpi.cpp:33-84,308-393) with a modern
// shape: a C API exported from a shared library, query-shard parallelism
// via std::thread (each thread plays the role an MPI rank plays in the
// reference, cf. MPI_Scatter knn_mpi.cpp:226-227), a heap-based top-k
// select instead of the reference's full std::sort (knn_mpi.cpp:323,366),
// and the framework's deterministic tie-break: the k-nearest set is the
// lexicographically smallest k (distance, index) pairs, matching
// knn_tpu.ops.topk exactly.
//
// Differences from the reference, by design:
//   - extrema init at +/-inf, not {-1, 999999} (fixes knn_mpi.cpp:241-242)
//   - no memory leaks (the reference never frees; knn_mpi.cpp:326,369)
//   - out-of-range labels are rejected, not an OOB write (knn_mpi.cpp:330)
//
// Built as libknn_native.so via the Makefile next to this file; bound from
// Python with ctypes (knn_tpu/native/__init__.py).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <thread>
#include <vector>

extern "C" {

// Metric codes shared with the Python binding.
enum KnnMetric : int32_t {
  KNN_METRIC_SQL2 = 0,   // squared L2 (ranking-equivalent to Euclidean)
  KNN_METRIC_L1 = 1,     // Manhattan
  KNN_METRIC_COSINE = 2, // 1 - cosine similarity
  KNN_METRIC_DOT = 3,    // negative inner product
};

}  // extern "C"

namespace {

struct Candidate {
  double dist;
  int64_t index;
  // Lexicographic (dist, index): the framework-wide tie-break contract.
  bool operator<(const Candidate& o) const {
    return dist < o.dist || (dist == o.dist && index < o.index);
  }
};

double squared_l2(const float* q, const float* t, int64_t dim) {
  double acc = 0.0;
  for (int64_t d = 0; d < dim; ++d) {
    const double diff = static_cast<double>(q[d]) - static_cast<double>(t[d]);
    acc += diff * diff;
  }
  return acc;
}

double manhattan(const float* q, const float* t, int64_t dim) {
  double acc = 0.0;
  for (int64_t d = 0; d < dim; ++d) {
    acc += std::fabs(static_cast<double>(q[d]) - static_cast<double>(t[d]));
  }
  return acc;
}

double dot(const float* q, const float* t, int64_t dim) {
  double acc = 0.0;
  for (int64_t d = 0; d < dim; ++d) {
    acc += static_cast<double>(q[d]) * static_cast<double>(t[d]);
  }
  return acc;
}

double norm(const float* x, int64_t dim) {
  return std::sqrt(dot(x, x, dim));
}

double distance(int32_t metric, const float* q, const float* t, int64_t dim) {
  switch (metric) {
    case KNN_METRIC_SQL2:
      return squared_l2(q, t, dim);
    case KNN_METRIC_L1:
      return manhattan(q, t, dim);
    case KNN_METRIC_COSINE: {
      const double nq = norm(q, dim), nt = norm(t, dim);
      const double denom = std::max(nq * nt, 1e-24);
      return 1.0 - dot(q, t, dim) / denom;
    }
    case KNN_METRIC_DOT:
      return -dot(q, t, dim);
    default:
      return std::numeric_limits<double>::quiet_NaN();
  }
}

int resolve_threads(int32_t num_threads) {
  if (num_threads > 0) return num_threads;
  const unsigned hw = std::thread::hardware_concurrency();
  return hw ? static_cast<int>(hw) : 1;
}

// Run fn(first_row, last_row) over [0, n) split into contiguous shards —
// the thread-level analogue of the reference's per-rank query shards.
template <typename Fn>
void parallel_rows(int64_t n, int threads, Fn fn) {
  threads = static_cast<int>(std::max<int64_t>(1, std::min<int64_t>(threads, n)));
  if (threads == 1) {
    fn(static_cast<int64_t>(0), n);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads);
  const int64_t per = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = std::min(n, lo + per);
    if (lo >= hi) break;
    pool.emplace_back([=] { fn(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

// Exact top-k of one query row: max-heap of size k ordered by the
// lexicographic Candidate comparator; replaces the reference's full
// O(N log N) std::sort per query with O(N log k).
void topk_row(const float* query, const float* train, int64_t n_train,
              int64_t dim, int64_t k, int32_t metric,
              std::vector<Candidate>& heap) {
  heap.clear();
  for (int64_t j = 0; j < n_train; ++j) {
    Candidate c{distance(metric, query, train + j * dim, dim), j};
    if (static_cast<int64_t>(heap.size()) < k) {
      heap.push_back(c);
      std::push_heap(heap.begin(), heap.end());
    } else if (c < heap.front()) {
      std::pop_heap(heap.begin(), heap.end());
      heap.back() = c;
      std::push_heap(heap.begin(), heap.end());
    }
  }
  std::sort_heap(heap.begin(), heap.end());  // ascending (dist, index)
}

// First-label-to-reach-the-final-max vote over neighbors in ascending
// (dist, index) order — the reference's running argmax with strict '>'
// (knn_mpi.cpp:324-336).
int32_t vote(const std::vector<Candidate>& neighbors, const int32_t* labels,
             int32_t num_classes, std::vector<int32_t>& counts) {
  counts.assign(num_classes, 0);
  int32_t best_label = -1;
  int32_t best_count = 0;
  for (const Candidate& c : neighbors) {
    const int32_t lab = labels[c.index];
    if (lab < 0 || lab >= num_classes) return -1;  // reject, don't corrupt
    if (++counts[lab] > best_count) {
      best_count = counts[lab];
      best_label = lab;
    }
  }
  return best_label;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// KNN search: out_dist/out_idx are [n_queries, k] row-major. Returns 0 on
// success, nonzero on bad arguments.
int32_t knn_native_search(const float* train, int64_t n_train, int64_t dim,
                          const float* queries, int64_t n_queries, int64_t k,
                          int32_t metric, int32_t num_threads,
                          double* out_dist, int64_t* out_idx) {
  if (!train || !queries || !out_dist || !out_idx) return 1;
  if (k < 1 || k > n_train || dim < 1 || n_queries < 0) return 2;
  const int threads = resolve_threads(num_threads);
  parallel_rows(n_queries, threads, [&](int64_t lo, int64_t hi) {
    std::vector<Candidate> heap;
    heap.reserve(k);
    for (int64_t i = lo; i < hi; ++i) {
      topk_row(queries + i * dim, train, n_train, dim, k, metric, heap);
      for (int64_t j = 0; j < k; ++j) {
        out_dist[i * k + j] = heap[j].dist;
        out_idx[i * k + j] = heap[j].index;
      }
    }
  });
  return 0;
}

// KNN classification: predicted labels in out_labels [n_queries]. Returns 0
// on success; 3 if any training label is outside [0, num_classes).
int32_t knn_native_predict(const float* train, const int32_t* labels,
                           int64_t n_train, int64_t dim, const float* queries,
                           int64_t n_queries, int64_t k, int32_t num_classes,
                           int32_t metric, int32_t num_threads,
                           int32_t* out_labels) {
  if (!train || !labels || !queries || !out_labels) return 1;
  if (k < 1 || k > n_train || dim < 1 || num_classes < 1) return 2;
  for (int64_t j = 0; j < n_train; ++j) {
    if (labels[j] < 0 || labels[j] >= num_classes) return 3;
  }
  std::atomic<int32_t> status{0};
  const int threads = resolve_threads(num_threads);
  parallel_rows(n_queries, threads, [&](int64_t lo, int64_t hi) {
    std::vector<Candidate> heap;
    heap.reserve(k);
    std::vector<int32_t> counts;
    for (int64_t i = lo; i < hi; ++i) {
      topk_row(queries + i * dim, train, n_train, dim, k, metric, heap);
      const int32_t lab = vote(heap, labels, num_classes, counts);
      if (lab < 0) status.store(3);
      out_labels[i] = lab;
    }
  });
  return status.load();
}

// Per-dimension running extrema over one array; call repeatedly to fold in
// train/test/val for the reference's transductive normalization
// (knn_mpi.cpp:245-274). Initialize io_min to +inf and io_max to -inf.
int32_t knn_native_minmax(const float* data, int64_t n, int64_t dim,
                          float* io_min, float* io_max) {
  if (!data || !io_min || !io_max || dim < 1) return 1;
  for (int64_t i = 0; i < n; ++i) {
    const float* row = data + i * dim;
    for (int64_t d = 0; d < dim; ++d) {
      io_min[d] = std::min(io_min[d], row[d]);
      io_max[d] = std::max(io_max[d], row[d]);
    }
  }
  return 0;
}

// In-place min-max rescale; constant dims (max == min) pass through
// untouched (the knn_mpi.cpp:284 guard).
int32_t knn_native_minmax_apply(float* data, int64_t n, int64_t dim,
                                const float* mins, const float* maxs) {
  if (!data || !mins || !maxs || dim < 1) return 1;
  for (int64_t d = 0; d < dim; ++d) {
    const float range = maxs[d] - mins[d];
    if (range == 0.0f) continue;
    for (int64_t i = 0; i < n; ++i) {
      data[i * dim + d] = (data[i * dim + d] - mins[d]) / range;
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Fast CSV parse: comma-separated floats, one row per line, uniform width.
// On success fills *out_rows/*out_cols and returns a malloc'd row-major
// float buffer the caller releases with knn_native_free. Returns nullptr on
// I/O error, ragged rows, or parse failure (*out_rows carries an error
// code: -1 io, -2 ragged, -3 parse, -4 empty).
float* knn_native_read_csv(const char* path, int64_t* out_rows,
                           int64_t* out_cols) {
  *out_rows = -1;
  *out_cols = 0;
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  // ftell returns -1 on non-seekable files; size_t(-1) would then be
  // passed to fread against a 0-byte buffer
  if (std::fseek(f, 0, SEEK_END) != 0) {
    std::fclose(f);
    return nullptr;
  }
  const long size = std::ftell(f);
  if (size < 0 || std::fseek(f, 0, SEEK_SET) != 0) {
    std::fclose(f);
    return nullptr;
  }
  std::vector<char> buf(static_cast<size_t>(size) + 1);
  const size_t got = std::fread(buf.data(), 1, size, f);
  std::fclose(f);
  if (static_cast<long>(got) != size) return nullptr;
  buf[got] = '\0';

  std::vector<float> values;
  values.reserve(1 << 16);
  int64_t cols = -1, rows = 0;
  const char* p = buf.data();
  const char* end = buf.data() + got;
  while (p < end) {
    // one line
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    // skip blank lines
    const char* q = p;
    while (q < line_end && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    if (q == line_end) {
      p = line_end + 1;
      continue;
    }
    int64_t row_cols = 0;
    while (p < line_end) {
      char* next = nullptr;
      const float v = std::strtof(p, &next);
      if (next == p) {
        *out_rows = -3;
        return nullptr;
      }
      values.push_back(v);
      ++row_cols;
      p = next;
      while (p < line_end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
      if (p < line_end) {
        if (*p != ',') {
          *out_rows = -3;
          return nullptr;
        }
        ++p;  // past comma
        if (p >= line_end) {  // trailing comma = empty field, like the
          *out_rows = -3;     // python fallback rejects
          return nullptr;
        }
      }
    }
    if (cols < 0) {
      cols = row_cols;
    } else if (row_cols != cols) {
      *out_rows = -2;
      return nullptr;
    }
    ++rows;
    p = line_end + 1;
  }
  if (rows == 0 || cols <= 0) {
    *out_rows = -4;
    return nullptr;
  }
  float* out = static_cast<float*>(std::malloc(values.size() * sizeof(float)));
  if (!out) return nullptr;
  std::memcpy(out, values.data(), values.size() * sizeof(float));
  *out_rows = rows;
  *out_cols = cols;
  return out;
}

void knn_native_free(void* ptr) { std::free(ptr); }

// Classification accuracy — acc_calc (knn_mpi.cpp:69-84).
double knn_native_accuracy(const int32_t* pred, const int32_t* real,
                           int64_t n) {
  if (!pred || !real || n <= 0) return 0.0;
  int64_t hits = 0;
  for (int64_t i = 0; i < n; ++i) hits += (pred[i] == real[i]);
  return static_cast<double>(hits) / static_cast<double>(n);
}

int32_t knn_native_version() { return 1; }

}  // extern "C"
