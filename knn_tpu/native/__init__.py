"""ctypes bindings for the native CPU backend (libknn_native.so).

This is the framework's C++ parity oracle — the role the reference's whole
program plays (SURVEY.md §2: the single native component).  The library
builds on demand via the Makefile next to this file; when no C++ toolchain
is available, :func:`available` returns False and every caller falls back
to the pure-Python/JAX paths.

API mirrors the JAX ops one-to-one so parity tests can swap backends:
  knn_search / knn_predict      <-> ops.topk.knn_search / models knn_predict
  minmax_stats / minmax_apply   <-> ops.normalize
  read_csv                      <-> data.csv_io (fast path)
  accuracy                      <-> acc_calc (knn_mpi.cpp:69-84)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libknn_native.so")

_METRIC_CODES = {
    "l2": 0, "sql2": 0, "euclidean": 0,
    "l1": 1, "manhattan": 1,
    "cosine": 2,
    "dot": 3,
}

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _try_build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR, "-s"],
            check=True,
            capture_output=True,
            timeout=300,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_LIB_PATH) and not _try_build():
            _build_failed = True
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        f64p = ctypes.POINTER(ctypes.c_double)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.knn_native_search.restype = ctypes.c_int32
        lib.knn_native_search.argtypes = [
            f32p, ctypes.c_int64, ctypes.c_int64, f32p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, f64p, i64p,
        ]
        lib.knn_native_predict.restype = ctypes.c_int32
        lib.knn_native_predict.argtypes = [
            f32p, i32p, ctypes.c_int64, ctypes.c_int64, f32p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, i32p,
        ]
        lib.knn_native_minmax.restype = ctypes.c_int32
        lib.knn_native_minmax.argtypes = [f32p, ctypes.c_int64, ctypes.c_int64, f32p, f32p]
        lib.knn_native_minmax_apply.restype = ctypes.c_int32
        lib.knn_native_minmax_apply.argtypes = [f32p, ctypes.c_int64, ctypes.c_int64, f32p, f32p]
        lib.knn_native_read_csv.restype = ctypes.POINTER(ctypes.c_float)
        lib.knn_native_read_csv.argtypes = [ctypes.c_char_p, i64p, i64p]
        lib.knn_native_free.restype = None
        lib.knn_native_free.argtypes = [ctypes.c_void_p]
        lib.knn_native_accuracy.restype = ctypes.c_double
        lib.knn_native_accuracy.argtypes = [i32p, i32p, ctypes.c_int64]
        lib.knn_native_version.restype = ctypes.c_int32
        lib.knn_native_version.argtypes = []
        _lib = lib
        return _lib


def available() -> bool:
    """True when the shared library is loaded (building it if needed)."""
    return _load() is not None


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _metric_code(metric: str) -> int:
    m = metric.lower()
    if m not in _METRIC_CODES:
        raise ValueError(f"unknown metric {metric!r}")
    return _METRIC_CODES[m]


def _as_f32c(x) -> np.ndarray:
    return np.ascontiguousarray(x, dtype=np.float32)


def knn_search(
    train, queries, k: int, metric: str = "l2", *, num_threads: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """(distances [Q,k] float64, indices [Q,k] int64), lexicographic
    (dist, index) order — same contract as ops.topk.knn_search."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    train = _as_f32c(train)
    queries = _as_f32c(queries)
    n_train, dim = train.shape
    n_q = queries.shape[0]
    if queries.shape[1] != dim:
        raise ValueError(f"dim mismatch: train {dim}, queries {queries.shape[1]}")
    out_d = np.empty((n_q, k), dtype=np.float64)
    out_i = np.empty((n_q, k), dtype=np.int64)
    rc = lib.knn_native_search(
        _f32p(train), n_train, dim, _f32p(queries), n_q, k,
        _metric_code(metric), num_threads,
        out_d.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out_i.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        raise ValueError(f"knn_native_search failed with code {rc}")
    return out_d, out_i


def knn_predict(
    train, labels, queries, *, k: int, num_classes: int, metric: str = "l2",
    num_threads: int = 0,
) -> np.ndarray:
    """Predicted labels [Q] int32 with the reference vote semantics."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    train = _as_f32c(train)
    queries = _as_f32c(queries)
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    n_train, dim = train.shape
    out = np.empty(queries.shape[0], dtype=np.int32)
    rc = lib.knn_native_predict(
        _f32p(train), labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n_train, dim, _f32p(queries), queries.shape[0], k, num_classes,
        _metric_code(metric), num_threads,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        raise ValueError(
            f"knn_native_predict failed with code {rc}"
            + (" (label outside [0, num_classes))" if rc == 3 else "")
        )
    return out


def minmax_stats(arrays: Sequence) -> Tuple[np.ndarray, np.ndarray]:
    """Joint per-dim (min, max) over several [N, D] arrays — the
    transductive extrema of knn_mpi.cpp:245-274 with ±inf init."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    arrays = [_as_f32c(a) for a in arrays]
    if not arrays:
        raise ValueError("minmax_stats needs at least one array")
    dim = arrays[0].shape[1]
    lo = np.full(dim, np.inf, dtype=np.float32)
    hi = np.full(dim, -np.inf, dtype=np.float32)
    for a in arrays:
        if a.shape[1] != dim:
            raise ValueError("dim mismatch across arrays")
        rc = lib.knn_native_minmax(_f32p(a), a.shape[0], dim, _f32p(lo), _f32p(hi))
        if rc != 0:
            raise ValueError(f"knn_native_minmax failed with code {rc}")
    return lo, hi


def minmax_apply(x, mins, maxs) -> np.ndarray:
    """(x - min) / (max - min) with constant dims passed through
    (knn_mpi.cpp:284 guard).  Returns a new array."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    out = _as_f32c(x).copy()
    mins = _as_f32c(mins)
    maxs = _as_f32c(maxs)
    rc = lib.knn_native_minmax_apply(
        _f32p(out), out.shape[0], out.shape[1], _f32p(mins), _f32p(maxs)
    )
    if rc != 0:
        raise ValueError(f"knn_native_minmax_apply failed with code {rc}")
    return out


_CSV_ERRORS = {-1: "I/O error", -2: "ragged rows", -3: "parse error", -4: "empty file"}


def read_csv(path: str) -> np.ndarray:
    """Fast CSV parse to [rows, cols] float32 (uniform-width rows)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    ptr = lib.knn_native_read_csv(path.encode(), ctypes.byref(rows), ctypes.byref(cols))
    if not ptr:
        reason = _CSV_ERRORS.get(rows.value, "unknown error")
        raise ValueError(f"{path}: {reason}")
    try:
        n = rows.value * cols.value
        arr = np.ctypeslib.as_array(ptr, shape=(n,)).reshape(rows.value, cols.value).copy()
    finally:
        lib.knn_native_free(ptr)
    return arr


def accuracy(pred, real) -> float:
    """acc_calc (knn_mpi.cpp:69-84)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    pred = np.ascontiguousarray(pred, dtype=np.int32)
    real = np.ascontiguousarray(real, dtype=np.int32)
    if pred.shape != real.shape:
        raise ValueError("shape mismatch")
    return float(
        lib.knn_native_accuracy(
            pred.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            real.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pred.size,
        )
    )
