"""Open-loop load driver: submit a generated schedule against a
serving target and record what happened to every request.

The defining property is **open loop**: arrival times come from the
workload schedule (knn_tpu.loadgen.workload), never from completions.
Requests are partitioned round-robin across dedicated **submitter
threads** that sleep until each request's arrival time and call
``target.submit(...)`` (non-blocking by the queue's contract), while
separate **waiter threads** block on the returned futures — so a
saturated target slows completions, never arrivals (pinned in
tests/test_loadgen.py: the offered count matches the schedule even
against a stalled target).

Every request lands one record in a BOUNDED result log —
``(tenant, arrival, deadline, dispatch, completion, outcome)`` plus
rows/latency — with explicit outcomes:

- ``ok`` — admitted and completed;
- ``rejected:<reason>`` — refused at submit by admission control
  (``queue_full`` / ``quota`` / ``deadline``);
- ``shed:<reason>`` — admitted, then dropped before device dispatch
  (deadline expired while queued);
- ``error`` — resolved with a non-admission exception.

:func:`report` aggregates the log into the per-tenant and overall
numbers the knee sweep and the brownout test judge: offered/admitted
counts, outcome breakdown, ADMITTED-request latency percentiles (shed
requests never pollute the latency story — that is the whole point of
shedding), achieved q/s, and shed fraction.

The target is anything with a ``QueryQueue``-shaped ``submit``
(``submit(queries, tenant=..., deadline_ms=..., priority=...)`` ->
``Future``): the real micro-batching queue, or the jax-free
:class:`~knn_tpu.loadgen.synthetic.SyntheticTarget` for device-free
tests of the harness itself.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from knn_tpu.loadgen.workload import Request
from knn_tpu.serving.admission import AdmissionError

#: result-log bound: a long sweep must not grow per-request state
#: forever (the report counts EVERY request; only detail records are
#: bounded — dropped ones are counted, never silently lost)
DEFAULT_LOG_CAP = 65536


class ResultLog:
    """Bounded per-request record store + unbounded outcome counters:
    aggregate truth is always complete, detail is recent."""

    def __init__(self, cap: int = DEFAULT_LOG_CAP):
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=int(cap))
        self._dropped = 0
        self._outcomes: Dict[str, int] = {}
        self._by_tenant: Dict[str, Dict[str, int]] = {}
        #: write-op outcome counts, kind -> outcome -> n (kept apart
        #: from the read outcomes above: a write's latency must never
        #: pollute the ADMITTED-read percentiles the SLO judges)
        self._writes: Dict[str, Dict[str, int]] = {}
        #: bulk-join lane: outcome counts + ok latencies for ``bulk``
        #: requests (offline join superblocks riding the schedule).
        #: Same isolation contract as writes — the batch lane gets its
        #: own section, the admitted-read percentiles stay query-only.
        self._bulk: Dict[str, int] = {}
        self._bulk_lat: deque = deque(maxlen=int(cap))
        #: (tenant, latency_s, trace_id) of ok-outcome requests, bounded
        #: with the records (percentiles are window truth, counts are
        #: lifetime); the trace id is what joins a knee artifact's tail
        #: requests back to their spans/waterfalls
        self._lat: deque = deque(maxlen=int(cap))

    def add(self, rec: dict) -> None:
        kind = rec.get("kind", "query")
        with self._lock:
            if len(self._records) == self._records.maxlen:
                self._dropped += 1
            self._records.append(rec)
            out = rec["outcome"]
            if kind == "bulk":
                self._bulk[out] = self._bulk.get(out, 0) + 1
                if out == "ok" and rec.get("latency_s") is not None:
                    self._bulk_lat.append(rec["latency_s"])
                return
            if kind != "query":
                slot = self._writes.setdefault(kind, {})
                slot[out] = slot.get(out, 0) + 1
                return
            self._outcomes[out] = self._outcomes.get(out, 0) + 1
            slot = self._by_tenant.setdefault(rec["tenant"], {})
            slot[out] = slot.get(out, 0) + 1
            if out == "ok" and rec.get("latency_s") is not None:
                self._lat.append((rec["tenant"], rec["latency_s"],
                                  rec.get("trace_id")))

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "outcomes": dict(self._outcomes),
                "by_tenant": {t: dict(v)
                              for t, v in self._by_tenant.items()},
                "writes": {k: dict(v)
                           for k, v in self._writes.items()},
                "bulk": dict(self._bulk),
                "bulk_latencies": list(self._bulk_lat),
                "records_kept": len(self._records),
                "records_dropped": self._dropped,
                "latencies": list(self._lat),
            }


def _percentiles_ms(vals: Sequence[float]) -> Optional[dict]:
    """Millisecond latency summary — the serving layer's
    latency_summary (jax-free), so the knee artifact's quantiles can
    never diverge from the engine's stats() method/rounding."""
    from knn_tpu.serving.engine import latency_summary

    return latency_summary(list(vals))


def _outcome_of(exc: Exception) -> str:
    if isinstance(exc, AdmissionError):
        return f"shed:{exc.reason}"
    return "error"


#: base for the driver's deterministic write-id series — far above any
#: realistic corpus id, so generated inserts can't collide with base ids
WRITE_ID_BASE = 1 << 40


def run_workload(target, requests: Sequence[Request], *, queries,
                 submitters: int = 2, waiters: int = 2,
                 log_cap: int = DEFAULT_LOG_CAP,
                 time_scale: float = 1.0,
                 include_records: bool = False,
                 write_id_base: int = WRITE_ID_BASE) -> dict:
    """Drive ``requests`` against ``target`` open-loop and return the
    :func:`report`.  ``queries`` is the row pool requests slice their
    payload from (content is irrelevant to load; shape fidelity is
    what matters).  ``time_scale`` stretches (>1) or compresses (<1)
    the schedule — compressing a recorded trace is how a replay
    becomes a stress test.

    Write requests (``Request.kind`` insert/delete — the TenantSpec
    write-stream mix) go through ``target.submit_write``: inserts
    allocate ids from a monotone series starting at ``write_id_base``
    (fresh target per run, or pass a disjoint base), deletes retire the
    oldest still-live inserted id (none live yet -> the explicit
    ``skipped:no_live_id`` outcome, never an error).  Their outcomes
    land in the log's ``writes`` section and NEVER in the admitted-read
    latency percentiles.

    Bulk requests (``Request.kind`` == ``bulk`` — the TenantSpec
    ``bulk_fraction`` lane, offline join superblocks mixed into the
    serving schedule) are READS: they ride ``target.submit`` and the
    same admission control as queries, but their outcomes and latencies
    land in the report's ``bulk`` section — the interactive read-side
    percentiles stay query-only either way."""
    if not requests:
        raise ValueError("empty request schedule")
    if submitters < 1 or waiters < 1:
        raise ValueError("submitters and waiters must be >= 1")
    pool = np.ascontiguousarray(np.asarray(queries, np.float32))
    if pool.ndim != 2:
        raise ValueError(f"queries pool must be 2-D, got {pool.shape}")
    max_rows = max(r.rows for r in requests)
    if pool.shape[0] < max_rows:
        raise ValueError(
            f"queries pool has {pool.shape[0]} rows; schedule needs "
            f"{max_rows}")
    has_writes = any(r.kind in ("insert", "delete") for r in requests)
    if has_writes and not hasattr(target, "submit_write"):
        raise ValueError(
            f"schedule carries write ops but target "
            f"{type(target).__name__} has no submit_write (drive a "
            f"MutableServingEngine-backed queue, or the synthetic "
            f"target)")
    log = ResultLog(log_cap)
    import itertools
    import queue as _q

    inflight: _q.Queue = _q.Queue()
    #: monotone insert-id series + the live-id pool deletes draw from
    #: (pushed by the waiter on confirmed inserts)
    id_seq = itertools.count(int(write_id_base))
    id_lock = threading.Lock()
    live_ids: deque = deque()
    t0 = time.monotonic()

    def _submit_write(r: Request, t_sub: float, base: dict) -> None:
        base["kind"] = r.kind
        if r.kind == "insert":
            with id_lock:
                ids = [next(id_seq) for _ in range(r.rows)]
            base["write_ids"] = ids
            kwargs = {"vectors": pool[: r.rows], "ids": ids}
        else:
            with id_lock:
                wid = live_ids.popleft() if live_ids else None
            if wid is None:
                log.add({**base, "outcome": "skipped:no_live_id",
                         "dispatch_s": None, "completion_s": None,
                         "latency_s": None})
                return
            base["write_ids"] = [wid]
            kwargs = {"ids": [wid]}
        try:
            fut = target.submit_write(r.kind, tenant=r.tenant,
                                      **kwargs)
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            log.add({**base, "outcome": "error",
                     "error": f"{type(e).__name__}: {e}",
                     "dispatch_s": None, "completion_s": None,
                     "latency_s": None})
            return
        base["trace_id"] = getattr(fut, "trace_id", None)
        fut.add_done_callback(
            lambda f: setattr(f, "done_t", time.monotonic()))
        inflight.put((base, fut, t_sub))

    def _submit(part: List[Request]) -> None:
        for r in part:
            due = t0 + r.t * time_scale
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t_sub = time.monotonic()
            base = {
                "tenant": r.tenant, "rows": r.rows,
                "arrival_s": round(t_sub - t0, 6),
                "scheduled_s": round(r.t * time_scale, 6),
                "deadline_ms": r.deadline_ms,
                "priority": r.priority,
            }
            if r.kind in ("insert", "delete"):
                _submit_write(r, t_sub, base)
                continue
            if r.kind == "bulk":
                # a bulk-join superblock is a READ — it rides the same
                # submit path and admission control as queries, only its
                # outcome is logged into the batch lane, never the
                # admitted-read percentiles
                base["kind"] = "bulk"
            try:
                fut = target.submit(
                    pool[: r.rows], tenant=r.tenant,
                    deadline_ms=r.deadline_ms, priority=r.priority)
            except AdmissionError as e:
                log.add({**base, "outcome": f"rejected:{e.reason}",
                         "dispatch_s": None, "completion_s": None,
                         "latency_s": None})
                continue
            except Exception as e:  # noqa: BLE001 — recorded, not fatal
                log.add({**base, "outcome": "error",
                         "error": f"{type(e).__name__}: {e}",
                         "dispatch_s": None, "completion_s": None,
                         "latency_s": None})
                continue
            # the queue stamps its trace id on the future at submit
            # (alongside the dispatch_t contract): recorded so a knee
            # artifact's shed/tail requests can be joined against
            # traces and waterfalls
            base["trace_id"] = getattr(fut, "trace_id", None)
            # completion is stamped by the RESOLVING thread, not by the
            # waiter: the waiters drain a FIFO, so a request completing
            # out of order (priority scheduling) would otherwise have
            # its head-of-line wait billed as latency
            fut.add_done_callback(
                lambda f: setattr(f, "done_t", time.monotonic()))
            inflight.put((base, fut, t_sub))

    def _wait() -> None:
        while True:
            item = inflight.get()
            if item is None:
                break
            base, fut, t_sub = item
            outcome = "ok"
            err = None
            try:
                fut.result()
            except Exception as e:  # noqa: BLE001 — outcome, not crash
                outcome = _outcome_of(e)
                if outcome == "error":
                    err = f"{type(e).__name__}: {e}"
            if outcome == "ok" and base.get("kind") == "insert":
                # confirmed inserts feed the delete-id pool: a delete
                # can only ever target a row the target acknowledged
                with id_lock:
                    live_ids.extend(base["write_ids"])
            t_done = getattr(fut, "done_t", None) or time.monotonic()
            disp = getattr(fut, "dispatch_t", None)
            log.add({
                **base, "outcome": outcome,
                **({"error": err} if err else {}),
                "dispatch_s": (None if disp is None
                               else round(disp - t0, 6)),
                "completion_s": round(t_done - t0, 6),
                "latency_s": (round(t_done - t_sub, 6)
                              if outcome == "ok" else None),
            })

    parts: List[List[Request]] = [[] for _ in range(submitters)]
    for i, r in enumerate(requests):
        parts[i % submitters].append(r)
    sub_threads = [threading.Thread(target=_submit, args=(p,),
                                    name=f"loadgen-submit-{i}", daemon=True)
                   for i, p in enumerate(parts) if p]
    wait_threads = [threading.Thread(target=_wait,
                                     name=f"loadgen-wait-{i}", daemon=True)
                    for i in range(waiters)]
    for t in wait_threads:
        t.start()
    for t in sub_threads:
        t.start()
    for t in sub_threads:
        t.join()
    for _ in wait_threads:
        inflight.put(None)
    for t in wait_threads:
        t.join()
    wall = time.monotonic() - t0
    rep = report(log, offered=len(requests), wall_s=wall)
    if include_records:
        rep["records"] = log.records()
    return rep


def report(log: ResultLog, *, offered: int, wall_s: float) -> dict:
    """Aggregate the log: overall + per-tenant outcome counts, ADMITTED
    latency percentiles, achieved q/s, shed fraction.  Schedules with a
    write stream also carry a ``writes`` section (per-kind outcome
    counts), and schedules with a bulk-join lane a ``bulk`` section
    (outcomes + the batch lane's own latency summary); every read-side
    number — offered, shed fraction, percentiles — covers QUERIES
    only, so neither mix can dilute the admitted-read latency story."""
    snap = log.snapshot()
    writes = snap.get("writes") or {}
    n_writes = sum(sum(v.values()) for v in writes.values())
    bulk = snap.get("bulk") or {}
    n_bulk = sum(bulk.values())
    offered -= n_writes + n_bulk  # read-side offered: queries only
    outcomes = snap["outcomes"]
    ok = outcomes.get("ok", 0)
    rejected = sum(v for k, v in outcomes.items()
                   if k.startswith("rejected:"))
    shed = sum(v for k, v in outcomes.items() if k.startswith("shed:"))
    errors = outcomes.get("error", 0)
    lat_all = [s for _, s, _ in snap["latencies"]]
    per_tenant = {}
    for tenant, outs in sorted(snap["by_tenant"].items()):
        t_ok = outs.get("ok", 0)
        t_total = sum(outs.values())
        t_lat = [s for t, s, _ in snap["latencies"] if t == tenant]
        per_tenant[tenant] = {
            "offered": t_total,
            "ok": t_ok,
            "outcomes": outs,
            "latency_ms": _percentiles_ms(t_lat),
            "shed_fraction": (round(1.0 - t_ok / t_total, 4)
                              if t_total else None),
        }
    return {
        "offered": offered,
        "ok": ok,
        "rejected": rejected,
        "shed": shed,
        "errors": errors,
        "outcomes": outcomes,
        "wall_s": round(wall_s, 4),
        "offered_qps": (round(offered / wall_s, 2) if wall_s > 0
                        else None),
        "achieved_qps": round(ok / wall_s, 2) if wall_s > 0 else None,
        #: fraction of offered requests that did NOT complete ok —
        #: rejections, sheds, and errors all count (they are all load
        #: the server declined)
        "shed_fraction": (round((offered - ok) / offered, 4)
                          if offered else None),
        "latency_ms": _percentiles_ms(lat_all),
        #: the worst ADMITTED requests by latency, with the trace ids
        #: the queue stamped at submit — the knee sweep's tail becomes
        #: cross-examinable against spans/waterfalls (cli waterfall)
        "slowest": [
            {"tenant": t, "latency_ms": round(s * 1e3, 3),
             "trace_id": tid}
            for t, s, tid in sorted(snap["latencies"],
                                    key=lambda x: -x[1])[:5]
        ],
        "per_tenant": per_tenant,
        # write-stream outcome counts (kind -> outcome -> n), present
        # only when the schedule carried writes — the replayable
        # mixed-scenario record beside the read-side numbers
        **({"writes": {
            **{k: dict(v) for k, v in writes.items()},
            "total": n_writes,
            "ok": sum(v.get("ok", 0) for v in writes.values()),
        }} if writes else {}),
        # bulk-join batch lane (kind == "bulk"): its own outcome
        # counts and latency summary, present only when the schedule
        # carried bulk superblocks — the join/serving interference
        # record, kept beside (never inside) the read-side percentiles
        **({"bulk": {
            "outcomes": dict(bulk),
            "total": n_bulk,
            "ok": bulk.get("ok", 0),
            "latency_ms": _percentiles_ms(snap.get("bulk_latencies")
                                          or []),
        }} if bulk else {}),
        "records_kept": snap["records_kept"],
        "records_dropped": snap["records_dropped"],
    }
