"""The latency-vs-throughput knee: a stepped-rate sweep that locates
the maximum sustained rate where admitted-request tail latency still
meets the SLO, emitted as a curated bench artifact.

TPU-KNN (arXiv:2206.14286) frames peak-FLOP serving as a
throughput-recall-latency tradeoff; the knee is where that tradeoff
lives for a serving deployment — below it, added load is free; above
it, every extra offered request is paid in tail latency (or, with
admission control on, in explicit sheds).  ROADMAP item 4 wants the
knee RECORDED so regressions in it are judged like any other curated
metric: :func:`knee_block` is the artifact shape
``refresh_bench_artifacts.py`` validates (:func:`validate_knee_block`
— malformed blocks are REFUSED at curation, the roofline-block
discipline), and ``knee_qps`` joins the sentinel's curated fields so a
knee that slides down reads as the regression it is.

The sweep is target-agnostic: a factory returning a fresh
``QueryQueue``-shaped target per step (fresh so one step's saturated
backlog can never pollute the next step's latency — the real engine's
queue is cheap to rebuild over a warmed engine; the synthetic target's
knee is known by construction, which is what makes the detector
testable without a device).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

from knn_tpu.loadgen import driver
from knn_tpu.loadgen.workload import WorkloadSpec, generate

#: artifact schema version (bump on shape changes so the refresher can
#: tell a malformed block from an old one) — the version token the
#: artifact-schema catalog's ``loadgen_knee`` entry consumes
BLOCK_VERSION = 1


def _step_fields():
    from knn_tpu.analysis.artifacts import element_required

    return element_required("loadgen_knee", "rate_steps")


#: fields every rate step must carry for the artifact to curate —
#: DERIVED from the artifact-schema catalog (knn_tpu.analysis.
#: artifacts), the one declaration the validator, refresher, and
#: artifact-lockstep checker all read
STEP_FIELDS = _step_fields()


def run_step(target, spec: WorkloadSpec, *, queries,
             submitters: int = 2, waiters: int = 2) -> dict:
    """One rate step: drive the spec open-loop, return the driver
    report plus the step's offered-rate label."""
    reqs = generate(spec)
    rep = driver.run_workload(target, reqs, queries=queries,
                              submitters=submitters, waiters=waiters)
    rep["rate_qps"] = spec.rate_qps
    return rep


def knee_sweep(target_factory: Callable[[], object],
               base: WorkloadSpec, rates: Sequence[float], *,
               queries, slo_p99_ms: float,
               submitters: int = 2, waiters: int = 2) -> dict:
    """Stepped-rate sweep -> knee artifact block.  ``target_factory``
    builds a FRESH target per step (closed afterwards when it has a
    ``close``); ``rates`` are the offered request rates (q/s) to step
    through, ascending; the knee is the highest ACHIEVED rate among
    steps whose admitted p99 meets ``slo_p99_ms``."""
    if not rates:
        raise ValueError("need at least one rate step")
    if slo_p99_ms <= 0:
        raise ValueError(f"slo_p99_ms must be > 0, got {slo_p99_ms}")
    steps: List[dict] = []
    for rate in rates:
        spec = base.at_rate(rate)
        if not generate(spec):
            # a low step's Poisson draw can produce zero arrivals
            # (P = e^{-rate*duration}); record the empty step instead
            # of letting it abort the sweep and lose the higher steps
            steps.append({
                "rate_qps": float(rate), "offered": 0, "ok": 0,
                "rejected": 0, "shed": 0, "errors": 0,
                "offered_qps": None, "achieved_qps": None,
                "shed_fraction": None, "admitted_p50_ms": None,
                "admitted_p95_ms": None, "admitted_p99_ms": None,
                "within_slo": False, "empty_schedule": True,
                "per_tenant": {}})
            continue
        target = target_factory()
        try:
            rep = run_step(target, spec, queries=queries,
                           submitters=submitters, waiters=waiters)
        finally:
            close = getattr(target, "close", None)
            if callable(close):
                close()
        lat = rep.get("latency_ms") or {}
        p99 = lat.get("p99")
        within = p99 is not None and p99 <= slo_p99_ms
        steps.append({
            "rate_qps": float(rate),
            "offered": rep["offered"],
            "ok": rep["ok"],
            "rejected": rep["rejected"],
            "shed": rep["shed"],
            "errors": rep["errors"],
            "offered_qps": rep["offered_qps"],
            "achieved_qps": rep["achieved_qps"],
            "shed_fraction": rep["shed_fraction"],
            "admitted_p50_ms": lat.get("p50"),
            "admitted_p95_ms": lat.get("p95"),
            "admitted_p99_ms": lat.get("p99"),
            "within_slo": bool(within),
            "per_tenant": rep.get("per_tenant"),
            # worst admitted requests' trace ids: the step's tail is
            # joinable against spans/waterfalls (cli waterfall)
            "slowest": rep.get("slowest"),
        })
    return knee_block(steps, slo_p99_ms=slo_p99_ms)


def knee_block(steps: Sequence[dict], *, slo_p99_ms: float) -> dict:
    """The curated artifact: the step table plus the detected knee —
    the highest achieved q/s among SLO-meeting steps (None when no
    step met the SLO: an honest 'knee below the lowest step' beats a
    fabricated number)."""
    best = None
    best_rate = None
    for s in steps:
        if s.get("within_slo") and s.get("achieved_qps") is not None:
            if best is None or s["achieved_qps"] > best:
                best = s["achieved_qps"]
                best_rate = s["rate_qps"]
    return {
        "version": BLOCK_VERSION,
        "slo_p99_ms": float(slo_p99_ms),
        "rate_steps": list(steps),
        "knee_qps": best,
        "knee_rate_qps": best_rate,
    }


def validate_knee_block(block) -> List[str]:
    """Structural validation the artifact refresher runs before
    curating a line carrying a ``loadgen_knee`` block: returns the
    list of violations (empty = valid).  Blocks that recorded their
    own failure (an ``error`` key) are exempt — an honest error field
    beats a refused line.  A shim over the artifact-schema catalog
    (:mod:`knn_tpu.analysis.artifacts`, the ``loadgen_knee`` entry)
    with the legacy error strings byte-identical."""
    from knn_tpu.analysis.artifacts import validate

    return validate("loadgen_knee", block, style="legacy")


def closed_loop_anchor(queue, pool, *, requests: int = 32,
                       rows: int = 4) -> float:
    """A quick CLOSED-LOOP capacity probe: burst ``requests`` small
    submissions through ``queue`` and measure completions/s.  Bursts
    coalesce maximally, so this OVER-estimates open-loop capacity —
    pair it with :func:`rates_around`, whose default ladder reaches a
    decade below.  Drive an admission-FREE queue: the probe measures
    capacity, not policy (a tight depth bound would reject the burst
    before the sweep even starts)."""
    rows = min(rows, pool.shape[0])
    t0 = time.monotonic()
    futs = [queue.submit(pool[:rows]) for _ in range(requests)]
    for f in futs:
        f.result()
    return requests / max(time.monotonic() - t0, 1e-9)


def rates_around(anchor_qps: float,
                 fractions: Sequence[float] = (0.05, 0.1, 0.2, 0.4,
                                               0.7, 1.0, 1.5),
                 ) -> List[float]:
    """Default step ladder around an anchor rate.  The anchor is
    usually a CLOSED-LOOP burst probe, which over-estimates open-loop
    capacity (a burst coalesces maximally; spread arrivals pay a
    dispatch each), so the ladder reaches more than a decade below the
    anchor and modestly above it — wide enough to bracket the knee
    wherever the coalescing ratio lands it."""
    if anchor_qps <= 0:
        raise ValueError(f"anchor_qps must be > 0, got {anchor_qps}")
    return [round(anchor_qps * f, 3) for f in fractions]
