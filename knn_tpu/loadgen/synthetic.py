"""A jax-free synthetic serving target with a known capacity — the
device-free test double for the loadgen harness itself.

The knee sweep's correctness (does it find the latency-vs-throughput
knee?) must be testable without a device, a mesh, or XLA: this target
is a single-server queue with a CONFIGURED capacity, so its knee is
known by construction — latency stays near ``base_latency_ms`` below
``capacity_qps`` and grows without bound above it (the queueing-theory
shape the real engine shows at saturation).  A knee detector that
cannot find THIS knee cannot be trusted on hardware.

``submit`` matches the :class:`~knn_tpu.serving.queue.QueryQueue`
surface the driver targets (``tenant``/``deadline_ms``/``priority``
kwargs, Future result, ``dispatch_t`` stamped at service start), and
the optional ``max_depth``/``shed_deadlines`` knobs mimic admission so
shed accounting can be exercised end-to-end without hardware.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

from knn_tpu.serving.admission import DeadlineError, QueueFullError


class SyntheticTarget:
    """Single-server FIFO queue: service time ``1/capacity_qps`` per
    request, one worker thread — so an unloaded request's latency is
    one service time and the knee sits at ``capacity_qps`` by
    construction.  Close it (or use as a context manager) to join the
    worker."""

    def __init__(self, capacity_qps: float, *,
                 max_depth: Optional[int] = None,
                 shed_deadlines: bool = False):
        if capacity_qps <= 0:
            raise ValueError(
                f"capacity_qps must be > 0, got {capacity_qps}")
        self.capacity_qps = float(capacity_qps)
        self.max_depth = max_depth
        self.shed_deadlines = bool(shed_deadlines)
        self._q: _queue.Queue = _queue.Queue()
        self._depth = 0  # tracked explicitly: Queue.qsize is advisory
        #: write-op counts by kind (submit_write — the driver's
        #: write-stream accounting exercises against this)
        self.writes: dict = {}
        self._lock = threading.Lock()
        self._worker = threading.Thread(
            target=self._serve, name="synthetic-target", daemon=True)
        self._worker.start()

    def submit(self, queries, *, tenant: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               priority: Optional[int] = None) -> Future:
        from knn_tpu.obs import new_trace_id

        now = time.monotonic()
        with self._lock:
            if self.max_depth is not None and self._depth >= self.max_depth:
                raise QueueFullError(
                    f"synthetic queue at max_depth {self.max_depth}",
                    tenant=tenant)
            self._depth += 1
        fut: Future = Future()
        # same surface the real queue stamps (the loadgen driver's
        # ResultLog records it): ids stay jax-free via knn_tpu.obs
        fut.trace_id = new_trace_id()
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        self._q.put((fut, tenant, deadline))
        return fut

    def submit_write(self, kind: str, *, vectors=None, ids=None,
                     tenant: Optional[str] = None) -> Future:
        """Write-path double (the QueryQueue.submit_write surface): a
        synthetic index applies writes instantly, so the future
        resolves at submit and the counts land in ``self.writes`` —
        enough to exercise the driver's write-stream accounting without
        a device."""
        from knn_tpu.obs import new_trace_id

        if kind not in ("insert", "delete"):
            raise ValueError(
                f"unknown write kind {kind!r}; expected insert|delete")
        fut: Future = Future()
        fut.trace_id = new_trace_id()
        with self._lock:
            self.writes[kind] = self.writes.get(kind, 0) + 1
        fut.dispatch_t = time.monotonic()
        fut.set_result({"op": kind,
                        "rows": 0 if ids is None else len(ids)})
        return fut

    def _serve(self) -> None:
        service_s = 1.0 / self.capacity_qps
        while True:
            item = self._q.get()
            if item is None:
                break
            fut, tenant, deadline = item
            now = time.monotonic()
            if (self.shed_deadlines and deadline is not None
                    and now > deadline):
                if not fut.cancelled():
                    fut.set_exception(DeadlineError(
                        "deadline expired in synthetic queue",
                        tenant=tenant, reason="expired"))
                with self._lock:
                    self._depth -= 1
                continue
            fut.dispatch_t = now
            time.sleep(service_s)
            if not fut.cancelled():
                fut.set_result(None)
            # retire AFTER service, matching the real queue's
            # outstanding (queued + in flight) depth semantics — a
            # dequeue-time decrement would admit one extra request at
            # every depth bound
            with self._lock:
                self._depth -= 1

    def close(self) -> None:
        self._q.put(None)
        self._worker.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
