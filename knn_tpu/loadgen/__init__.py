"""knn_tpu.loadgen — production-shaped load generation, replay, and
knee measurement for the serving stack.

The serving layer (knn_tpu.serving) is fast on closed-loop
microbatches; whether it survives TRAFFIC — open-loop arrivals that do
not wait for completions, bursts, mixed request shapes, multiple
tenants — was unobservable before this package.  Four pieces:

- :mod:`~knn_tpu.loadgen.workload` — deterministic seeded arrival
  processes (Poisson, bursty on/off, JSONL trace replay) over a
  multi-tenant mix spec: same spec, same schedule, every time;
- :mod:`~knn_tpu.loadgen.driver` — the open-loop driver: dedicated
  submitter threads (arrivals never gated by completions) driving a
  ``QueryQueue``-shaped target, every request recorded into a bounded
  result log with an explicit outcome (ok / rejected:* / shed:* /
  error);
- :mod:`~knn_tpu.loadgen.knee` — the stepped-rate sweep that locates
  the latency-vs-throughput knee and emits it as the curated bench
  artifact the perf sentinel baselines;
- :mod:`~knn_tpu.loadgen.synthetic` — a jax-free single-server target
  with a configured capacity, so the harness itself (and the knee
  detector) is testable without hardware.

The controls the measured knee motivates live in
:mod:`knn_tpu.serving.admission`: bounded queues, deadline-aware
shedding, per-tenant quotas, starvation-safe priorities — shed, don't
collapse.  Entry points: ``python -m knn_tpu.cli loadgen`` and
bench.py's ``knee`` mode (docs/serving.md).

Jax-free by construction (numpy only): generating and replaying load
must not require the accelerator the target owns.
"""

from knn_tpu.loadgen.driver import (  # noqa: F401
    DEFAULT_LOG_CAP,
    ResultLog,
    report,
    run_workload,
)
from knn_tpu.loadgen.knee import (  # noqa: F401
    closed_loop_anchor,
    knee_block,
    knee_sweep,
    rates_around,
    run_step,
    validate_knee_block,
)
from knn_tpu.loadgen.synthetic import SyntheticTarget  # noqa: F401
from knn_tpu.loadgen.workload import (  # noqa: F401
    ARRIVALS,
    Request,
    TenantSpec,
    WorkloadSpec,
    generate,
    load_trace,
    parse_tenants,
    save_trace,
)

__all__ = [
    "ARRIVALS",
    "DEFAULT_LOG_CAP",
    "Request",
    "ResultLog",
    "SyntheticTarget",
    "TenantSpec",
    "WorkloadSpec",
    "closed_loop_anchor",
    "generate",
    "knee_block",
    "knee_sweep",
    "load_trace",
    "parse_tenants",
    "rates_around",
    "report",
    "run_step",
    "run_workload",
    "save_trace",
    "validate_knee_block",
]
