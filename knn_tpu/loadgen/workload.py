"""Workload specification and deterministic arrival generation.

The serving stack's design goals (no idle workers, bounded queues,
graceful brownout) only show up under production-shaped traffic, and a
closed-loop microbench can never produce it: a closed loop waits for
each completion before offering the next request, so the offered rate
collapses to whatever the server sustains and the knee is unobservable
by construction.  This module generates **open-loop** request schedules
— arrival times fixed in advance by the arrival process, independent of
how the server is doing — as plain data, so the same trace can be
generated, saved, replayed, and rate-scaled deterministically.

A :class:`WorkloadSpec` describes the mix: an aggregate request rate,
an arrival process (``poisson`` — memoryless open-loop; ``onoff`` —
bursty square-wave with a ``burst``-multiplied on-phase; ``replay`` —
a recorded JSONL trace), and a multi-tenant mix of
:class:`TenantSpec` entries (weights, request shapes, deadlines,
priorities).  :func:`generate` turns it into a list of
:class:`Request` values under a fixed seed — two calls with the same
spec are identical element for element (pinned in
tests/test_loadgen.py), which is what makes a measured knee
reproducible and a brownout test deterministic.

Everything here is jax-free (numpy only): workload generation must be
runnable on the box that writes the trace, not only the one with the
accelerator.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: arrival processes generate() understands
ARRIVALS = ("poisson", "onoff", "replay")


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's slice of the mix: its share of the aggregate rate
    (``weight``), the request shapes it sends (``batch_sizes``, drawn
    uniformly per request), and the admission-relevant tags that ride
    each request (deadline, priority, precision)."""

    name: str
    weight: float = 1.0
    #: request row counts, drawn uniformly per request
    batch_sizes: Tuple[int, ...] = (1, 2, 4, 8)
    #: neighbor count the tenant asks for (None = server default)
    k: Optional[int] = None
    #: distance metric tag (None = server default)
    metric: Optional[str] = None
    #: coarse-pass precision tag ("f32" / "int8"; None = server default)
    precision: Optional[str] = None
    #: per-request deadline (ms from arrival; None = no deadline)
    deadline_ms: Optional[float] = None
    #: dispatch priority (lower first; admission aging keeps it
    #: starvation-safe)
    priority: int = 0
    #: deterministic write-stream mix: each scheduled request is an
    #: ``insert`` with probability ``insert_fraction`` and a ``delete``
    #: with probability ``delete_fraction`` (seeded draw — same spec,
    #: same kinds), a query otherwise.  Inserts carry ``write_rows``
    #: vectors; deletes target one previously inserted id (the driver
    #: allocates/retires ids).  Both zero = the pre-write schedule,
    #: draw for draw.
    insert_fraction: float = 0.0
    delete_fraction: float = 0.0
    write_rows: int = 1
    #: offline bulk-join lane: with probability ``bulk_fraction`` a
    #: scheduled request is a ``bulk`` read of ``bulk_rows`` rows — a
    #: join superblock riding the serving schedule, the mixed
    #: join/serving interference shape.  Bulk outcomes land in their
    #: own report section; the admitted-read percentiles never see
    #: them.  Zero = the pre-bulk schedule, draw for draw.
    bulk_fraction: float = 0.0
    bulk_rows: int = 1024

    def validate(self) -> None:
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, got "
                f"{self.weight}")
        if not self.batch_sizes or any(b < 1 for b in self.batch_sizes):
            raise ValueError(
                f"tenant {self.name!r}: batch_sizes must be >= 1, got "
                f"{self.batch_sizes}")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"tenant {self.name!r}: deadline_ms must be > 0, got "
                f"{self.deadline_ms}")
        if self.insert_fraction < 0 or self.delete_fraction < 0 \
                or self.bulk_fraction < 0 \
                or (self.insert_fraction + self.delete_fraction
                        + self.bulk_fraction) > 1:
            raise ValueError(
                f"tenant {self.name!r}: kind fractions must be >= 0 "
                f"and sum to <= 1, got insert={self.insert_fraction} "
                f"delete={self.delete_fraction} "
                f"bulk={self.bulk_fraction}")
        if self.write_rows < 1:
            raise ValueError(
                f"tenant {self.name!r}: write_rows must be >= 1, got "
                f"{self.write_rows}")
        if self.bulk_rows < 1:
            raise ValueError(
                f"tenant {self.name!r}: bulk_rows must be >= 1, got "
                f"{self.bulk_rows}")


@dataclass(frozen=True)
class Request:
    """One scheduled request: WHEN it arrives (``t``, seconds from
    trace start — fixed in advance, the open-loop property), WHO sends
    it, and its shape/deadline/priority tags."""

    tenant: str
    t: float
    rows: int
    k: Optional[int] = None
    metric: Optional[str] = None
    precision: Optional[str] = None
    deadline_ms: Optional[float] = None
    priority: int = 0
    #: "query" | "insert" | "delete" | "bulk" — writes and bulk-join
    #: superblocks ride the same seeded open-loop schedule as reads
    #: (TenantSpec kind fractions); old traces without the field load
    #: as pure-query schedules
    kind: str = "query"


@dataclass(frozen=True)
class WorkloadSpec:
    """The full mix: aggregate ``rate_qps`` (requests/s, not rows/s)
    over ``duration_s``, split across ``tenants`` by weight, arriving
    by ``arrival``.  ``onoff`` alternates ``on_s`` seconds at
    ``rate_qps * burst`` with ``off_s`` seconds of silence (the bursty
    pattern admission control exists for); ``replay`` reads the JSONL
    trace at ``trace_path`` verbatim (rate/duration/tenants ignored)."""

    rate_qps: float = 100.0
    duration_s: float = 1.0
    seed: int = 0
    arrival: str = "poisson"
    tenants: Tuple[TenantSpec, ...] = field(
        default_factory=lambda: (TenantSpec("default"),))
    on_s: float = 0.25
    off_s: float = 0.25
    burst: float = 4.0
    trace_path: Optional[str] = None

    def validate(self) -> None:
        if self.arrival not in ARRIVALS:
            raise ValueError(
                f"arrival must be one of {ARRIVALS}, got {self.arrival!r}")
        if self.arrival == "replay":
            if not self.trace_path:
                raise ValueError("arrival='replay' needs trace_path")
            return
        if self.rate_qps <= 0:
            raise ValueError(f"rate_qps must be > 0, got {self.rate_qps}")
        if self.duration_s <= 0:
            raise ValueError(
                f"duration_s must be > 0, got {self.duration_s}")
        if not self.tenants:
            raise ValueError("at least one tenant required")
        seen = set()
        for t in self.tenants:
            if t.name in seen:
                raise ValueError(f"duplicate tenant name {t.name!r}")
            seen.add(t.name)
            t.validate()
        if self.arrival == "onoff":
            if self.on_s <= 0 or self.off_s < 0:
                raise ValueError(
                    f"onoff needs on_s > 0 and off_s >= 0, got "
                    f"on_s={self.on_s} off_s={self.off_s}")
            if self.burst <= 0:
                raise ValueError(f"burst must be > 0, got {self.burst}")

    def at_rate(self, rate_qps: float) -> "WorkloadSpec":
        """The same mix at a different aggregate rate — the knee
        sweep's step generator (same seed: the step traces differ only
        by arrival spacing, never by mix)."""
        return WorkloadSpec(
            rate_qps=float(rate_qps), duration_s=self.duration_s,
            seed=self.seed, arrival=self.arrival, tenants=self.tenants,
            on_s=self.on_s, off_s=self.off_s, burst=self.burst,
            trace_path=self.trace_path)


def _arrival_times(spec: WorkloadSpec, rng: np.random.Generator
                   ) -> List[float]:
    """Arrival offsets (seconds, ascending) for the configured process.
    Poisson: exponential gaps at ``rate_qps``.  On/off: exponential
    gaps at ``rate_qps * burst`` inside on-windows, silence in
    off-windows (arrivals landing in an off-window are pushed to the
    next on-edge — the synchronized-burst shape that stresses
    admission hardest)."""
    out: List[float] = []
    if spec.arrival == "poisson":
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / spec.rate_qps))
            if t >= spec.duration_s:
                break
            out.append(t)
        return out
    # onoff
    period = spec.on_s + spec.off_s
    rate_on = spec.rate_qps * spec.burst
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / rate_on))
        # skip the off part of whichever period t landed in — LOOPED:
        # a re-drawn gap can itself overshoot the next on-window (at
        # low rates e^{-rate_on*on_s} is not small), and an arrival in
        # a silence window would break the square-wave invariant the
        # admission tests lean on
        k, phase = divmod(t, period)
        while phase > spec.on_s:
            t = (k + 1) * period + float(rng.exponential(1.0 / rate_on))
            k, phase = divmod(t, period)
        if t >= spec.duration_s:
            break
        out.append(t)
    return out


def generate(spec: WorkloadSpec) -> List[Request]:
    """The deterministic request schedule for ``spec``: same spec ->
    identical list, element for element.  ``replay`` loads the trace
    verbatim (already a schedule)."""
    spec.validate()
    if spec.arrival == "replay":
        return load_trace(spec.trace_path)
    rng = np.random.default_rng(spec.seed)
    times = _arrival_times(spec, rng)
    weights = np.asarray([t.weight for t in spec.tenants], np.float64)
    weights = weights / weights.sum()
    picks = rng.choice(len(spec.tenants), size=len(times), p=weights)
    out: List[Request] = []
    for t, pick in zip(times, picks):
        ten = spec.tenants[int(pick)]
        rows = int(ten.batch_sizes[int(
            rng.integers(0, len(ten.batch_sizes)))])
        kind = "query"
        if ten.insert_fraction > 0 or ten.delete_fraction > 0 \
                or ten.bulk_fraction > 0:
            # the kind draw happens ONLY for mixed tenants, so a
            # pure-query spec's rng sequence — and therefore its whole
            # schedule — is unchanged draw for draw (pinned)
            u = float(rng.random())
            if u < ten.insert_fraction:
                kind = "insert"
            elif u < ten.insert_fraction + ten.delete_fraction:
                kind = "delete"
            elif u < (ten.insert_fraction + ten.delete_fraction
                      + ten.bulk_fraction):
                kind = "bulk"
        if kind == "insert":
            rows = ten.write_rows
        elif kind == "delete":
            rows = 1
        elif kind == "bulk":
            rows = ten.bulk_rows
        out.append(Request(
            tenant=ten.name, t=round(float(t), 6), rows=rows, k=ten.k,
            metric=ten.metric, precision=ten.precision,
            deadline_ms=ten.deadline_ms, priority=ten.priority,
            kind=kind))
    return out


# -- trace persistence (JSONL: one request per line) ----------------------
def save_trace(requests: Sequence[Request], path: str) -> None:
    """One JSON object per line; :func:`load_trace` round-trips it
    exactly (pinned in tests/test_loadgen.py)."""
    with open(path, "w") as f:
        for r in requests:
            f.write(json.dumps(asdict(r), sort_keys=True) + "\n")


def load_trace(path: str) -> List[Request]:
    out: List[Request] = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{ln}: not JSON: {e}") from e
            try:
                out.append(Request(**rec))
            except TypeError as e:
                raise ValueError(
                    f"{path}:{ln}: not a request record: {e}") from e
    out.sort(key=lambda r: r.t)
    return out


def parse_tenants(text: str) -> Tuple[TenantSpec, ...]:
    """CLI shorthand ``name[:weight[:priority]],...`` -> tenant specs
    (e.g. ``gold:3:0,free:1:2``)."""
    out = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) > 3:
            raise ValueError(
                f"tenant spec {part!r}: expected name[:weight[:priority]]")
        out.append(TenantSpec(
            name=bits[0],
            weight=float(bits[1]) if len(bits) > 1 else 1.0,
            priority=int(bits[2]) if len(bits) > 2 else 0))
    if not out:
        raise ValueError(f"no tenants in {text!r}")
    return tuple(out)
