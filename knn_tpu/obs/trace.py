"""Request-scoped trace spans + the structured JSONL event log.

A **trace id** is minted where a request enters the system
(``ServingEngine.submit`` for direct callers, ``QueryQueue.submit`` for
queued ones) and rides the request through micro-batching, dispatch,
and result join — so ONE request's queue-wait / compile / device / join
times are attributable end-to-end even when the request was coalesced
into a batch with strangers (each batch member keeps its own id; the
batch dispatch event lists the member ids it carried).

A **span** is a timed scope: ``with span("serving.dispatch",
trace_id=tid, op="search"):`` records wall duration into the
``knn_tpu_span_seconds{span=...}`` histogram and emits one structured
event.  Events land in a bounded in-memory ring (always, when enabled)
and, when ``KNN_TPU_OBS_LOG`` names a path, as JSON lines on disk —
machine-scrapable, one object per line, append-only.

Disabled mode (``KNN_TPU_OBS=0``): :func:`span` yields a shared inert
span, :func:`new_trace_id` returns None, and :func:`emit_event` drops —
zero allocation on the hot path.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Optional

from knn_tpu.obs import ident, names, registry

#: env var naming the JSONL sink (unset = in-memory ring only)
LOG_ENV = "KNN_TPU_OBS_LOG"

#: env var capping the JSONL sink's size before rotation (bytes)
LOG_MAX_BYTES_ENV = "KNN_TPU_OBS_LOG_MAX_BYTES"

#: default rotation cap: a long-running serving process must not grow
#: the event log unboundedly; at ~200 bytes/event this holds ~300k
#: events live plus one rotated generation
DEFAULT_LOG_MAX_BYTES = 64 * 1024 * 1024

#: in-memory event ring size — enough to hold a serving trace's worth of
#: spans for tests/debugging without unbounded growth
RING_SIZE = 8192


def new_trace_id() -> Optional[str]:
    """A 16-hex-char request id, or None when the subsystem is off (so
    propagation sites can thread it unconditionally)."""
    if not registry.enabled():
        return None
    return uuid.uuid4().hex[:16]


class EventLog:
    """Bounded ring + optional size-capped JSONL file sink.  ``emit`` is
    thread-safe and never raises into the instrumented path: a failing
    sink counts ``knn_tpu_events_dropped_total`` instead.

    The file sink ROTATES: when appending the next line would push the
    file past ``max_bytes`` (``KNN_TPU_OBS_LOG_MAX_BYTES``), the current
    file is atomically renamed to ``<path>.1`` (replacing any previous
    generation) and a fresh file begins — so a long-running serving
    process holds at most two generations on disk, and because rotation
    happens on LINE boundaries (never mid-write), both sides of the cut
    are always valid JSONL."""

    def __init__(self, path: Optional[str] = None, ring: int = RING_SIZE,
                 max_bytes: Optional[int] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(ring))
        self._path = path
        self._fh = None
        self._size = 0  # bytes in the current generation (set on open)
        if max_bytes is None:
            try:
                max_bytes = int(os.environ.get(
                    LOG_MAX_BYTES_ENV, DEFAULT_LOG_MAX_BYTES))
            except ValueError:
                max_bytes = DEFAULT_LOG_MAX_BYTES
        self._max_bytes = max(1, int(max_bytes))

    @property
    def path(self) -> Optional[str]:
        return self._path

    def emit(self, event: dict) -> None:
        evt = {"ts": round(time.time(), 6), **event}
        # serialize OUTSIDE the lock: concurrent serving threads must
        # contend only for the append/write, not for json encoding.
        # FILE lines additionally carry the process identity stamp
        # (knn_tpu.obs.ident): rotated/merged multi-process logs must
        # stay attributable to a host, and the fleet trace stitcher
        # keys cross-host segments off it.  The in-memory ring stays
        # unstamped — it never leaves the process.
        line = (json.dumps({**evt, "identity": ident.identity()}) + "\n"
                if self._path is not None else None)
        with self._lock:
            self._ring.append(evt)
            if line is not None:
                try:
                    if self._fh is None:
                        self._fh = open(self._path, "a")
                        self._fh.seek(0, 2)
                        self._size = self._fh.tell()
                    # json.dumps default is ASCII-escaped, so character
                    # count == byte count for the size accounting
                    if (self._size > 0
                            and self._size + len(line) > self._max_bytes):
                        # rotate BETWEEN lines: close, atomic rename to
                        # the .1 generation, start fresh — a reader of
                        # either file only ever sees whole JSON lines
                        self._fh.close()
                        self._fh = None
                        os.replace(self._path, self._path + ".1")
                        self._fh = open(self._path, "a")
                        self._size = 0
                    self._fh.write(line)
                    self._fh.flush()
                    self._size += len(line)
                except OSError:
                    registry.counter(names.EVENTS_DROPPED).inc()

    def recent(self, n: Optional[int] = None) -> list:
        """Newest-last copy of the ring (``n`` trailing events)."""
        with self._lock:
            evts = list(self._ring)
        return evts if n is None else evts[-n:]

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


_state_lock = threading.Lock()
_log: Optional[EventLog] = None


def get_event_log() -> EventLog:
    global _log
    log = _log
    if log is None:
        with _state_lock:
            if _log is None:
                _log = EventLog(os.environ.get(LOG_ENV) or None)
            log = _log
    return log


def reset_event_log(path: Optional[str] = None,
                    from_env: bool = False,
                    max_bytes: Optional[int] = None) -> EventLog:
    """Swap in a fresh event log (tests; ``from_env`` re-reads
    ``KNN_TPU_OBS_LOG``; ``max_bytes`` overrides the rotation cap)."""
    global _log
    with _state_lock:
        if _log is not None:
            _log.close()
        _log = EventLog(
            os.environ.get(LOG_ENV) or None if from_env else path,
            max_bytes=max_bytes)
        return _log


def emit_event(name: str, **fields) -> None:
    """One structured event (non-span), dropped when disabled."""
    if not registry.enabled():
        return
    get_event_log().emit({"type": "event", "name": name, **fields})


class Span:
    """A live span: mutate ``attrs`` (via :meth:`set`) before the scope
    closes and the attributes ride the emitted event."""

    __slots__ = ("name", "trace_id", "attrs")

    def __init__(self, name: str, trace_id: Optional[str], attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.attrs = attrs

    def set(self, key: str, value) -> None:
        self.attrs[key] = value


class _NoopSpan:
    __slots__ = ()
    name = None
    trace_id = None

    def set(self, key: str, value) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def record_span(name: str, trace_id: Optional[str], dur_s: float,
                **attrs) -> None:
    """Record an already-measured span (the engine's latency join points
    measure durations themselves): histogram observe + one event."""
    if not registry.enabled():
        return
    registry.histogram(names.SPAN_SECONDS, span=name).observe(dur_s)
    evt = {"type": "span", "span": name, "dur_s": round(dur_s, 6), **attrs}
    if trace_id is not None:
        evt["trace_id"] = trace_id
    get_event_log().emit(evt)


@contextlib.contextmanager
def span(name: str, trace_id: Optional[str] = None, **attrs):
    """Timed scope -> ``knn_tpu_span_seconds{span=name}`` + one event.
    Yields the :class:`Span` (``.trace_id``, ``.set``); disabled mode
    yields the shared inert span and records nothing.

    ``trace_id`` is PROPAGATED, never minted here: ids are created where
    a request enters the system (``new_trace_id()`` at the submit
    sites), so a span without one (a warmup compile, a background task)
    emits without a trace_id field instead of fabricating a phantom
    single-span request."""
    if not registry.enabled():
        yield NOOP_SPAN
        return
    sp = Span(name, trace_id, dict(attrs))
    t0 = time.perf_counter()
    try:
        yield sp
    finally:
        record_span(name, sp.trace_id, time.perf_counter() - t0,
                    **sp.attrs)
