"""Shadow audit sampler: the end-to-end ground-truth check the
certificates cannot provide (docs/OBSERVABILITY.md "Quality
observability").

The serving path is approximate-first since the IVF tier landed, and
the certificate machinery is blind to whole classes of wrong answers
(epoch races, merge-order bugs, stale snapshots): a certified query is
only certified against the snapshot the *certificate* saw.  This module
closes the loop by replaying a deterministic sample of LIVE requests —
selected by trace-id hash, so the same request samples identically on
every replica — against the f64 exact oracle (``ops.refine`` over all
live rows) and scoring what was actually served:

- **recall@k** per tenant: the fraction of served neighbors whose exact
  distance is within the oracle's k-th distance (tie-tolerant);
- **rank displacement**: how far each served neighbor sits from its
  oracle rank (0 everywhere when the served set IS the exact set);
- **distance error**: the relative error of each served distance
  against its f64 recompute — the arithmetic-drift signal.

The replay NEVER runs on a serving thread: ``sampled()`` + the record
enqueue are the only hot-path costs (one hash + one bounded-queue put
on the sampled fraction only), and the oracle scan runs on one daemon
worker under a hard row budget (``KNN_TPU_AUDIT_BUDGET_ROWS_S`` rows
per second, token-bucket).  Over-budget and over-queue records are
DROPPED LOUDLY (``knn_tpu_audit_dropped_total{reason}``) — a silent
drop would read as a healthy audit.

Off by default: ``KNN_TPU_AUDIT_RATE`` unset or 0 arms nothing, and
``KNN_TPU_OBS=0`` pins the whole layer off (no worker thread, no
copies, bitwise-identical served results) regardless of the rate.

Deficient queries (recall < 1) feed the grouped ``audit_recall`` SLO
objective; its edge-triggered breach writes a postmortem bundle whose
``audit`` section embeds the failing records kept in the bounded
failure ring here (:func:`evidence`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import queue
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from knn_tpu.obs import names, registry

#: sampling probability env knob — fraction of live requests audited,
#: selected deterministically by trace-id hash; unset/0 = off
AUDIT_RATE_ENV = "KNN_TPU_AUDIT_RATE"
#: hard row budget env knob — oracle rows scored per second
#: (token-bucket; over-budget records are dropped and counted)
AUDIT_BUDGET_ENV = "KNN_TPU_AUDIT_BUDGET_ROWS_S"

#: the quality artifact block's schema version (docs/OBSERVABILITY.md)
QUALITY_VERSION = 1

#: default oracle row budget: generous for the shapes bench/test audit,
#: a real bound against a full-corpus scan storm in production
DEFAULT_BUDGET_ROWS_S = 5_000_000.0
#: pending replay records (each holds a query copy) — bounded so a
#: stalled worker can never grow host memory
QUEUE_CAP = 64
#: failing audit records retained for postmortem bundles
FAILURE_CAP = 16

#: relative + absolute tie tolerance when judging a served distance
#: against the oracle's k-th (f64 recompute vs f64 oracle)
_TIE_REL = 1e-9
_TIE_ABS = 1e-12


@dataclasses.dataclass
class AuditRecord:
    """One sampled request, pinned to the snapshot/epoch it was served
    from.  ``oracle(queries, served_ids)`` returns
    ``(oracle_d, oracle_ids, served_exact_d)`` — the exact top-k and
    the f64 recompute of what was served — and runs ONLY on the audit
    worker thread."""

    trace_id: str
    tenant: Optional[str]
    k: int
    queries: np.ndarray
    served_d: np.ndarray
    served_ids: np.ndarray
    epoch: Optional[int]
    cost_rows: int
    oracle: Callable[[np.ndarray, np.ndarray],
                     Tuple[np.ndarray, np.ndarray, np.ndarray]]


def _parse_rate(raw: Optional[str]) -> float:
    if not raw:
        return 0.0
    try:
        rate = float(raw)
    except ValueError:
        raise ValueError(
            f"{AUDIT_RATE_ENV}={raw!r} is not a float in [0, 1]")
    if not (0.0 <= rate <= 1.0):
        raise ValueError(
            f"{AUDIT_RATE_ENV}={raw!r} is not a float in [0, 1]")
    return rate


def _parse_budget(raw: Optional[str]) -> float:
    if not raw:
        return DEFAULT_BUDGET_ROWS_S
    try:
        budget = float(raw)
    except ValueError:
        raise ValueError(
            f"{AUDIT_BUDGET_ENV}={raw!r} is not a positive float")
    if budget <= 0:
        raise ValueError(
            f"{AUDIT_BUDGET_ENV}={raw!r} is not a positive float")
    return budget


class Auditor:
    """The audit sampler + off-path replay worker.

    One process-wide instance (:func:`get_auditor`); env knobs are
    resolved at construction so tests re-arm with
    :func:`reset_auditor`.  All mutable state is guarded by
    ``self._lock`` except the queue (its own lock) and the counters the
    worker feeds into the registry."""

    def __init__(self) -> None:
        self._rate = _parse_rate(os.environ.get(AUDIT_RATE_ENV))
        self._budget = _parse_budget(os.environ.get(AUDIT_BUDGET_ENV))
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._queue: "queue.Queue[Optional[AuditRecord]]" = \
            queue.Queue(maxsize=QUEUE_CAP)
        self._worker: Optional[threading.Thread] = None
        self._pending = 0
        # token bucket: budget rows/s, burst-capped at one second
        self._tokens = self._budget
        self._refill_at = time.monotonic()
        # plain tallies beside the registry twins: the stats/doctor
        # sections read these without a registry scrape
        self._sampled = 0
        self._replayed = 0
        self._deficient = 0
        self._rows_scored = 0
        self._dropped: Dict[str, int] = {}
        self._last_recall: Optional[float] = None
        self._failures: deque = deque(maxlen=FAILURE_CAP)

    # --- the hot-path side (serving threads) ---------------------------
    @property
    def rate(self) -> float:
        return self._rate

    def enabled(self) -> bool:
        return self._rate > 0.0 and registry.enabled()

    def sampled(self, trace_id: Optional[str]) -> bool:
        """Deterministic per-request sampling decision: the same
        trace id samples identically everywhere.  False whenever the
        layer is off — the KNN_TPU_OBS=0 pin."""
        if trace_id is None or not self.enabled():
            return False
        if self._rate >= 1.0:
            return True
        digest = hashlib.sha1(trace_id.encode()).hexdigest()[:13]
        return int(digest, 16) / float(16 ** 13) < self._rate

    def submit(self, rec: AuditRecord) -> bool:
        """Enqueue a sampled request for replay; cheap (no oracle
        work).  Returns False when the record was dropped (budget or
        backlog), counting the drop loudly either way."""
        if not self.enabled():
            return False
        tenant = rec.tenant or "-"
        registry.counter(names.AUDIT_SAMPLED, tenant=tenant).inc()
        with self._lock:
            self._sampled += 1
            now = time.monotonic()
            self._tokens = min(
                self._budget,
                self._tokens + (now - self._refill_at) * self._budget)
            self._refill_at = now
            if rec.cost_rows > self._tokens:
                self._drop_locked("budget")
                return False
            self._tokens -= rec.cost_rows
            self._ensure_worker_locked()
            self._pending += 1
        try:
            self._queue.put_nowait(rec)
        except queue.Full:
            with self._lock:
                self._pending -= 1
                self._drop_locked("queue_full")
                self._idle.notify_all()
            return False
        return True

    def _drop_locked(self, reason: str) -> None:
        self._dropped[reason] = self._dropped.get(reason, 0) + 1
        registry.counter(names.AUDIT_DROPPED, reason=reason).inc()

    def _ensure_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="knn-audit", daemon=True)
            self._worker.start()

    # --- the replay side (the one worker thread) -----------------------
    def _run(self) -> None:
        while True:
            rec = self._queue.get()
            if rec is None:
                return
            try:
                self._score(rec)
            except Exception as e:  # noqa: BLE001 - audit must not die
                with self._lock:
                    self._drop_locked("error")
                    self._failures.append({
                        "trace_id": rec.trace_id,
                        "tenant": rec.tenant or "-",
                        "error": f"{type(e).__name__}: {e}",
                    })
            finally:
                with self._lock:
                    self._pending -= 1
                    self._idle.notify_all()

    def _score(self, rec: AuditRecord) -> None:
        fault = _FAULT
        if fault is not None:
            rec = fault(rec)
        k = int(rec.k)
        oracle_d, oracle_ids, served_exact = rec.oracle(
            rec.queries, rec.served_ids)
        oracle_d = np.asarray(oracle_d, np.float64)[:, :k]
        served_exact = np.asarray(served_exact, np.float64)[:, :k]
        served_d = np.asarray(rec.served_d, np.float64)[:, :k]
        # tie-tolerant recall@k: a served neighbor counts when its f64
        # exact distance is within the oracle's k-th (ties included)
        thr = oracle_d[:, k - 1:k]
        good = served_exact <= thr + _TIE_REL * np.abs(thr) + _TIE_ABS
        recall = good.mean(axis=1)
        # rank displacement: the served neighbor's exact rank minus the
        # slot it was served in (0 everywhere for the exact answer)
        ranks = (served_exact[:, :, None]
                 > oracle_d[:, None, :]
                 + _TIE_REL * np.abs(oracle_d[:, None, :])
                 + _TIE_ABS).sum(axis=2)
        disp = np.clip(ranks - np.arange(k)[None, :], 0, None)
        # relative distance error: served (device-precision) distance
        # vs its own f64 recompute — arithmetic drift, not ranking
        denom = np.maximum(np.abs(served_exact), _TIE_ABS)
        finite = np.isfinite(served_d) & np.isfinite(served_exact)
        err = np.where(finite,
                       np.abs(served_d - served_exact) / denom, 1.0)
        deficient = int((recall < 1.0).sum())
        tenant = rec.tenant or "-"
        n_q = int(recall.shape[0])
        registry.counter(names.AUDIT_REPLAYED, tenant=tenant).inc(n_q)
        registry.counter(names.AUDIT_ROWS_SCORED).inc(rec.cost_rows)
        registry.histogram(names.AUDIT_RECALL, tenant=tenant
                           ).observe_many(recall.tolist())
        registry.histogram(names.AUDIT_RANK_DISPLACEMENT, tenant=tenant
                           ).observe_many(disp.ravel().tolist())
        registry.histogram(names.AUDIT_DISTANCE_ERROR, tenant=tenant
                           ).observe_many(err.ravel().tolist())
        if deficient:
            registry.counter(names.AUDIT_DEFICIENT, tenant=tenant
                             ).inc(deficient)
        with self._lock:
            self._replayed += n_q
            self._rows_scored += int(rec.cost_rows)
            self._deficient += deficient
            self._last_recall = float(recall.mean())
            if deficient:
                worst = int(np.argmin(recall))
                self._failures.append({
                    "trace_id": rec.trace_id,
                    "tenant": tenant,
                    "epoch": rec.epoch,
                    "k": k,
                    "deficient_queries": deficient,
                    "recall_at_k": [round(float(r), 6) for r in recall],
                    "worst_query": worst,
                    "worst_served_ids":
                        [int(i) for i in rec.served_ids[worst][:k]],
                    "worst_oracle_ids":
                        [int(i) for i in oracle_ids[worst][:k]],
                    "max_rank_displacement": int(disp.max()),
                })

    # --- introspection --------------------------------------------------
    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every enqueued record scored (tests, bench)."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    def worker_alive(self) -> bool:
        with self._lock:
            return self._worker is not None and self._worker.is_alive()

    def summary(self) -> dict:
        """The quality stats section (engine stats, /statusz, doctor,
        the bench quality block) — JSON-safe, registry-free reads."""
        with self._lock:
            return {
                "rate": self._rate,
                "budget_rows_s": self._budget,
                "sampled_requests": self._sampled,
                "replayed_queries": self._replayed,
                "deficient_queries": self._deficient,
                "dropped": dict(self._dropped),
                "rows_scored": self._rows_scored,
                "pending": self._pending,
                "worker_alive": (self._worker is not None
                                 and self._worker.is_alive()),
                "last_recall_at_k": self._last_recall,
            }

    def evidence(self) -> dict:
        """What the postmortem bundle embeds: the audit summary plus
        the bounded ring of failing records (newest last)."""
        with self._lock:
            failures = list(self._failures)
        return {"summary": self.summary(), "failures": failures}

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            worker = self._worker
            self._worker = None
        if worker is not None and worker.is_alive():
            self._queue.put(None)
            worker.join(timeout)


# --- the process-wide instance + module-level conveniences --------------
_auditor_lock = threading.Lock()
_auditor: Optional[Auditor] = None

#: test seam: a callable AuditRecord -> AuditRecord applied on the
#: WORKER thread before scoring — the seeded index-perturbation fault
#: of the acceptance test injects here, never on the serving path
_FAULT: Optional[Callable[[AuditRecord], AuditRecord]] = None


def get_auditor() -> Auditor:
    global _auditor
    with _auditor_lock:
        if _auditor is None:
            _auditor = Auditor()
        return _auditor


def reset_auditor() -> Auditor:
    """Tear down the worker and re-resolve the env knobs (tests)."""
    global _auditor
    with _auditor_lock:
        old, _auditor = _auditor, None
    if old is not None:
        old.close()
    return get_auditor()


def set_fault(fn: Callable[[AuditRecord], AuditRecord]) -> None:
    global _FAULT
    _FAULT = fn


def clear_fault() -> None:
    global _FAULT
    _FAULT = None


def audit_rate() -> float:
    return get_auditor().rate


def enabled() -> bool:
    return get_auditor().enabled()


def sampled(trace_id: Optional[str]) -> bool:
    return get_auditor().sampled(trace_id)


def submit(rec: AuditRecord) -> bool:
    return get_auditor().submit(rec)


def status() -> dict:
    """The /statusz + doctor quality section: never arms the layer —
    when no auditor exists and the rate is 0, says so without starting
    anything."""
    a = get_auditor()
    out = a.summary()
    out["enabled"] = a.enabled()
    return out
