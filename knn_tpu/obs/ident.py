"""Process identity — the stamp that makes multi-process telemetry
attributable.

Since PR 12 one replica spans jax.distributed processes, but snapshots
and JSONL event lines were anonymous: merge two hosts' rotated logs and
nothing says which line came from where.  This module is the ONE
jax-free home for the identity every telemetry payload carries —
``write_json_snapshot``/``/metrics.json`` (knn_tpu.obs.export) and
every ``KNN_TPU_OBS_LOG`` event (knn_tpu.obs.trace) stamp it, and the
fleet aggregator (knn_tpu.obs.fleet) keys members and detects
catalog-version skew off it.

Defaults are honest for a single process (pid + hostname, process 0 of
1, unknown device/coordinator); the jax-side multi-host path calls
:func:`set_identity` with the real process_index / host count / device
kind / coordinator address at init — this module itself never imports
jax.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Dict, Optional

from knn_tpu.obs import names

_lock = threading.Lock()
_overrides: Dict[str, object] = {}
_commit: Optional[str] = None
_commit_resolved = False


def _resolve_commit() -> Optional[str]:
    """The repo HEAD commit, read straight from ``.git`` (no
    subprocess, works from any checkout depth); None outside a git
    checkout or on any read problem."""
    d = os.path.dirname(os.path.abspath(__file__))
    for _ in range(8):
        head = os.path.join(d, ".git", "HEAD")
        if os.path.isfile(head):
            try:
                with open(head) as f:
                    ref = f.read().strip()
                if ref.startswith("ref:"):
                    ref_path = os.path.join(
                        d, ".git", *ref.split(None, 1)[1].split("/"))
                    with open(ref_path) as f:
                        return f.read().strip()[:12]
                return ref[:12]
            except OSError:
                return None
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


def set_identity(**fields) -> None:
    """Override identity fields (the multi-host init path stamps the
    real process_index / process_count / device_kind /
    coordinator_address here).  Unknown field names are refused — a
    typo'd stamp must not silently vanish from every payload."""
    allowed = {"host", "process_index", "process_count", "device_kind",
               "coordinator_address", "commit"}
    bad = set(fields) - allowed
    if bad:
        raise ValueError(
            f"unknown identity field(s) {sorted(bad)}; "
            f"allowed: {sorted(allowed)}")
    with _lock:
        _overrides.update(fields)


def reset_identity() -> None:
    """Drop every override (tests)."""
    with _lock:
        _overrides.clear()


def identity() -> dict:
    """The current process identity stamp: host, pid, process_index,
    process_count, device_kind, coordinator_address, commit, and the
    metric catalog-version token (the fleet skew check's key)."""
    global _commit, _commit_resolved
    if not _commit_resolved:
        c = _resolve_commit()
        with _lock:
            _commit, _commit_resolved = c, True
    with _lock:
        ov = dict(_overrides)
    out = {
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "process_index": 0,
        "process_count": 1,
        "device_kind": None,
        "coordinator_address": None,
        "commit": _commit,
        "catalog_version": names.catalog_version(),
    }
    out.update(ov)
    return out
