"""Analytic per-config roofline model — the attribution layer behind
every MFU number the repo reports.

``mfu`` alone says "6% of peak" without saying what the hardware
ceiling for *this* config actually is, so nothing in the system could
name the resource binding a given (shape, precision, kernel, geometry)
point — the gap ROADMAP item 1 exists to close.  This module computes,
term by term and jax-free:

- **HBM bytes moved** per sweep: the db operand stream (bf16 hi+lo =
  4 B/elem, the fused ``bf16x3f`` contraction 6 B/elem, int8 1 B/elem,
  f32 4 B/elem — mirroring exactly what ``ops.pallas_knn`` streams),
  the norms/aux block (8 f32 sublane rows; int8 stacks scales under
  norms, 16 rows), the re-fetched query blocks, and the candidate
  output round-trip.  Grid order matters: ``query_major`` (and the
  streaming kernel, inherently query-major) re-streams the full db
  once per query block; ``db_major`` at single-chunk dims streams it
  ONCE per sweep (ops.pallas_knn.GRID_ORDERS).
- **MXU FLOPs**: the distance matmul's *executed* passes (bf16x3 /
  bf16x3f = 3 MXU passes, f32-"highest" = 6, int8 = 1 counted at the
  MXU's int8 rate) beside the *useful* 2·nq·n·d the headline MFU
  divides by.
- **VPU select cost**: ops per score element for the grouped / lane
  in-kernel selects and the XLA ``lax.top_k`` / ApproxTopK paths —
  calibration constants from the measured cost model in docs/PERF.md.

Each term divides by the device's peak (``PEAKS_BY_KIND`` — the single
source of truth ``bench.py``'s ``_PEAK_BY_KIND`` is now a view over)
to a time; the largest term names the bound class, and the combined
time reflects whether the select can hide in the stream's shadow::

    non-fused / XLA:  ceiling_qps = nq / (max(t_hbm, t_mxu) + t_vpu)
    kernel="fused":   ceiling_qps = nq / max(t_hbm, t_mxu, t_vpu)
    bound_class in {"hbm_bound", "mxu_bound", "vpu_select_bound"}
    roofline_pct = measured_qps / ceiling_qps

The distance matmul overlaps the db stream in every kernel (that IS
the double buffer), but the select runs AFTER each tile's scores
exist — serialized — except in the fused kernel, whose in-loop
carry/early-out select rides the HBM stream (``select_overlapped`` on
the block says which formula applied; MODEL_VERSION 2).  The ceiling
assumes peak-rate execution of every term, so ``roofline_pct <= 1`` up
to peak-table error — a pct near 1 means the config is done and the
*model's* bound must move (different precision, grid order, geometry);
a low pct names implementation slack.  Everything here is pure arithmetic on plain
numbers: the bench, the artifact refresher, the sentinel lint, and the
``cli roofline`` subcommand all run it without importing JAX.

MODEL_VERSION 3 closes the analytic/measured gap: every block consults
the calibration overlay (:mod:`knn_tpu.obs.calibrate`, fed by the
device-trace / host-phase reconciler over :mod:`knn_tpu.obs.traceread`)
— an applied calibration re-times the terms by their measured scale
factors and splits ``ceiling_qps`` (measured) from
``ceiling_qps_analytic``; absent one, the block says
``calibration: {applied: false}`` explicitly.

Derivation, peak-table provenance, how to read ``bound_class``, and
the calibration/campaign runbook: docs/PERF.md "Roofline model" and
"Calibration & measured ceilings".
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Optional, Tuple

from knn_tpu.analysis import widths as _widths
from knn_tpu.obs import names, registry, trace

#: bump when the model's terms/peaks/output schema change: the tuning
#: cache embeds this in its key (tuning.cache.roofline_token), so
#: persisted winners carrying attributions from an older model
#: self-invalidate instead of republishing a stale verdict.
#: 2 = the select-overlap refinement: non-fused kernels SERIALIZE the
#: select after the stream (``max(t_hbm, t_mxu) + t_vpu``); the fused
#: kernel rides the select in the HBM stream's shadow
#: (``max(t_hbm, t_mxu, t_vpu)``) — so the fused int8/streaming arm's
#: modeled ceiling rises above the non-fused one, which is exactly the
#: gap the in-kernel fused select exists to close.
#: 3 = the CALIBRATED model: every block consults the measured-term
#: calibration overlay (knn_tpu.obs.calibrate, ``KNN_TPU_CALIBRATION``)
#: and gains an explicit ``calibration`` verdict — when a reconciled
#: device measurement covers the block's shape key, the per-term scale
#: factors re-time the terms and ``ceiling_qps`` becomes the MEASURED
#: ceiling beside the untouched ``ceiling_qps_analytic``; when none
#: does, ``calibration: {applied: false}`` says so explicitly (a line
#: can never silently claim calibrated).  The ``estimated`` flag keeps
#: its PR-6 semantics either way: it names the PEAK TABLE's provenance,
#: not the overlay's.
#: 4 = the multi-host DCN merge term: blocks modeled with ``db_hosts >
#: 1`` gain a ``terms.dcn`` entry pricing the cross-host top-k merge
#: volume (parallel.crossover.merge_bytes at the chosen ring/allgather
#: strategy) against the per-host DCN bandwidth — serialized AFTER the
#: per-host compute (a global merge cannot complete before its inputs),
#: so ``ceiling_qps = nq / (combined_compute_time + t_dcn)`` and
#: ``bound_class`` may read ``dcn_bound``.  Single-host blocks are
#: numerically unchanged; the bump re-keys the tuning cache and
#: calibration store so pre-DCN attributions self-invalidate.
#: 5 = the IVF probed-bytes term: ``nprobe``/``ncentroids`` on a block
#: scale every row-proportional term by ``probe_fraction = nprobe /
#: ncentroids`` — a probed search streams and scores only the gathered
#: lists (``expected_probe_fraction × db stream``), which is the whole
#: point of the tier — plus a centroid-scan add-on (the [C, d] table
#: bytes + ``2·nq·C·d`` assign flops) pricing the probe itself, under
#: ``terms.probe``.  Blocks without the knobs are numerically
#: unchanged; probed blocks skip the calibration overlay (no measured
#: entry covers a pruned stream yet — an explicit absent verdict beats
#: mis-scaling) and the bump re-keys the tuning cache and calibration
#: store so pre-IVF attributions self-invalidate.
#: 6 = the sub-int8 byte widths (PR 17): the per-precision width
#: tables move to :mod:`knn_tpu.analysis.widths` (ONE shared home with
#: analysis.vmem / analysis.hbm) and the model prices the new arms —
#: "int4" streams nibble-packed rows at 0.5 B/elem (db_row_bytes
#: rounds the DIM_CHUNK-padded dim to whole bytes) and scores at the
#: int8 MXU rate; "pq" streams ``ceil(d / dsub)`` code bytes per row,
#: re-fetches the per-query [nq, m·ncodes] f32 LUT per db tile in
#: place of the query blocks, and its executed MXU flops are the
#: one-hot expansion dot the kernel actually runs
#: (``2·nq·n·m·ncodes``) plus the LUT build — honestly mxu-heavy,
#: which is why PQ's win is the byte term and its natural home is the
#: IVF composition (probed blocks gather PQ codes).  The bump re-keys
#: the tuning cache and calibration store so v5 attributions
#: self-invalidate.
#: 7 = the bulk kNN-join model (:func:`join_cost_model`): a joined
#: superblock of S query rows streams the db ONCE per dispatch, so the
#: modeled db HBM bytes PER QUERY fall as 1/S (the amortization the
#: join engine exists for) until another term binds; the block gains a
#: ``terms.h2d`` entry pricing the host->device stream the byte model
#: plans (analysis.hbm.plan_join's winning nesting order) against the
#: host-link bandwidth (H2D_GBPS_* — the PCIe attach, not HBM), and
#: because the engine double-buffers, h2d OVERLAPS device compute:
#: steady-state time is ``max(t_device, t_h2d)`` and ``bound_class``
#: can read the new ``h2d_bound``.  Serving blocks are numerically
#: unchanged; the bump re-keys the tuning cache (rl7) and calibration
#: store (cal7) so v6 attributions self-invalidate.
MODEL_VERSION = 7

#: the resources a config can exhaust, in tie-break order (dcn_bound
#: only appears on multi-host blocks, db_hosts > 1; h2d_bound only on
#: join blocks, where the query stream's host link can bind)
BOUND_CLASSES = ("hbm_bound", "mxu_bound", "vpu_select_bound",
                 "dcn_bound", "h2d_bound")

#: per-device-kind peaks (public spec sheets; bf16 column = the table
#: bench.py carried since round 1, now living here).  ``hbm_gbps`` is
#: the chip's HBM bandwidth in GB/s; ``int8_flops`` the int8 MXU rate
#: (2x bf16 on every announced generation; v7's fp8 4614 TF/s stands in
#: for int8 there); ``vpu_ops`` is the vector-unit element-op rate —
#: ESTIMATED: v5e is anchored at the ~3.9 Tops/s the measured cost
#: model in docs/PERF.md calibrated, other kinds scale by their MXU
#: ratio.  An unknown kind gets no silent default — callers fall back
#: to GENERIC_CPU_PEAKS with ``estimated`` set.
PEAKS_BY_KIND: Dict[str, Dict[str, float]] = {
    "TPU v2":      {"bf16_flops": 46e12,   "int8_flops": 92e12,
                    "hbm_gbps": 700.0,  "vpu_ops": 0.9e12},
    "TPU v3":      {"bf16_flops": 123e12,  "int8_flops": 246e12,
                    "hbm_gbps": 900.0,  "vpu_ops": 2.4e12},
    "TPU v4":      {"bf16_flops": 275e12,  "int8_flops": 550e12,
                    "hbm_gbps": 1228.0, "vpu_ops": 5.4e12},
    "TPU v4i":     {"bf16_flops": 138e12,  "int8_flops": 276e12,
                    "hbm_gbps": 614.0,  "vpu_ops": 2.7e12},
    "TPU v5 lite": {"bf16_flops": 197e12,  "int8_flops": 394e12,
                    "hbm_gbps": 819.0,  "vpu_ops": 3.9e12},
    "TPU v5e":     {"bf16_flops": 197e12,  "int8_flops": 394e12,
                    "hbm_gbps": 819.0,  "vpu_ops": 3.9e12},
    "TPU v5":      {"bf16_flops": 459e12,  "int8_flops": 918e12,
                    "hbm_gbps": 2765.0, "vpu_ops": 9.1e12},
    "TPU v5p":     {"bf16_flops": 459e12,  "int8_flops": 918e12,
                    "hbm_gbps": 2765.0, "vpu_ops": 9.1e12},
    "TPU v6 lite": {"bf16_flops": 918e12,  "int8_flops": 1836e12,
                    "hbm_gbps": 1640.0, "vpu_ops": 18.2e12},
    "TPU v6e":     {"bf16_flops": 918e12,  "int8_flops": 1836e12,
                    "hbm_gbps": 1640.0, "vpu_ops": 18.2e12},
    "TPU v6":      {"bf16_flops": 918e12,  "int8_flops": 1836e12,
                    "hbm_gbps": 1640.0, "vpu_ops": 18.2e12},
    "TPU v6p":     {"bf16_flops": 1847e12, "int8_flops": 3694e12,
                    "hbm_gbps": 7370.0, "vpu_ops": 36.6e12},
    # Ironwood: 4614 TFLOP/s fp8 per chip; bf16 assumed half
    "TPU v7":      {"bf16_flops": 2307e12, "int8_flops": 4614e12,
                    "hbm_gbps": 7370.0, "vpu_ops": 45.7e12},
    "TPU v7x":     {"bf16_flops": 2307e12, "int8_flops": 4614e12,
                    "hbm_gbps": 7370.0, "vpu_ops": 45.7e12},
}

#: the generic fallback for CPU backends / unknown device kinds: one
#: modern core's SIMD matmul (~100 GFLOP/s), dual-channel DRAM
#: (~25 GB/s), and a vector-select rate in the same ballpark as the
#: matmul.  Deliberately round numbers — any block computed from them
#: carries ``estimated: true`` and exists so CPU microbench lines stop
#: being attribution-blind, not to be defended to a digit.
GENERIC_CPU_PEAKS: Dict[str, float] = {
    "bf16_flops": 100e9, "int8_flops": 200e9,
    "hbm_gbps": 25.0, "vpu_ops": 50e9, "dcn_gbps": 5.0,
}

#: per-host DCN bandwidth (GB/s) by device kind for the cross-host
#: merge term — ESTIMATED from public inter-slice networking figures
#: (~100-200 Gbps NICs per host on v4+ pods, less on v2/v3); like
#: ``vpu_ops`` these exist to rank configurations and name the bound,
#: not to be defended to a digit.  Kinds absent here fall back to
#: DCN_GBPS_DEFAULT.
DCN_GBPS_BY_KIND: Dict[str, float] = {
    "TPU v2": 12.5, "TPU v3": 12.5,
}
DCN_GBPS_DEFAULT = 25.0


def dcn_gbps_for(device_kind, peaks) -> float:
    """The per-host DCN bandwidth a block's dcn term divides by:
    an explicit ``dcn_gbps`` in a caller-supplied peaks dict wins,
    else the kind table, else the v4+ default."""
    if peaks and "dcn_gbps" in peaks:
        return float(peaks["dcn_gbps"])
    return DCN_GBPS_BY_KIND.get(device_kind or "", DCN_GBPS_DEFAULT)


#: host->device link bandwidth (GB/s) for the join model's h2d query-
#: stream term — the PCIe attach between the host's RAM (where a
#: super-HBM query set lives) and the chip, NOT HBM.  ESTIMATED from
#: public attach generations (gen3 x16 ~16 GB/s on v2/v3 era hosts,
#: gen4+ on later kinds); like ``vpu_ops``/``dcn_gbps`` these rank
#: configurations and name the bound, not defend a digit.  Kinds
#: absent here fall back to H2D_GBPS_DEFAULT.
H2D_GBPS_BY_KIND: Dict[str, float] = {
    "TPU v2": 8.0, "TPU v3": 8.0,
}
H2D_GBPS_DEFAULT = 16.0


def h2d_gbps_for(device_kind, peaks) -> float:
    """The host->device bandwidth a join block's h2d term divides by:
    an explicit ``h2d_gbps`` in a caller-supplied peaks dict wins, else
    the kind table, else the gen4-attach default."""
    if peaks and "h2d_gbps" in peaks:
        return float(peaks["h2d_gbps"])
    return H2D_GBPS_BY_KIND.get(device_kind or "", H2D_GBPS_DEFAULT)

#: db operand stream width per element, by kernel matmul precision —
#: EXACTLY what ops.pallas_knn._bin_candidates builds, living since
#: MODEL_VERSION 6 in the ONE shared width table
#: (:mod:`knn_tpu.analysis.widths`) so the cost model, the VMEM launch
#: budget, and the HBM placement budget can never drift.  These names
#: are VIEWS of that table (``is``-identity, pinned by
#: tests/test_analysis.py); tests/test_roofline.py additionally pins
#: them against the actual operand arrays' nbytes.
DB_ELEM_BYTES = _widths.DB_ELEM_BYTES

#: f32 sublane rows of the per-tile aux block (norms; int8/int4 stack
#: scales under norms) — ops.pallas_knn's aux_rows
AUX_ROWS = _widths.AUX_ROWS
AUX_ROWS_DEFAULT = _widths.AUX_ROWS_DEFAULT

#: query operand width per element (int8/int4 queries quantize in the
#: XLA prologue and stream as int8 + a [block_q, 128] f32 scale block;
#: pq's query-side operand is the per-query LUT — pq_lut_bytes)
QUERY_ELEM_BYTES = _widths.QUERY_ELEM_BYTES
QUERY_ELEM_BYTES_DEFAULT = _widths.QUERY_ELEM_BYTES_DEFAULT

#: executed MXU passes over the 2·nq·n·d useful flops, by precision:
#: bf16x3/bf16x3f reconstruct the f32 product in three bf16 passes,
#: "highest" is the native six-pass f32 path, int8/int4 and "default"
#: are one pass (int8/int4 at the int8 MXU rate — int4 unpacks to int8
#: operands in the kernel prologue).  "pq" is nominally one pass but
#: its executed flops are shape-dependent (the one-hot dot's
#: ``m·ncodes`` contraction width) — pallas_cost_model prices that
#: directly.
MXU_PASSES: Dict[str, int] = {
    "bf16x3": 3, "bf16x3f": 3, "highest": 6, "default": 1, "int8": 1,
    "int4": 1, "pq": 1,
}

#: VPU element-ops per score element for the in-kernel selects — the
#: measured cost model's calibration (docs/PERF.md: "grouped select
#: ~12 VPU ops x 4.1e9 score elements"); lane pays ~7 shuffle rounds
#: per reduction, ~5x more
SELECT_OPS: Dict[str, float] = {"grouped": 12.0, "lane": 60.0}

#: VPU element-ops per score element for the XLA selectors: a full
#: ``lax.top_k`` over a db-wide row measured ~30x the distance matmul
#: (the "selection-bound" finding the Pallas kernel exists to fix);
#: the hardware ApproxTopK coarse pass plus the count-below compare is
#: far cheaper.  Rough calibration constants — they set a CEILING, and
#: both XLA paths sit well under it.
XLA_SELECT_OPS: Dict[str, float] = {"exact": 32.0, "approx": 12.0}

#: kernel geometry defaults mirrored from ops.pallas_knn (TILE_N /
#: BLOCK_Q / grouped survivors=2) so this module stays jax-free; a
#: test pins them against the kernel module's constants
TILE_N_DEFAULT = 16384
BLOCK_Q_DEFAULT = 128
BIN_W = 128
SURVIVORS_GROUPED_DEFAULT = 2
DIM_CHUNK = _widths.DIM_CHUNK
#: mirror of ops.pallas_knn.MAX_CARRY_DEPTH (pinned by the same test):
#: past ceil((k+margin+2)/128) carry stats per lane the fused kernel
#: DISARMS its early-out and runs the plain serialized streaming path,
#: so the model must stop granting those configs the overlapped ceiling
MAX_CARRY_DEPTH = 8

#: matmul dtype widths for the XLA (non-pallas) selectors
_DTYPE_BYTES = {"bfloat16": 2, "float32": 4, "float64": 8}
_DTYPE_PASSES = {"bfloat16": 1, "float32": 6, "float64": 6}

_METRIC_RE = re.compile(r"^knn_qps_.+_n(?P<n>\d+)_d(?P<d>\d+)_k(?P<k>\d+)$")

_lock = threading.Lock()
#: config label -> last published compact attribution (/statusz renders
#: these); bounded so a label-churning process can't grow it forever
_LAST: Dict[str, dict] = {}
_LAST_MAX = 16
#: every label ever published in this process — the publish-once dedup
#: surface (:func:`was_published`).  Deliberately NOT the bounded
#: ``_LAST`` store: eviction there must not re-open a label for
#: re-publication on a warm-cache hot path.  Labels are config shapes,
#: bounded in practice.
_PUBLISHED: set = set()


def bf16_peak_by_kind() -> Dict[str, float]:
    """``{device_kind: bf16 MXU peak FLOP/s}`` — the view bench.py's
    ``_PEAK_BY_KIND`` historically carried, now derived from the one
    table."""
    return {kind: rec["bf16_flops"] for kind, rec in PEAKS_BY_KIND.items()}


def peaks_for(device_kind: Optional[str] = None,
              backend: Optional[str] = None) -> Tuple[Dict[str, float], bool]:
    """(peaks, estimated): the device's peak record, or the generic CPU
    fallback with ``estimated=True`` when the kind is unknown or the
    backend is cpu — a flagged estimate beats an attribution-blind
    line."""
    if backend != "cpu" and device_kind in PEAKS_BY_KIND:
        return dict(PEAKS_BY_KIND[device_kind]), False
    return dict(GENERIC_CPU_PEAKS), True


def _ceil_div(a: int, b: int) -> int:
    return -(-int(a) // int(b))


def db_operand_nbytes(n: int, d: int, precision: str, *,
                      dsub: Optional[int] = None) -> Dict[str, int]:
    """Bytes of the db-side operands ONE full-db stream moves — the
    values array(s) plus the lane-major aux block — matching the arrays
    ``ops.pallas_knn._bin_candidates`` actually builds (the property
    test compares against their ``nbytes``).  The shape-dependent arms
    route through ``widths.db_row_bytes``: int4 streams the nibble-
    packed (DIM_CHUNK-padded) rows at 0.5 B/elem, "pq" streams
    ``ceil(d / dsub)`` code bytes per row."""
    return {
        "db_values": int(n) * _widths.db_row_bytes(d, precision,
                                                   dsub=dsub),
        "db_aux": int(n) * _widths.aux_rows_for(precision) * 4,
    }


def _combined(times: Dict[str, float], select_overlapped: bool) -> float:
    # the DCN merge serializes AFTER the per-host compute: a global
    # merge cannot complete before its inputs exist
    t_dcn = times.get("dcn_bound", 0.0)
    compute = {k: v for k, v in times.items() if k != "dcn_bound"}
    if select_overlapped:
        return max(compute.values()) + t_dcn
    return max(compute["hbm_bound"], compute["mxu_bound"]) + \
        compute["vpu_select_bound"] + t_dcn


def _terms_to_verdict(model: dict, nq: int,
                      select_overlapped: bool = False) -> None:
    """Fill ceiling_qps + bound_class from the per-term times.  The
    bound class is the largest term (ties break in BOUND_CLASSES
    order); the ceiling's combined time depends on whether the select
    overlaps the stream: non-fused kernels and the XLA selectors run
    the select AFTER the streamed scores exist —
    ``max(t_hbm, t_mxu) + t_vpu`` — while the fused kernel's in-loop
    select rides the HBM stream's shadow, ``max`` of all three.

    MODEL_VERSION 3: the verdict then consults the calibration overlay
    (:mod:`knn_tpu.obs.calibrate`) — an applied calibration re-times
    every term by its measured scale factor, making ``ceiling_qps``
    the MEASURED ceiling (``ceiling_qps_analytic`` keeps the
    spec-sheet one), and ``bound_class`` names the binding term of the
    CALIBRATED machine.  With no overlay the analytic numbers stand,
    under an explicit ``calibration: {applied: false}``."""
    terms = model["terms"]
    times = {
        "hbm_bound": terms["hbm"]["time_s"],
        "mxu_bound": terms["mxu"]["time_s"],
        "vpu_select_bound": terms["vpu_select"]["time_s"],
    }
    if "dcn" in terms:
        times["dcn_bound"] = terms["dcn"]["time_s"]
    bound = max(times, key=lambda c: (times[c], -BOUND_CLASSES.index(c)))
    t = _combined(times, select_overlapped)
    model["bound_class"] = bound
    model["select_overlapped"] = bool(select_overlapped)
    model["ceiling_qps"] = round(nq / t, 1) if t > 0 else None
    model["ceiling_qps_analytic"] = model["ceiling_qps"]
    model["term_times_s"] = {k: round(v, 6) for k, v in times.items()}
    _consult_calibration(model, nq, times, select_overlapped)


def _consult_calibration(model: dict, nq: int,
                         times: Dict[str, float],
                         select_overlapped: bool) -> None:
    """Overlay the persisted measured-term factors onto this block, if
    the calibration store covers its shape key.  Failure-proof: a
    broken store degrades to the analytic verdict with the reason on
    the block — the model must render even when the overlay cannot."""
    from knn_tpu.obs import calibrate

    if "dcn_bound" in times:
        # multi-host blocks: no calibration entry covers the DCN term
        # yet (the campaign measures single-host arms); an explicit
        # absent verdict beats silently mis-scaling three of four terms
        model["calibration"] = {
            "applied": False,
            "note": "multi-host blocks use the analytic DCN model"}
        return
    if "probe" in model.get("terms", {}):
        # probed (IVF) blocks: every measured entry covers a full-db
        # stream; applying its factors to a pruned stream would claim a
        # measured ceiling for an unmeasured shape
        model["calibration"] = {
            "applied": False,
            "note": "probed blocks use the analytic IVF model"}
        return
    try:
        entry = calibrate.lookup_for_block(model)
    except Exception as e:  # noqa: BLE001 — overlay must not kill the model
        model["calibration"] = {
            "applied": False,
            "error": f"{type(e).__name__}: {e}"}
        return
    if entry is None:
        model["calibration"] = {"applied": False}
        return
    # a factor is a fit AGAINST one combined-time formula; the kernel
    # axis in the store key should make this unreachable, but a
    # hand-edited store must degrade to analytic, never mis-apply
    if "select_overlapped" in entry and \
            bool(entry["select_overlapped"]) != bool(select_overlapped):
        model["calibration"] = {
            "applied": False,
            "error": "entry fit under the other select-overlap formula"}
        return
    factors = entry.get("factors") or {}
    cal_times = {
        "hbm_bound": times["hbm_bound"] * float(factors.get("hbm", 1.0)),
        "mxu_bound": times["mxu_bound"] * float(factors.get("mxu", 1.0)),
        "vpu_select_bound": times["vpu_select_bound"]
        * float(factors.get("vpu_select", 1.0)),
    }
    t = _combined(cal_times, select_overlapped)
    if t <= 0:
        model["calibration"] = {"applied": False,
                                "error": "non-positive calibrated time"}
        return
    model["ceiling_qps"] = round(nq / t, 1)
    model["bound_class"] = max(
        cal_times,
        key=lambda c: (cal_times[c], -BOUND_CLASSES.index(c)))
    model["term_times_calibrated_s"] = {
        k: round(v, 6) for k, v in cal_times.items()}
    model["calibration"] = {
        "applied": True,
        "factors": dict(factors),
        "method": entry.get("method"),
        "source": entry.get("source"),
        "age_s": calibrate.entry_age_s(entry),
        "samples": entry.get("samples"),
        "model_residual_pct": entry.get("model_residual_pct"),
        "term_residual_pct": entry.get("term_residual_pct"),
        "measured_at": entry.get("measured_at"),
        "provenance": entry.get("provenance"),
    }


def _probe_setup(n: int, d: int, nq: int, nprobe: Optional[int],
                 ncentroids: Optional[int]):
    """The MODEL_VERSION-5 IVF pruning substitution: ``(n_eff, probe)``
    where ``n_eff`` is the expected row count a probed search actually
    streams (``ceil(n * nprobe / ncentroids)`` — balanced lists, the
    training objective) and ``probe`` prices the centroid scan the
    pruning costs: the [C, d] f32 table plus the per-query [C] f32
    distances, and ``2·nq·C·d`` assign flops.  Both knobs None → the
    identity ``(n, None)``; exactly one set is a config error."""
    if nprobe is None and ncentroids is None:
        return int(n), None
    if nprobe is None or ncentroids is None:
        raise ValueError("nprobe and ncentroids must be set together")
    cc = max(1, int(ncentroids))
    pp = min(max(1, int(nprobe)), cc)
    n_eff = _ceil_div(int(n) * pp, cc)
    return n_eff, {
        "nprobe": pp,
        "ncentroids": cc,
        "probe_fraction": pp / cc,
        "rows_probed": int(n_eff),
        "centroid_table_bytes": int(cc * d * 4 + nq * cc * 4),
        "assign_flops": 2.0 * nq * cc * d,
    }


def _dcn_term(nq: int, k: int, db_hosts: int, dcn_merge: Optional[str],
              device_kind, peaks) -> Optional[dict]:
    """The MODEL_VERSION-4 cross-host merge term, or None on a
    single-host config: the hierarchical merge's DCN candidate volume
    (parallel.crossover.merge_bytes at the resolved strategy) over the
    per-host DCN bandwidth."""
    hosts = max(1, int(db_hosts))
    if hosts <= 1:
        return None
    from knn_tpu.parallel import crossover

    strategy = dcn_merge or crossover.choose_merge(k, hosts)
    nbytes = crossover.merge_bytes(nq, k, hosts, strategy)
    rate = dcn_gbps_for(device_kind, peaks)
    return {
        "bytes": int(nbytes),
        "strategy": strategy,
        "hosts": hosts,
        "rate_gbps": rate,
        "time_s": nbytes / (rate * 1e9),
    }


def pallas_cost_model(
    *, n: int, d: int, k: int, nq: int,
    precision: Optional[str] = None, kernel: Optional[str] = None,
    grid_order: Optional[str] = None, binning: Optional[str] = None,
    tile_n: Optional[int] = None, block_q: Optional[int] = None,
    survivors: Optional[int] = None, margin: int = 28,
    device_kind: Optional[str] = None, backend: Optional[str] = None,
    num_devices: int = 1, peaks: Optional[Dict[str, float]] = None,
    db_hosts: int = 1, dcn_merge: Optional[str] = None,
    nprobe: Optional[int] = None, ncentroids: Optional[int] = None,
    pq_dsub: Optional[int] = None, pq_ncodes: Optional[int] = None,
) -> dict:
    """The roofline model of one Pallas-selector config (see module
    docstring for the terms).  ``None`` knobs take the library defaults
    the kernel itself would (tile 16384, block_q 128, grouped
    survivors 2).  Sharding is modeled as perfect scaling: each of
    ``num_devices`` devices streams ``n / num_devices`` rows in
    parallel.  ``db_hosts > 1`` adds the cross-host DCN merge term
    (MODEL_VERSION 4): the hierarchical top-k merge ships each host's
    ``[nq, k]`` candidate list over DCN at the ``dcn_merge`` strategy
    (None = the measured crossover pick), serialized after the
    per-host compute.  ``nprobe``/``ncentroids`` (MODEL_VERSION 5)
    scale the streamed rows by the expected probe fraction and add the
    centroid-scan term (``_probe_setup``).  ``pq_dsub``/``pq_ncodes``
    (MODEL_VERSION 6) size the "pq" arm's codebook geometry — ignored
    by every other precision; None takes the widths defaults (4, 256).
    The two knob pairs COMPOSE: a probed pq block streams
    ``probe_fraction × ceil(d/dsub)`` code bytes per row, the two byte
    reductions multiplying."""
    precision = precision or "bf16x3"
    kernel = kernel or "tiled"
    if kernel not in ("tiled", "streaming", "fused"):
        raise ValueError(
            f"kernel {kernel!r} not in ('tiled', 'streaming', 'fused')")
    grid_order = grid_order or "query_major"
    binning = binning or "grouped"
    tile = int(tile_n or TILE_N_DEFAULT)
    bq = int(block_q or BLOCK_Q_DEFAULT)
    estimated = False
    if peaks is None:
        peaks, estimated = peaks_for(device_kind, backend)

    n_total = int(n)
    n, probe = _probe_setup(n_total, d, nq, nprobe, ncentroids)
    n_dev = _ceil_div(n, max(1, int(num_devices)))
    tile = min(tile, max(BIN_W, _ceil_div(n_dev, BIN_W) * BIN_W))
    n_tiles = _ceil_div(n_dev, tile)
    q_blocks = _ceil_div(nq, bq)
    if binning == "grouped":
        surv = int(survivors or SURVIVORS_GROUPED_DEFAULT)
        out_w = surv * BIN_W
        bound_w = BIN_W
        sel_ops = SELECT_OPS["grouped"]
    else:
        surv = int(survivors or 2)
        n_bins = max(1, tile // BIN_W)
        out_w = _ceil_div(n_bins * surv, BIN_W) * BIN_W
        bound_w = _ceil_div(n_bins, BIN_W) * BIN_W
        sel_ops = SELECT_OPS["lane"]

    # --- HBM bytes ------------------------------------------------------
    # db stream passes: query_major (and the inherently query-major
    # streaming/fused kernels) re-stream the full db once per query
    # block; db_major streams it ONCE at single-chunk dims but
    # degenerates to query_major traffic when the innermost chunk axis
    # cycles between query blocks (ops.pallas_knn.GRID_ORDERS)
    if grid_order == "db_major" and d <= DIM_CHUNK and kernel == "tiled":
        db_passes = 1
    else:
        db_passes = q_blocks
    eff_dsub = int(pq_dsub or _widths.PQ_DSUB_DEFAULT)
    eff_ncodes = int(pq_ncodes or _widths.PQ_NCODES_DEFAULT)
    opnd = db_operand_nbytes(n_dev, d, precision, dsub=eff_dsub)
    db_stream = db_passes * opnd["db_values"]
    db_aux = db_passes * opnd["db_aux"]
    # query blocks re-fetch once per db tile (their mapped index cycles
    # with the dim-chunk axis); int8/int4 add the [block_q, 128] f32
    # per-query scale block per cell; pq's query-side operand is the
    # per-query LUT ([nq, m·ncodes] f32), re-fetched per db tile in
    # place of the raw query blocks (the raw queries are consumed ONCE
    # by the XLA LUT prologue)
    if precision == "pq":
        queries_b = n_tiles * _widths.pq_lut_bytes(
            nq, d, dsub=eff_dsub, ncodes=eff_ncodes) + nq * d * 4
    else:
        q_elem = QUERY_ELEM_BYTES.get(precision, QUERY_ELEM_BYTES_DEFAULT)
        queries_b = n_tiles * nq * d * q_elem
    if precision in ("int8", "int4"):
        queries_b += n_tiles * nq * BIN_W * 4
    # candidate outputs: every (query block, db tile) cell writes its
    # disjoint (block_q, out_w) f32+i32 candidates and bound_w bounds
    # exactly once (the streaming kernel flushes the same total width
    # once per query block — identical bytes, fewer launches)
    cand_b = q_blocks * n_tiles * bq * (out_w * 8 + bound_w * 4)
    hbm_total = db_stream + db_aux + queries_b + cand_b
    if probe is not None:
        hbm_total += probe["centroid_table_bytes"]
    t_hbm = hbm_total / (peaks["hbm_gbps"] * 1e9)

    # --- MXU flops ------------------------------------------------------
    useful = 2.0 * nq * n * d
    passes = MXU_PASSES[precision]
    if precision == "pq":
        # the kernel's one dense dot contracts over the one-hot
        # expansion's m·ncodes width (ops.pallas_knn._pq_onehot_qt),
        # not d — plus the per-query LUT build in the XLA prologue.
        # Honest and mxu-heavy: PQ's win is the BYTE term, and the
        # model says so rather than pricing a gather kernel it does
        # not run.
        m_sub = _widths.pq_nsub(d, eff_dsub)
        lut_flops = _widths.pq_lut_flops(nq, d, dsub=eff_dsub,
                                         ncodes=eff_ncodes)
        executed = 2.0 * nq * n * (m_sub * eff_ncodes) + lut_flops
    else:
        executed = useful * passes
    if probe is not None:
        useful += probe["assign_flops"]
        executed += probe["assign_flops"]
    mxu_rate = peaks["int8_flops"] if precision in ("int8", "int4") \
        else peaks["bf16_flops"]
    # executed flops are per-device work summed over the (perfectly
    # scaled) mesh: each device runs executed/num_devices in parallel
    t_mxu = executed / max(1, int(num_devices)) / mxu_rate

    # --- VPU select -----------------------------------------------------
    vpu_ops = nq * float(n) * sel_ops
    t_vpu = vpu_ops / max(1, int(num_devices)) / peaks["vpu_ops"]

    model = {
        "model_version": MODEL_VERSION,
        "selector": "pallas",
        "device_kind": device_kind,
        "estimated": estimated,
        "peaks": {"hbm_gbps": peaks["hbm_gbps"],
                  "mxu_flops": mxu_rate, "vpu_ops": peaks["vpu_ops"]},
        "config": {
            "n": n_total, "d": int(d), "k": int(k), "nq": int(nq),
            "precision": precision, "kernel": kernel,
            "grid_order": grid_order, "binning": binning,
            "tile_n": tile, "block_q": bq, "survivors": surv,
            "margin": int(margin), "num_devices": int(num_devices),
            "db_hosts": max(1, int(db_hosts)),
        },
        "terms": {
            "hbm": {
                "bytes": {
                    "db_stream": int(db_stream), "db_aux": int(db_aux),
                    "queries": int(queries_b),
                    "candidates_out": int(cand_b),
                    "total": int(hbm_total),
                },
                "db_passes": int(db_passes),
                "time_s": t_hbm,
            },
            "mxu": {
                "flops_useful": useful, "flops_executed": executed,
                "passes": passes, "rate_flops": mxu_rate, "time_s": t_mxu,
            },
            "vpu_select": {
                "ops": vpu_ops, "ops_per_elem": sel_ops,
                "rate_ops": peaks["vpu_ops"], "time_s": t_vpu,
            },
        },
    }
    if precision == "pq":
        model["config"]["pq_dsub"] = eff_dsub
        model["config"]["pq_ncodes"] = eff_ncodes
        model["terms"]["mxu"]["pq_onehot_width"] = int(
            _widths.pq_nsub(d, eff_dsub) * eff_ncodes)
        model["terms"]["mxu"]["pq_lut_flops"] = float(lut_flops)
    if probe is not None:
        model["config"]["nprobe"] = probe["nprobe"]
        model["config"]["ncentroids"] = probe["ncentroids"]
        model["config"]["probe_fraction"] = probe["probe_fraction"]
        model["terms"]["probe"] = probe
    dcn = _dcn_term(nq, k, db_hosts, dcn_merge, device_kind, peaks)
    if dcn is not None:
        model["terms"]["dcn"] = dcn
    # the fused kernel's in-loop select rides the HBM stream's shadow
    # (its early-out makes the 12-op calibration an upper bound there —
    # skipped tiles pay ~1 op/elem, unmodelable statically); the
    # non-fused kernels run the select serially after each tile's
    # scores exist.  A fused config whose carry would exceed
    # MAX_CARRY_DEPTH (keep = k+margin+2 past 128*8) DISARMS in the
    # kernel and runs serialized — the model mirrors that, so the
    # pruning gate and `--best` can never rank a disarmed config
    # against a ceiling it cannot reach (the kernel's m-cap can only
    # shrink keep below this estimate, making the disarm call here
    # conservative, never optimistic)
    fused_armed = kernel == "fused" and _ceil_div(
        int(k) + int(margin) + 2, BIN_W) <= MAX_CARRY_DEPTH
    _terms_to_verdict(model, nq, select_overlapped=fused_armed)
    return model


def xla_cost_model(
    *, n: int, d: int, k: int, nq: int, selector: str = "exact",
    dtype: Optional[str] = None, batch: Optional[int] = None,
    margin: int = 28, device_kind: Optional[str] = None,
    backend: Optional[str] = None, num_devices: int = 1,
    peaks: Optional[Dict[str, float]] = None,
    db_hosts: int = 1, dcn_merge: Optional[str] = None,
    nprobe: Optional[int] = None, ncentroids: Optional[int] = None,
) -> dict:
    """Roofline for the XLA selectors: ``exact`` (coarse ``lax.top_k``,
    one db pass) and ``approx`` (ApproxTopK coarse + the count-below
    certificate matmul, two passes).  The db streams once per
    ``batch``-query chunk per pass at the placement dtype's width.
    ``nprobe``/``ncentroids`` apply the MODEL_VERSION-5 IVF pruning
    substitution exactly as in ``pallas_cost_model``."""
    if selector not in ("exact", "approx"):
        raise ValueError(f"xla selector {selector!r} not in "
                         f"('exact', 'approx')")
    dtype = dtype or "float32"
    if dtype not in _DTYPE_BYTES:
        raise ValueError(f"dtype {dtype!r} not in {sorted(_DTYPE_BYTES)}")
    bs = int(batch or nq)
    estimated = False
    if peaks is None:
        peaks, estimated = peaks_for(device_kind, backend)

    n_total = int(n)
    n, probe = _probe_setup(n_total, d, nq, nprobe, ncentroids)
    n_dev = _ceil_div(n, max(1, int(num_devices)))
    chunks = _ceil_div(nq, bs)
    passes = 1 if selector == "exact" else 2
    elem = _DTYPE_BYTES[dtype]
    db_stream = chunks * passes * n_dev * d * elem
    db_aux = chunks * passes * n_dev * 4  # f32 row norms
    queries_b = passes * nq * d * 4
    cand_b = passes * nq * min(n, k + margin) * 8
    hbm_total = db_stream + db_aux + queries_b + cand_b
    if probe is not None:
        hbm_total += probe["centroid_table_bytes"]
    t_hbm = hbm_total / (peaks["hbm_gbps"] * 1e9)

    useful = 2.0 * nq * n * d
    executed = useful * passes * _DTYPE_PASSES[dtype]
    if probe is not None:
        useful += probe["assign_flops"]
        executed += probe["assign_flops"]
    t_mxu = executed / max(1, int(num_devices)) / peaks["bf16_flops"]

    sel_ops = XLA_SELECT_OPS[selector]
    vpu_ops = nq * float(n) * sel_ops
    t_vpu = vpu_ops / max(1, int(num_devices)) / peaks["vpu_ops"]

    model = {
        "model_version": MODEL_VERSION,
        "selector": selector,
        "device_kind": device_kind,
        "estimated": estimated,
        "peaks": {"hbm_gbps": peaks["hbm_gbps"],
                  "mxu_flops": peaks["bf16_flops"],
                  "vpu_ops": peaks["vpu_ops"]},
        "config": {
            "n": n_total, "d": int(d), "k": int(k), "nq": int(nq),
            "dtype": dtype, "batch": bs, "passes": passes,
            "margin": int(margin), "num_devices": int(num_devices),
            "db_hosts": max(1, int(db_hosts)),
        },
        "terms": {
            "hbm": {
                "bytes": {
                    "db_stream": int(db_stream), "db_aux": int(db_aux),
                    "queries": int(queries_b),
                    "candidates_out": int(cand_b),
                    "total": int(hbm_total),
                },
                "db_passes": int(chunks * passes),
                "time_s": t_hbm,
            },
            "mxu": {
                "flops_useful": useful, "flops_executed": executed,
                "passes": passes * _DTYPE_PASSES[dtype],
                "rate_flops": peaks["bf16_flops"], "time_s": t_mxu,
            },
            "vpu_select": {
                "ops": vpu_ops, "ops_per_elem": sel_ops,
                "rate_ops": peaks["vpu_ops"], "time_s": t_vpu,
            },
        },
    }
    if probe is not None:
        model["config"]["nprobe"] = probe["nprobe"]
        model["config"]["ncentroids"] = probe["ncentroids"]
        model["config"]["probe_fraction"] = probe["probe_fraction"]
        model["terms"]["probe"] = probe
    dcn = _dcn_term(nq, k, db_hosts, dcn_merge, device_kind, peaks)
    if dcn is not None:
        model["terms"]["dcn"] = dcn
    _terms_to_verdict(model, nq)
    return model


def cost_model(*, selector: str = "pallas", **kwargs) -> dict:
    """One entry point over both model families: ``selector="pallas"``
    takes the kernel knobs, ``"exact"``/``"approx"`` the XLA placement
    dtype + batch."""
    if selector == "pallas":
        return pallas_cost_model(**kwargs)
    return xla_cost_model(selector=selector, **kwargs)


def join_cost_model(
    *, n_a: int, n_b: int, d: int, k: int, superblock_rows: int,
    selector: str = "exact", db_segment_rows: int = 0,
    device_kind: Optional[str] = None, backend: Optional[str] = None,
    num_devices: int = 1, peaks: Optional[Dict[str, float]] = None,
    db_hosts: int = 1, dcn_merge: Optional[str] = None,
    **selector_kwargs,
) -> dict:
    """The MODEL_VERSION-7 bulk kNN-join roofline: ``n_a`` query rows
    joined against an ``n_b``-row corpus in superblocks of
    ``superblock_rows``, per the join engine's execution shape
    (knn_tpu.join.engine).

    The device-side terms are the serving cost model of ONE superblock
    dispatch — ``nq = superblock_rows`` and (for the XLA selectors the
    stream path actually runs) ``batch = superblock_rows``, so the db
    streams ONCE per superblock and the modeled db HBM bytes PER QUERY
    are ``db_bytes / superblock_rows`` — the 1/S amortization, falling
    until ``bound_class`` flips off ``hbm_bound`` to whichever term
    stops shrinking (mxu, usually).  On top, ``terms.h2d`` prices the
    host->device stream :func:`knn_tpu.analysis.hbm.plan_join` plans
    (queries, plus the db segments when B is host-tiered, at the
    winning nesting order) against :func:`h2d_gbps_for`; the engine
    double-buffers, so the steady-state per-superblock time is
    ``max(t_device, t_h2d)`` — an h2d stream slower than compute makes
    the block ``h2d_bound``.  ``ceiling_qps`` is the steady-state JOIN
    throughput in rows of A per second; the analytic verdict stands
    (calibration entries cover serving shapes, so the block carries an
    explicit skip note)."""
    from knn_tpu.analysis import hbm as _hbm

    sb = int(superblock_rows)
    if sb < 1:
        raise ValueError(f"superblock_rows must be >= 1, got {sb}")
    base_kw = dict(
        n=n_b, d=d, k=k, nq=sb, device_kind=device_kind,
        backend=backend, num_devices=num_devices, peaks=peaks,
        db_hosts=db_hosts, dcn_merge=dcn_merge, **selector_kwargs)
    if selector in ("exact", "approx"):
        # one superblock = one chunk: the whole point of the regime
        base_kw.setdefault("batch", sb)
    model = cost_model(selector=selector, **base_kw)
    plan = _hbm.plan_join(n_a, n_b, d, superblock_rows=sb,
                          db_segment_rows=db_segment_rows)
    s = plan["superblocks"]
    h2d_total = plan["h2d_bytes"][plan["order"]]
    rate = h2d_gbps_for(device_kind, peaks)
    per_sb = h2d_total / s
    t_h2d = per_sb / (rate * 1e9)
    # re-derive the device combined time from the ANALYTIC term times
    # (a serving calibration entry fit a different batch shape; the
    # join verdict stays analytic, explicitly)
    times = dict(model["term_times_s"])
    t_dev = _combined(times, model.get("select_overlapped", False))
    t_sb = max(t_dev, t_h2d)
    hbm_b = model["terms"]["hbm"]["bytes"]
    model["terms"]["h2d"] = {
        "bytes": int(per_sb),
        "total_bytes": int(h2d_total),
        "rate_gbps": rate,
        "time_s": t_h2d,
        "overlapped": True,  # double buffering hides the smaller side
    }
    model["join"] = {
        "n_a": int(n_a),
        "superblock_rows": sb,
        "superblocks": int(s),
        "db_segments": int(plan["db_segments"]),
        "order": plan["order"],
        # the amortization headline: db HBM bytes each query costs
        "db_bytes_per_query": (hbm_b["db_stream"] + hbm_b["db_aux"])
        / sb,
        "h2d_bytes_per_query": h2d_total / max(1, int(n_a)),
        "rows_per_s_ceiling": round(sb / t_sb, 1) if t_sb > 0 else None,
    }
    times["h2d_bound"] = t_h2d
    model["bound_class"] = max(
        times, key=lambda c: (times[c], -BOUND_CLASSES.index(c)))
    model["ceiling_qps"] = round(sb / t_sb, 1) if t_sb > 0 else None
    model["ceiling_qps_analytic"] = model["ceiling_qps"]
    model["term_times_s"] = {c: round(v, 6) for c, v in times.items()}
    model.pop("term_times_calibrated_s", None)
    model["calibration"] = {
        "applied": False,
        "note": "join blocks use the analytic h2d model"}
    return model


def attribute(model: dict, measured_qps: Optional[float]) -> dict:
    """The model plus the measured verdict: ``roofline_pct`` =
    measured / ceiling (NOT clamped — a pct > 1 means the peak table or
    a term is wrong, which is a finding, not an error)."""
    out = dict(model)
    if measured_qps is not None and model.get("ceiling_qps"):
        out["measured_qps"] = round(float(measured_qps), 2)
        out["roofline_pct"] = round(
            float(measured_qps) / model["ceiling_qps"], 4)
    else:
        out["measured_qps"] = None
        out["roofline_pct"] = None
    return out


def validate_block(block) -> list:
    """Structural validation of a ``roofline`` block (bench lines,
    curated artifacts, cache entries).  Returns a list of error
    strings, empty when well-formed — the refresher refuses malformed
    blocks and ``perf_sentinel --lint`` sweeps the history with this.
    A shim over the artifact-schema catalog
    (:mod:`knn_tpu.analysis.artifacts`, the ``roofline`` entry) with
    the legacy error strings byte-identical."""
    from knn_tpu.analysis.artifacts import validate

    return validate("roofline", block, style="legacy")


def config_label(n: int, d: int, k: int, *, metric: str = "l2",
                 dtype: Optional[str] = None,
                 device_kind: Optional[str] = None) -> str:
    """The registry label one attribution publishes under — the tuning
    cache key's shape prefix, so a scraped gauge and a cached winner
    name the same config."""
    kind = device_kind or "unknown"
    return (f"{kind}|n{int(n)}|d{int(d)}|k{int(k)}|{metric.lower()}|"
            f"{dtype or 'float32'}")


def publish(label: str, block: dict) -> None:
    """Export one attribution to the metrics registry + the /statusz
    store.  No-op when telemetry is disabled (``KNN_TPU_OBS=0``) — the
    roofline surface is part of the obs opt-in, like every exporter."""
    if not registry.enabled():
        return
    pct = block.get("roofline_pct")
    if pct is not None:
        registry.gauge(names.ROOFLINE_PCT, config=label).set(float(pct))
    if block.get("ceiling_qps"):
        registry.gauge(names.ROOFLINE_CEILING_QPS, config=label).set(
            float(block["ceiling_qps"]))
    bound = block.get("bound_class")
    if bound in BOUND_CLASSES:
        for cls in BOUND_CLASSES:
            registry.gauge(
                names.ROOFLINE_BOUND, config=label,
                **{"class": cls}).set(1.0 if cls == bound else 0.0)
    registry.counter(names.ROOFLINE_EVALUATIONS).inc()
    cal = block.get("calibration")
    if isinstance(cal, dict):
        from knn_tpu.obs import calibrate

        calibrate.publish(label, cal)
    compact = {
        "roofline_pct": pct,
        "ceiling_qps": block.get("ceiling_qps"),
        "ceiling_qps_analytic": block.get("ceiling_qps_analytic"),
        "bound_class": bound,
        "measured_qps": block.get("measured_qps"),
        "estimated": bool(block.get("estimated")),
        "model_version": block.get("model_version"),
        "calibration_applied": bool(
            cal.get("applied")) if isinstance(cal, dict) else False,
    }
    with _lock:
        _LAST.pop(label, None)
        _LAST[label] = compact
        while len(_LAST) > _LAST_MAX:
            _LAST.pop(next(iter(_LAST)))
        _PUBLISHED.add(label)
    trace.emit_event("roofline.publish", config=label,
                     roofline_pct=pct, bound_class=bound)


def was_published(label: str) -> bool:
    """Whether :func:`publish` ever ran for this label in this process
    (survives the bounded /statusz store's eviction) — the hot-path
    dedup ``tuning.resolve_full`` consults so a warm-cache resolve
    publishes once, not once per call."""
    with _lock:
        return label in _PUBLISHED


def last_reports() -> Dict[str, dict]:
    """The last published attributions, newest last — the /statusz +
    doctor surface (empty when nothing published or obs disabled)."""
    with _lock:
        return {k: dict(v) for k, v in _LAST.items()}


def reset() -> None:
    """Drop the published-attribution store (test isolation)."""
    with _lock:
        _LAST.clear()
        _PUBLISHED.clear()


def block_for_bench_line(rec: dict) -> Optional[dict]:
    """Best-effort attribution of one bench JSON line from its own
    fields (metric-name shape, ``pallas_knobs``, ``device_kind``,
    ``device_phase_qps``/``value``) — what the artifact refresher
    curates onto lines that predate the in-bench roofline block.
    Returns None when the line doesn't carry enough to model."""
    m = _METRIC_RE.match(str(rec.get("metric") or ""))
    if not m:
        return None
    n, d, k = (int(m.group(g)) for g in ("n", "d", "k"))
    mode = rec.get("mode")
    device_kind = rec.get("device_kind")
    backend = rec.get("backend")
    devices = int(rec.get("devices") or 1)
    nq = int(rec.get("batch") or 4096)
    ivf = rec.get("ivf") if isinstance(rec.get("ivf"), dict) else {}
    probe_kw = ({"nprobe": int(ivf["nprobe"]),
                 "ncentroids": int(ivf["ncentroids"])}
                if ivf.get("nprobe") and ivf.get("ncentroids") else {})
    try:
        if mode == "certified_pallas":
            knobs = rec.get("pallas_knobs") or {}
            model = pallas_cost_model(
                n=n, d=d, k=k, nq=nq,
                precision=knobs.get("precision") or rec.get("precision"),
                kernel=knobs.get("kernel"),
                grid_order=knobs.get("grid_order"),
                binning=knobs.get("binning"), tile_n=knobs.get("tile_n"),
                block_q=knobs.get("block_q"),
                survivors=knobs.get("survivors"),
                margin=int(knobs.get("margin") or 28),
                device_kind=device_kind, backend=backend,
                num_devices=devices, **probe_kw)
            measured = rec.get("device_phase_qps") or rec.get("value")
        elif mode in ("exact", "certified_approx"):
            model = xla_cost_model(
                n=n, d=d, k=k, nq=nq,
                selector="exact" if mode == "exact" else "approx",
                dtype=rec.get("compute_dtype"), batch=rec.get("batch"),
                device_kind=device_kind, backend=backend,
                num_devices=devices, **probe_kw)
            measured = rec.get("value")
        else:
            return None
    except (ValueError, TypeError):
        return None
    return attribute(model, measured)


def render_text(block: dict) -> str:
    """Human-readable rendering of one model/attribution — shared by
    ``cli roofline`` and doctor so both print the same shape."""
    cfg = block.get("config", {})
    lines = []
    head = (f"roofline v{block.get('model_version')} "
            f"[{block.get('selector')}] "
            f"n={cfg.get('n')} d={cfg.get('d')} k={cfg.get('k')} "
            f"nq={cfg.get('nq')}")
    if block.get("selector") == "pallas":
        head += (f" precision={cfg.get('precision')} "
                 f"kernel={cfg.get('kernel')} "
                 f"grid={cfg.get('grid_order')} "
                 f"tile_n={cfg.get('tile_n')} block_q={cfg.get('block_q')}")
    else:
        head += f" dtype={cfg.get('dtype')} batch={cfg.get('batch')}"
    lines.append(head)
    kind = block.get("device_kind") or "generic-cpu"
    est = " (ESTIMATED generic fallback peaks)" if block.get(
        "estimated") else ""
    lines.append(f"device: {kind}{est}")
    terms = block.get("terms", {})
    hb = terms.get("hbm", {})
    by = hb.get("bytes", {})
    lines.append(
        f"  hbm:        {by.get('total', 0) / 1e9:10.3f} GB  "
        f"-> {hb.get('time_s', 0) * 1e3:9.3f} ms   "
        f"(db {by.get('db_stream', 0) / 1e9:.3f} GB x "
        f"{hb.get('db_passes')} passes, aux "
        f"{by.get('db_aux', 0) / 1e9:.3f}, q "
        f"{by.get('queries', 0) / 1e9:.3f}, out "
        f"{by.get('candidates_out', 0) / 1e9:.3f})")
    mx = terms.get("mxu", {})
    lines.append(
        f"  mxu:        {mx.get('flops_executed', 0) / 1e12:10.3f} TFLOP "
        f"-> {mx.get('time_s', 0) * 1e3:9.3f} ms   "
        f"({mx.get('passes')}x passes over "
        f"{mx.get('flops_useful', 0) / 1e12:.3f} useful TFLOP at "
        f"{mx.get('rate_flops', 0) / 1e12:.0f} TF/s)")
    vp = terms.get("vpu_select", {})
    lines.append(
        f"  vpu_select: {vp.get('ops', 0) / 1e9:10.3f} Gops  "
        f"-> {vp.get('time_s', 0) * 1e3:9.3f} ms   "
        f"({vp.get('ops_per_elem')} ops/elem at "
        f"{vp.get('rate_ops', 0) / 1e12:.1f} Tops/s)")
    dc = terms.get("dcn")
    if dc:
        lines.append(
            f"  dcn:        {dc.get('bytes', 0) / 1e6:10.3f} MB  "
            f"-> {dc.get('time_s', 0) * 1e3:9.3f} ms   "
            f"({dc.get('hosts')} hosts, {dc.get('strategy')} merge at "
            f"{dc.get('rate_gbps')} GB/s)")
    pr = terms.get("probe")
    if pr:
        lines.append(
            f"  probed:     {pr.get('rows_probed', 0) / 1e6:10.3f} Mrow "
            f"of {(cfg.get('n') or 0) / 1e6:.3f} M    "
            f"(nprobe {pr.get('nprobe')}/{pr.get('ncentroids')} lists = "
            f"{pr.get('probe_fraction', 0):.4f} of db bytes, centroid "
            f"scan {pr.get('centroid_table_bytes', 0) / 1e6:.3f} MB)")
    overlap = (" select overlapped" if block.get("select_overlapped")
               else "")
    cal = block.get("calibration")
    if isinstance(cal, dict) and cal.get("applied"):
        lines.append(
            f"ceiling: {block.get('ceiling_qps')} q/s CALIBRATED "
            f"({block.get('bound_class')}{overlap}; analytic "
            f"{block.get('ceiling_qps_analytic')} q/s, model off by "
            f"{cal.get('model_residual_pct')}%, source "
            f"{cal.get('source')}, age {cal.get('age_s')}s)")
    else:
        err = (f", overlay error: {cal['error']}"
               if isinstance(cal, dict) and cal.get("error") else "")
        lines.append(f"ceiling: {block.get('ceiling_qps')} q/s "
                     f"({block.get('bound_class')}{overlap}) "
                     f"[calibration: absent{err}]")
    if block.get("roofline_pct") is not None:
        lines.append(f"measured: {block.get('measured_qps')} q/s = "
                     f"{block['roofline_pct'] * 100:.1f}% of roofline")
    return "\n".join(lines) + "\n"
