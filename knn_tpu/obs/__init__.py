"""knn_tpu.obs — the unified telemetry subsystem.

One registry, one event log, two exporters; everything else in the
repo (serving, certified search, tuning, pipeline phases, JAX compiles)
writes through here instead of keeping private ad-hoc counters:

- **Metrics registry** (:mod:`knn_tpu.obs.registry`): process-wide,
  thread-safe counters / gauges / bounded histograms with p50/p95/p99,
  validated against the catalog (:mod:`knn_tpu.obs.names`).  Disabled
  mode (``KNN_TPU_OBS=0``) hands out one shared no-op instrument —
  near-zero cost, bitwise-identical results.
- **Spans + events** (:mod:`knn_tpu.obs.trace`): request-scoped trace
  ids minted at submit and propagated through micro-batching; a bounded
  in-memory event ring plus an optional JSONL sink
  (``KNN_TPU_OBS_LOG``).
- **Exporters** (:mod:`knn_tpu.obs.export`): Prometheus text served
  from a stdlib-HTTP endpoint (``--metrics-port``), an atomic JSON
  snapshot writer, and ``python -m knn_tpu.cli metrics`` to read
  either.
- **Compile hook** (:mod:`knn_tpu.obs.jax_hooks`): every XLA compile's
  count + seconds via ``jax.monitoring``.
- **Roofline model** (:mod:`knn_tpu.obs.roofline`): the analytic
  per-config HBM/MXU/VPU cost model behind every ``roofline_pct`` /
  ``bound_class`` the bench, autotuner, sentinel, and /statusz report —
  jax-free attribution of the MFU gap per config.
- **Device trace capture** (:mod:`knn_tpu.obs.profiler`): opt-in
  ``jax.profiler.trace`` wrapping of bench/tuning runs
  (``KNN_TPU_PROFILE_DIR``), for the slack the model can't name.
- **Tail forensics** (:mod:`knn_tpu.obs.waterfall`): per-request
  latency waterfalls reconstructed from the span stream, critical-path
  attribution at p50 vs p99 per tenant/bucket, histogram->trace
  exemplars, and the slowest-requests tables.
- **Flight recorder** (:mod:`knn_tpu.obs.blackbox`): one atomic,
  retention-capped postmortem bundle per edge-triggered SLO breach
  (``KNN_TPU_POSTMORTEM_DIR``), readable offline by ``cli waterfall``.
- **Shadow audit sampler** (:mod:`knn_tpu.obs.audit`): off-path exact
  replay of a deterministic sample of served requests against the f64
  oracle (``KNN_TPU_AUDIT_RATE``), emitting per-tenant recall@k,
  rank-displacement, and distance-error telemetry under a hard row
  budget.
- **Drift detection** (:mod:`knn_tpu.obs.drift`): streaming query
  distribution sketches (norms, centroid assignments) scored by PSI
  against train-time baselines, plus index-health gauges.
- **Fleet plane** (:mod:`knn_tpu.obs.fleet`): N processes' telemetry
  merged into one cross-host report — counters summed, gauges kept
  per-host with min/max/argmax, quantiles from element-wise-summed
  histogram buckets (never averaged percentiles), stitched multi-host
  waterfalls, fleet SLO edges with member-embedding postmortems
  (``KNN_TPU_FLEET_MEMBERS``, ``/fleetz``, ``cli fleet``); every
  payload stamped with the process identity (:mod:`knn_tpu.obs.ident`).

The package itself imports no JAX (jax_hooks defers it), so the CLI's
flag parsing and the lint script stay import-light.

Metric catalog, span lifecycle, and overhead numbers:
``docs/OBSERVABILITY.md``.
"""

from knn_tpu.obs import (  # noqa: F401
    audit,
    blackbox,
    drift,
    fleet,
    health,
    ident,
    names,
    profiler,
    roofline,
    sentinel,
    slo,
    waterfall,
)
from knn_tpu.obs.export import (  # noqa: F401
    compact_snapshot,
    prometheus_text,
    start_metrics_server,
    write_json_snapshot,
)
from knn_tpu.obs.jax_hooks import install_compile_hook  # noqa: F401
from knn_tpu.obs.slo import (  # noqa: F401
    SLOEngine,
    Objective,
    get_slo_engine,
    load_objectives,
    reset_slo_engine,
    slo_report,
)
from knn_tpu.obs.registry import (  # noqa: F401
    NOOP,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    enabled,
    gauge,
    get_registry,
    histogram,
    reset,
    snapshot,
)
from knn_tpu.obs.trace import (  # noqa: F401
    EventLog,
    emit_event,
    get_event_log,
    new_trace_id,
    record_span,
    reset_event_log,
    span,
)

__all__ = [
    "NOOP", "Counter", "EventLog", "Gauge", "Histogram",
    "MetricsRegistry", "Objective", "SLOEngine", "audit", "blackbox",
    "compact_snapshot", "drift",
    "counter", "emit_event", "enabled", "fleet", "gauge",
    "get_event_log",
    "get_registry", "get_slo_engine", "health", "histogram", "ident",
    "install_compile_hook", "load_objectives", "names", "new_trace_id",
    "profiler", "prometheus_text", "record_span", "reset",
    "reset_event_log", "reset_slo_engine", "roofline", "sentinel", "slo",
    "slo_report", "snapshot", "span", "start_metrics_server",
    "waterfall", "write_json_snapshot",
]
