"""The metric catalog — ONE jax-free home for every metric name the
registry may hand out.

Every metric the subsystem can register is declared here, with its type,
label names, and help string.  The registry REFUSES names outside this
catalog (knn_tpu.obs.registry), and ``scripts/lint_metric_names.py``
checks two invariants over it: every name matches ``knn_tpu_[a-z0-9_]+``
and every name appears in the ``docs/OBSERVABILITY.md`` catalog table —
so an instrumented code path can neither invent an undocumented metric
nor document a phantom one.

Names follow the Prometheus conventions the exporters assume: a
``knn_tpu_`` namespace prefix, ``_total`` suffix on counters, ``_seconds``
on time-valued metrics, base units throughout.

:func:`catalog_version` digests the whole catalog into a short token.
Identity-stamped snapshots carry it (knn_tpu.obs.export), and the fleet
aggregator refuses to merge members whose token differs — summing a
counter whose meaning changed between versions would silently produce
nonsense (knn_tpu.obs.fleet lists such members under ``skewed``).
"""

from __future__ import annotations

import hashlib
from functools import lru_cache


@lru_cache(maxsize=1)
def catalog_version() -> str:
    """A 12-hex digest of every (name, kind, labels) triple in the
    catalog — help-string edits don't move it, but adding/removing a
    metric or changing its kind/labels does."""
    h = hashlib.sha256()
    for name in sorted(CATALOG):
        kind, labels, _help = CATALOG[name]
        h.update(f"{name}|{kind}|{','.join(sorted(labels))}\n".encode())
    return h.hexdigest()[:12]

# --- serving engine (knn_tpu.serving.engine) ---------------------------
SERVING_REQUESTS = "knn_tpu_serving_requests_total"
SERVING_QUERIES = "knn_tpu_serving_queries_total"
SERVING_ERRORS = "knn_tpu_serving_errors_total"
SERVING_DISPATCHES = "knn_tpu_serving_dispatches_total"
SERVING_COMPILES = "knn_tpu_serving_compiles_total"
SERVING_REQUEST_LATENCY = "knn_tpu_serving_request_latency_seconds"

# --- micro-batching queue (knn_tpu.serving.queue) ----------------------
QUEUE_DEPTH_REQUESTS = "knn_tpu_queue_depth_requests"
QUEUE_DEPTH_ROWS = "knn_tpu_queue_depth_rows"
QUEUE_REQUESTS = "knn_tpu_queue_requests_total"
QUEUE_DISPATCHES = "knn_tpu_queue_dispatches_total"
QUEUE_COALESCED_ROWS = "knn_tpu_queue_coalesced_rows_total"
QUEUE_ERRORS = "knn_tpu_queue_errors_total"
QUEUE_WAIT = "knn_tpu_queue_wait_seconds"
QUEUE_REQUEST_LATENCY = "knn_tpu_queue_request_latency_seconds"

# --- admission control (knn_tpu.serving.admission / queue) -------------
ADMISSION_ADMITTED = "knn_tpu_admission_admitted_total"
ADMISSION_REJECTED = "knn_tpu_admission_rejected_total"
ADMISSION_SHED = "knn_tpu_admission_shed_total"
ADMISSION_WAIT_ESTIMATE = "knn_tpu_admission_queue_wait_estimate_seconds"

# --- per-tenant serving attribution (knn_tpu.serving) ------------------
TENANT_REQUESTS = "knn_tpu_tenant_requests_total"
TENANT_ERRORS = "knn_tpu_tenant_errors_total"
TENANT_REQUEST_LATENCY = "knn_tpu_tenant_request_latency_seconds"

# --- certified search (knn_tpu.parallel.sharded) -----------------------
CERTIFIED_QUERIES = "knn_tpu_certified_queries_total"
CERTIFIED_FALLBACKS = "knn_tpu_certified_fallback_queries_total"
CERTIFIED_GENUINE_MISSES = "knn_tpu_certified_fallback_genuine_misses_total"
CERTIFIED_FALSE_ALARMS = "knn_tpu_certified_fallback_false_alarms_total"
CERTIFIED_HOST_EXACT = "knn_tpu_certified_host_exact_queries_total"
CERTIFIED_RANK_CORRECTED = "knn_tpu_certified_rank_corrected_queries_total"
CERTIFIED_QUANT_BOUND = "knn_tpu_certified_quant_bound"

# --- autotuner (knn_tpu.tuning) ----------------------------------------
TUNING_RESOLVES = "knn_tpu_tuning_resolve_total"
TUNING_CACHE_HITS = "knn_tpu_tuning_cache_hits_total"
TUNING_CACHE_MISSES = "knn_tpu_tuning_cache_misses_total"
TUNING_SEARCHES = "knn_tpu_tuning_searches_total"
TUNING_CANDIDATES_TIMED = "knn_tpu_tuning_candidates_timed_total"
TUNING_GATE_FAILURES = "knn_tpu_tuning_gate_failures_total"
TUNING_CANDIDATES_PRUNED = "knn_tpu_tuning_candidates_pruned_total"
TUNING_CANDIDATES_VMEM_REFUSED = \
    "knn_tpu_tuning_candidates_vmem_refused_total"

# --- certified pipeline overlap (knn_tpu.parallel.sharded) -------------
PIPELINE_OVERLAP_RATIO = "knn_tpu_pipeline_overlap_ratio"

# --- JAX compile events (knn_tpu.obs.jax_hooks) ------------------------
JAX_COMPILES = "knn_tpu_jax_compiles_total"
JAX_COMPILE_SECONDS = "knn_tpu_jax_compile_seconds_total"

# --- pipeline / spans (knn_tpu.utils.timing, knn_tpu.obs.trace) --------
PHASE_SECONDS = "knn_tpu_phase_seconds"
SPAN_SECONDS = "knn_tpu_span_seconds"
EVENTS_DROPPED = "knn_tpu_events_dropped_total"

# --- SLO engine (knn_tpu.obs.slo) --------------------------------------
SLO_BURN_RATE = "knn_tpu_slo_burn_rate"
SLO_BREACHED = "knn_tpu_slo_breached"
SLO_BREACH_TRANSITIONS = "knn_tpu_slo_breach_transitions_total"
SLO_EVALUATIONS = "knn_tpu_slo_evaluations_total"

# --- health introspection (knn_tpu.obs.health) -------------------------
HEALTH_READY = "knn_tpu_health_ready"

# --- flight recorder (knn_tpu.obs.blackbox) ----------------------------
POSTMORTEMS_WRITTEN = "knn_tpu_postmortems_written_total"

# --- roofline model (knn_tpu.obs.roofline) -----------------------------
ROOFLINE_PCT = "knn_tpu_roofline_pct"
ROOFLINE_CEILING_QPS = "knn_tpu_roofline_ceiling_qps"
ROOFLINE_BOUND = "knn_tpu_roofline_bound"
ROOFLINE_EVALUATIONS = "knn_tpu_roofline_evaluations_total"

# --- measured-term calibration (knn_tpu.obs.calibrate) -----------------
CALIBRATION_APPLIED = "knn_tpu_calibration_applied"
CALIBRATION_AGE = "knn_tpu_calibration_age_seconds"
CALIBRATION_RESIDUAL = "knn_tpu_calibration_residual_pct"

# --- measured-ceiling campaign (knn_tpu.campaign) ----------------------
CAMPAIGN_ARMS = "knn_tpu_campaign_arms_total"
CAMPAIGN_STAGES = "knn_tpu_campaign_stages_total"

# --- multi-host merge tree (knn_tpu.parallel.sharded / .multihost) -----
MERGE_SELECTED = "knn_tpu_merge_strategy_selected_total"
MERGE_BYTES = "knn_tpu_merge_bytes_total"
MERGE_STRAGGLER_GAP = "knn_tpu_merge_straggler_gap_seconds"

# --- host-RAM shard tier (knn_tpu.parallel.sharded) --------------------
HOSTTIER_SWEEPS = "knn_tpu_hosttier_sweeps_total"
HOSTTIER_SEGMENT_ROWS = "knn_tpu_hosttier_segment_rows"
HOSTTIER_SWEEP_SECONDS = "knn_tpu_hosttier_sweep_seconds"

# --- mutable index (knn_tpu.index.mutable) -----------------------------
INDEX_EPOCH = "knn_tpu_index_epoch"
INDEX_TAIL_ROWS = "knn_tpu_index_tail_rows"
INDEX_TOMBSTONES = "knn_tpu_index_tombstones"
INDEX_COMPACTIONS = "knn_tpu_index_compactions_total"
INDEX_SWAP_SECONDS = "knn_tpu_index_swap_seconds"

# --- shadow audit sampler (knn_tpu.obs.audit) --------------------------
AUDIT_SAMPLED = "knn_tpu_audit_sampled_requests_total"
AUDIT_REPLAYED = "knn_tpu_audit_replayed_queries_total"
AUDIT_DEFICIENT = "knn_tpu_audit_deficient_queries_total"
AUDIT_DROPPED = "knn_tpu_audit_dropped_total"
AUDIT_ROWS_SCORED = "knn_tpu_audit_rows_scored_total"
AUDIT_RECALL = "knn_tpu_audit_recall_at_k"
AUDIT_RANK_DISPLACEMENT = "knn_tpu_audit_rank_displacement"
AUDIT_DISTANCE_ERROR = "knn_tpu_audit_distance_rel_error"

# --- certificate-margin telemetry (sharded / ivf certified paths) ------
CERTIFIED_MARGIN = "knn_tpu_certified_margin_ratio"

# --- IVF per-search quality (knn_tpu.ivf.index) ------------------------
IVF_FALLBACK_RATE = "knn_tpu_ivf_fallback_rate"
IVF_RECALL_AT_K = "knn_tpu_ivf_recall_at_k"
IVF_PROBE_FRACTION = "knn_tpu_ivf_probe_fraction"
IVF_BYTES_STREAMED_RATIO = "knn_tpu_ivf_bytes_streamed_ratio"

# --- query-distribution drift (knn_tpu.obs.drift) ----------------------
DRIFT_NORM_PSI = "knn_tpu_drift_query_norm_psi"
DRIFT_ASSIGN_PSI = "knn_tpu_drift_centroid_assign_psi"
DRIFT_QUERIES = "knn_tpu_drift_queries_observed_total"

# --- index-health gauges (knn_tpu.obs.drift) ---------------------------
INDEX_LIST_IMBALANCE = "knn_tpu_index_list_imbalance"
INDEX_TAIL_FRACTION = "knn_tpu_index_delta_tail_fraction"
INDEX_TOMBSTONE_DENSITY = "knn_tpu_index_tombstone_density"

# --- fleet observability plane (knn_tpu.obs.fleet) ---------------------
FLEET_MEMBERS = "knn_tpu_fleet_members"
FLEET_UNREACHABLE = "knn_tpu_fleet_unreachable"
FLEET_MERGE_STALENESS = "knn_tpu_fleet_merge_staleness_seconds"
FLEET_STRAGGLER_HOST = "knn_tpu_fleet_straggler_host"

#: name -> (type, label names, help).  Types: "counter" (monotone,
#: float-valued so second-counters work), "gauge", "histogram" (bounded
#: sample window + lifetime count/sum; exported as a Prometheus summary).
CATALOG = {
    SERVING_REQUESTS: (
        "counter", ("op",),
        "Lifetime requests accepted by ServingEngine.submit()."),
    SERVING_QUERIES: (
        "counter", ("op",),
        "Lifetime query rows accepted by ServingEngine.submit()."),
    SERVING_ERRORS: (
        "counter", ("op",),
        "Requests that raised through dispatch or result join."),
    SERVING_DISPATCHES: (
        "counter", ("op", "bucket"),
        "Bucketed chunk dispatches, by op and bucket rung."),
    SERVING_COMPILES: (
        "counter", ("op", "bucket"),
        "Executable builds per (op, bucket) — the bucket ladder's "
        "compile-bound proof."),
    SERVING_REQUEST_LATENCY: (
        "histogram", ("op",),
        "Arrival-to-result request latency through the engine (seconds)."),
    QUEUE_DEPTH_REQUESTS: (
        "gauge", (),
        "Requests currently waiting in the micro-batching queue."),
    QUEUE_DEPTH_ROWS: (
        "gauge", (),
        "Query rows currently waiting in the micro-batching queue."),
    QUEUE_REQUESTS: (
        "counter", (),
        "Lifetime requests accepted by QueryQueue.submit()."),
    QUEUE_DISPATCHES: (
        "counter", (),
        "Coalesced batches the queue dispatched to the engine."),
    QUEUE_COALESCED_ROWS: (
        "counter", (),
        "Query rows dispatched through coalesced batches."),
    QUEUE_ERRORS: (
        "counter", (),
        "Queued requests resolved with an exception."),
    QUEUE_WAIT: (
        "histogram", (),
        "Per-request wait from arrival to batch dispatch (seconds)."),
    QUEUE_REQUEST_LATENCY: (
        "histogram", (),
        "Per-request arrival-to-result latency through the queue "
        "(seconds) — includes the micro-batching wait."),
    ADMISSION_ADMITTED: (
        "counter", ("tenant",),
        "Requests admitted past the admission controller, by tenant "
        "('-' for untagged traffic)."),
    ADMISSION_REJECTED: (
        "counter", ("tenant", "reason"),
        "Requests rejected AT SUBMIT with an explicit outcome "
        "(queue_full / quota / deadline) instead of unbounded queue "
        "growth."),
    ADMISSION_SHED: (
        "counter", ("tenant", "reason"),
        "Admitted requests shed before device dispatch (expired: the "
        "deadline passed while queued) — load the controller dropped "
        "instead of wasting device time on."),
    ADMISSION_WAIT_ESTIMATE: (
        "gauge", (),
        "Current wait estimate (seconds) the deadline-aware shedding "
        "decision uses: outstanding rows (queued + in flight) x EWMA "
        "per-row service time + the micro-batching deadline."),
    TENANT_REQUESTS: (
        "counter", ("tenant",),
        "Lifetime requests per tenant through the serving layer (only "
        "tenant-tagged submissions produce series)."),
    TENANT_ERRORS: (
        "counter", ("tenant",),
        "Per-tenant requests resolved with an exception (admission "
        "rejections/sheds count separately, not here)."),
    TENANT_REQUEST_LATENCY: (
        "histogram", ("tenant",),
        "Per-tenant arrival-to-result latency (seconds) of ADMITTED "
        "requests — the per-tenant SLO objectives read this."),
    CERTIFIED_QUERIES: (
        "counter", ("selector",),
        "Queries processed by ShardedKNN.search_certified."),
    CERTIFIED_FALLBACKS: (
        "counter", ("selector",),
        "Queries that failed certification and took the widened "
        "re-select fallback."),
    CERTIFIED_GENUINE_MISSES: (
        "counter", ("selector",),
        "Fallbacks where the repair CHANGED the answer (the coarse pass "
        "really missed a neighbor)."),
    CERTIFIED_FALSE_ALARMS: (
        "counter", ("selector",),
        "Fallbacks that reproduced the original answer (the tolerance "
        "cried wolf)."),
    CERTIFIED_HOST_EXACT: (
        "counter", ("selector",),
        "Fallbacks escalated to the unconditional float64 host scan."),
    CERTIFIED_RANK_CORRECTED: (
        "counter", (),
        "Pallas-selector queries whose near-tie runs were re-ranked in "
        "float64."),
    CERTIFIED_QUANT_BOUND: (
        "histogram", (),
        "Per-query int8 certified quantization error bound epsilon "
        "(score units) — the quality signal the int8 coarse pass "
        "computes."),
    TUNING_RESOLVES: (
        "counter", (), "tuning.resolve() invocations."),
    TUNING_CACHE_HITS: (
        "counter", (), "Knob resolutions served from the persisted "
        "winner cache."),
    TUNING_CACHE_MISSES: (
        "counter", (), "Knob resolutions that fell back to defaults."),
    TUNING_SEARCHES: (
        "counter", (), "autotune() runs that actually searched the "
        "grid."),
    TUNING_CANDIDATES_TIMED: (
        "counter", (), "Autotuner candidates built and timed (0 on a "
        "warm cache)."),
    TUNING_GATE_FAILURES: (
        "counter", (), "Autotuner candidates rejected by the bitwise "
        "end-result gate."),
    TUNING_CANDIDATES_PRUNED: (
        "counter", (), "Autotuner candidates skipped before timing by "
        "the roofline-model pruning gate (KNN_TPU_TUNE_PRUNE; every "
        "skip is recorded in the tune entry's pruning provenance)."),
    TUNING_CANDIDATES_VMEM_REFUSED: (
        "counter", (), "Autotuner candidates refused before timing by "
        "the analytic VMEM budget gate (knn_tpu.analysis.vmem): their "
        "estimated per-launch footprint exceeds the device kind's VMEM, "
        "so they would fail at Mosaic compile time; every refusal is "
        "recorded in the tune entry's vmem provenance."),
    PIPELINE_OVERLAP_RATIO: (
        "gauge", (),
        "Fraction of the last certified pipeline-overlap run's wall "
        "time with >= 2 batches in flight (coarse-dispatch start to "
        "result-repair end) — the two-stage coarse/rescore pipeline's "
        "measured dispatch-timeline overlap."),
    JAX_COMPILES: (
        "counter", ("event",),
        "JAX/XLA compile events observed via jax.monitoring."),
    JAX_COMPILE_SECONDS: (
        "counter", ("event",),
        "Cumulative seconds spent in observed JAX/XLA compile events."),
    PHASE_SECONDS: (
        "histogram", ("phase",),
        "PhaseTimer phase durations (seconds), by phase name."),
    SPAN_SECONDS: (
        "histogram", ("span",),
        "Trace span durations (seconds), by span name."),
    EVENTS_DROPPED: (
        "counter", (),
        "Structured events dropped because the JSONL sink raised."),
    SLO_BURN_RATE: (
        "gauge", ("objective", "window"),
        "Error-budget burn rate per SLO objective and evaluation window "
        "(ratio objectives: window error ratio / budget; quantile "
        "objectives: window quantile / threshold, window label 'hist')."),
    SLO_BREACHED: (
        "gauge", ("objective",),
        "1 while the objective's multi-window burn-rate policy is "
        "breached, 0 otherwise (edge transitions emit slo.alert events)."),
    SLO_BREACH_TRANSITIONS: (
        "counter", ("objective",),
        "Healthy-to-breached transitions per objective (each one also "
        "emits exactly one firing slo.alert event)."),
    SLO_EVALUATIONS: (
        "counter", (),
        "SLO engine evaluation passes (each appends one counter sample "
        "to the burn-rate window ring)."),
    HEALTH_READY: (
        "gauge", (),
        "1 when the readiness probe passes (warmup complete, worker "
        "threads live), 0 otherwise; set on every /healthz or report()."),
    POSTMORTEMS_WRITTEN: (
        "counter", ("objective",),
        "Flight-recorder postmortem bundles written to "
        "KNN_TPU_POSTMORTEM_DIR, one per edge-triggered SLO breach "
        "transition, by the objective that fired."),
    ROOFLINE_PCT: (
        "gauge", ("config",),
        "Measured throughput as a fraction of the analytic roofline "
        "ceiling for the labeled config (knn_tpu.obs.roofline)."),
    ROOFLINE_CEILING_QPS: (
        "gauge", ("config",),
        "Predicted roofline ceiling q/s for the labeled config — the "
        "slowest of the HBM / MXU / VPU-select terms at device peaks."),
    ROOFLINE_BOUND: (
        "gauge", ("config", "class"),
        "1 for the config's active bound class (hbm_bound / mxu_bound "
        "/ vpu_select_bound), 0 for the others."),
    ROOFLINE_EVALUATIONS: (
        "counter", (),
        "Roofline attributions published to the registry (autotuner "
        "winners, warm-cache resolves, bench runs)."),
    CALIBRATION_APPLIED: (
        "gauge", ("config",),
        "1 when the labeled config's published roofline block carried "
        "an APPLIED measured-term calibration overlay "
        "(knn_tpu.obs.calibrate), 0 when it rendered analytic-only."),
    CALIBRATION_AGE: (
        "gauge", ("config",),
        "Age (seconds) of the calibration entry applied to the "
        "labeled config — how stale the measured factors are."),
    CALIBRATION_RESIDUAL: (
        "gauge", ("config",),
        "Signed percent by which the ANALYTIC model mispredicted the "
        "measured device time for the labeled config (the reconciled "
        "model_residual_pct) — the calibration-drift signal the "
        "sentinel baselines."),
    CAMPAIGN_ARMS: (
        "counter", ("status",),
        "Measured-ceiling campaign arms completed (cli campaign), by "
        "terminal status (ok / error)."),
    CAMPAIGN_STAGES: (
        "counter", ("stage",),
        "Campaign pipeline stages executed (gates / tune / bench / "
        "capture / reconcile / calibrate / curate), across arms."),
    MERGE_SELECTED: (
        "counter", ("level", "strategy", "source"),
        "Merge-strategy resolutions at placement time, by merge level "
        "(intra = per-host ICI db axis, dcn = cross-host) x chosen "
        "strategy (ring / allgather) x provenance (explicit caller / "
        "env switch / measured crossover table)."),
    MERGE_BYTES: (
        "counter", ("level", "strategy"),
        "Modeled candidate bytes moved by top-k merges "
        "(parallel.crossover.merge_bytes), by level and strategy — "
        "the DCN volume the roofline's dcn term prices."),
    MERGE_STRAGGLER_GAP: (
        "gauge", (),
        "Max-minus-min per-host local search wall time of the last "
        "cross-host merge (parallel.multihost) — the straggler signal "
        "/statusz and doctor attribute."),
    HOSTTIER_SWEEPS: (
        "counter", (),
        "Host-RAM tier segment sweeps executed: one per super-HBM "
        "db segment streamed through the device placement."),
    HOSTTIER_SEGMENT_ROWS: (
        "gauge", (),
        "Padded rows per host-RAM tier segment of the last planned "
        "sweep (every sweep reuses this one compiled shape)."),
    HOSTTIER_SWEEP_SECONDS: (
        "histogram", (),
        "Wall seconds per host-RAM tier sweep (dispatch to fetch of "
        "one segment) — flat across sweeps when the stream overlaps."),
    INDEX_EPOCH: (
        "gauge", (),
        "Current snapshot epoch of the mutable index — bumps once per "
        "compaction swap (knn_tpu.index.mutable)."),
    INDEX_TAIL_ROWS: (
        "gauge", (),
        "Rows currently in the mutable index's delta tail (searched "
        "alongside the main placement; compaction folds them in)."),
    INDEX_TOMBSTONES: (
        "gauge", (),
        "Ids currently tombstoned in the mutable index — masked out of "
        "every merged select under the certify reserve; compaction "
        "drops the rows and resets this."),
    INDEX_COMPACTIONS: (
        "counter", (),
        "Completed compaction cycles (tail merged + tombstones "
        "dropped into a fresh placement, snapshot-swapped in)."),
    INDEX_SWAP_SECONDS: (
        "histogram", (),
        "Seconds the compaction's atomic pointer swap held the index "
        "lock — the only slice of a compaction that can contend with "
        "the serving path (the build/warm runs off it)."),
    AUDIT_SAMPLED: (
        "counter", ("tenant",),
        "Live requests selected by the shadow audit sampler's "
        "trace-id hash (KNN_TPU_AUDIT_RATE), by tenant ('-' for "
        "untagged traffic) — includes records later dropped by the "
        "budget or backlog."),
    AUDIT_REPLAYED: (
        "counter", ("tenant",),
        "Query rows replayed against the f64 exact oracle by the "
        "audit worker, by tenant — the denominator of the "
        "audit_recall SLO objective."),
    AUDIT_DEFICIENT: (
        "counter", ("tenant",),
        "Audited query rows whose served neighbors missed the exact "
        "top-k (recall@k < 1), by tenant — the numerator of the "
        "audit_recall SLO objective."),
    AUDIT_DROPPED: (
        "counter", ("reason",),
        "Sampled audit records dropped WITHOUT replay, by reason "
        "(budget: over the KNN_TPU_AUDIT_BUDGET_ROWS_S token bucket; "
        "queue_full: the bounded replay backlog; error: the oracle "
        "replay raised) — a silent drop would read as a healthy "
        "audit."),
    AUDIT_ROWS_SCORED: (
        "counter", (),
        "Oracle rows scanned by completed audit replays — the spend "
        "the row budget meters."),
    AUDIT_RECALL: (
        "histogram", ("tenant",),
        "Per-audited-query recall@k of the served answer against the "
        "f64 exact oracle (1.0 = the exact set, tie-tolerant), by "
        "tenant."),
    AUDIT_RANK_DISPLACEMENT: (
        "histogram", ("tenant",),
        "Per-served-neighbor displacement from its exact oracle rank "
        "(0 = served in its true position), by tenant."),
    AUDIT_DISTANCE_ERROR: (
        "histogram", ("tenant",),
        "Relative error of each served distance against its own f64 "
        "recompute — arithmetic drift, independent of ranking."),
    CERTIFIED_MARGIN: (
        "histogram", ("path",),
        "Per-certified-query relative margin between the k-th result "
        "distance and the exclusion bound that certified it, by "
        "certification path (sharded / ivf).  Margins crowding 0 are "
        "the leading indicator that fallback rate is about to grow."),
    IVF_FALLBACK_RATE: (
        "gauge", ("selector",),
        "Fraction of the last IVF search's queries that failed the "
        "probe-pruning certificate and fell back to wider scans."),
    IVF_RECALL_AT_K: (
        "gauge", ("selector",),
        "Measured recall@k of the last IVF search against its own "
        "exact rescore (1.0 when every certificate held)."),
    IVF_PROBE_FRACTION: (
        "gauge", ("selector",),
        "Fraction of trained IVF lists probed by the last search — "
        "the pruning the tier exists to deliver."),
    IVF_BYTES_STREAMED_RATIO: (
        "gauge", ("selector",),
        "Bytes streamed by the last IVF search as a fraction of the "
        "brute-force full-corpus stream."),
    DRIFT_NORM_PSI: (
        "gauge", (),
        "Population-stability index of the live query-norm histogram "
        "against the train-time baseline (0 = identical; > 0.2 "
        "investigate, > 0.5 act)."),
    DRIFT_ASSIGN_PSI: (
        "gauge", (),
        "Population-stability index of the live IVF "
        "centroid-assignment histogram against the k-means training "
        "assignment counts."),
    DRIFT_QUERIES: (
        "counter", (),
        "Query rows folded into the drift sketches."),
    INDEX_LIST_IMBALANCE: (
        "gauge", (),
        "Max/mean trained IVF list size of the current snapshot "
        "(1.0 = perfectly balanced; growth concentrates probe cost)."),
    INDEX_TAIL_FRACTION: (
        "gauge", (),
        "Fraction of all index rows sitting in the unindexed delta "
        "tail — the slice every search brute-forces until "
        "compaction."),
    INDEX_TOMBSTONE_DENSITY: (
        "gauge", (),
        "Fraction of all index rows tombstoned — dead bytes diluting "
        "every stream until compaction drops them."),
    FLEET_MEMBERS: (
        "gauge", (),
        "Members the last fleet collection merged (knn_tpu.obs.fleet) "
        "— live endpoints reached or snapshot files read."),
    FLEET_UNREACHABLE: (
        "gauge", (),
        "Members the last fleet collection could NOT merge "
        "(unreachable endpoint, torn/unreadable snapshot, or "
        "catalog-version skew) — nonzero marks the report partial."),
    FLEET_MERGE_STALENESS: (
        "gauge", (),
        "Spread (seconds) between the oldest and newest member "
        "snapshot the last fleet collection merged — how far apart in "
        "time the merged numbers are."),
    FLEET_STRAGGLER_HOST: (
        "gauge", ("host",),
        "1 on the member whose per-host DCN-merge wall time was the "
        "fleet maximum in the last collection (the named straggler), "
        "0 on the others."),
}
