"""JAX compile-event hook: count + seconds per compile, via
``jax.monitoring``.

JAX reports named durations (``/jax/core/compile`` and friends) through
``jax.monitoring.record_event_duration_secs``; registering a listener is
the supported way to observe every XLA compile in the process — inline
jit compiles, AOT ``lower().compile()`` calls, and cache lookups alike —
without wrapping any call site.  The listener filters for event keys
containing ``compile`` and mirrors them into
``knn_tpu_jax_compiles_total`` / ``knn_tpu_jax_compile_seconds_total``,
labeled by the sanitized event key (a small, version-bounded set).

:func:`install_compile_hook` is idempotent and safe to call from every
instrumented entry point (engine construction, ``run_job``, the bench);
it no-ops when the subsystem is disabled or the monitoring API is
absent (older jaxlibs), so no caller needs a guard.
"""

from __future__ import annotations

import re
import threading

from knn_tpu.obs import names, registry

_lock = threading.Lock()
_installed = False

_SANITIZE = re.compile(r"[^a-z0-9_]+")


def _event_label(key: str) -> str:
    return _SANITIZE.sub("_", key.lower()).strip("_")


def _on_duration(event: str, duration: float, **_kw) -> None:
    # **_kw: newer jax versions pass extra keyword context; ignore it
    if "compile" not in event:
        return
    try:
        label = _event_label(event)
        registry.counter(names.JAX_COMPILES, event=label).inc()
        registry.counter(
            names.JAX_COMPILE_SECONDS, event=label).inc(float(duration))
    except Exception:  # noqa: BLE001 - a hook must never break compiles
        pass


def install_compile_hook() -> bool:
    """Register the listener once per process; returns whether the hook
    is (now) active."""
    global _installed
    if not registry.enabled():
        return False
    with _lock:
        if _installed:
            return True
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_duration)
        except Exception:  # noqa: BLE001 - older jax: no monitoring API
            return False
        _installed = True
        return True
