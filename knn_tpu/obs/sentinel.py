"""Noise-aware perf-regression sentinel over the bench history.

The repo accumulates one measured line per config per round
(``TPU_BENCH_r*.jsonl`` curated artifacts, ``BENCH_r*.json`` driver
records).  This module turns that history into a ROBUST baseline per
curated metric — median + MAD (median absolute deviation), the
estimator pair that one outlier round cannot drag — and classifies a
fresh measurement against it:

- ``ok``       within historical jitter (<= max(2·σ_rel, 2%) below the
               median, where σ = 1.4826·MAD, the normal-consistent
               robust sigma), or faster than baseline;
- ``warn``     between the jitter band and the regression bar;
- ``regress``  >= max(4·σ_rel, 10%) below the median — an effect no
               plausible run-to-run noise explains;
- ``no_baseline``  fewer than MIN_SAMPLES comparable historical points.

Both bars are CAPPED (OK_CEIL / REGRESS_CEIL): however scattered the
history, a 40% drop is always a regression — wide MAD must not grant
unlimited absolution.

Baseline hygiene (the part that makes the verdict trustworthy):

- **stale guard**: lines the artifact refresher marked ``stale`` (a
  republished earlier-round number) NEVER enter a baseline — a stale
  line is the same measurement again, and double-counting it both
  shrinks the MAD dishonestly and double-weights one round;
- **commit dedupe**: two lines carrying the same ``measured_at_commit``
  and the same value are one measurement republished, not two
  observations (the pre-provenance curation did exactly this), so they
  count once;
- **like-for-like keys**: baselines key on (metric, backend, precision
  family) — a CPU-fallback line must never enter (or be judged
  against) a TPU baseline, and an int8 A/B line never the f32-family
  one (the same separation the artifact refresher curates by).

Everything here is jax-free and file-format tolerant: a malformed line
is skipped, never fatal — the sentinel rides inside ``bench.py``'s
one-JSON-line contract and must not be able to kill it.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

#: minimum comparable history points before a verdict is rendered
MIN_SAMPLES = 3

#: relative drop always inside jitter (measurement floor)
OK_FLOOR = 0.02

#: relative drop always a regression, however noisy the history
REGRESS_FLOOR = 0.10

#: jitter band: ok while drop <= OK_SIGMAS * sigma_rel
OK_SIGMAS = 2.0

#: regression bar: regress once drop >= REGRESS_SIGMAS * sigma_rel
REGRESS_SIGMAS = 4.0

#: noise ceilings: however scattered the history, a drop past
#: REGRESS_CEIL is always a regression (and past OK_CEIL never plain
#: ok) — wide MAD must not grant unlimited absolution
OK_CEIL = 0.25
REGRESS_CEIL = 0.40

#: normal-consistency constant: sigma = MAD_SCALE * MAD
MAD_SCALE = 1.4826

def _curated_fields() -> Tuple[Tuple[str, str], ...]:
    from knn_tpu.analysis.artifacts import curated_fields

    return curated_fields()


#: the curated fields a baseline tracks, with their good direction —
#: DERIVED from the artifact-schema catalog (knn_tpu.analysis.
#: artifacts: each block's schema declares its curated contribution;
#: the hand-maintained list is gone, and the ``artifact-lockstep``
#: checker fails the lint if this derivation is ever removed).
#: ``roofline_pct`` is the model-anchored family: where the raw-qps
#: fields judge a line against its own HISTORY, percent-of-roofline
#: judges it against the hardware ceiling the cost model predicts for
#: its exact config (knn_tpu.obs.roofline) — a geometry change that
#: legitimately lowers qps but holds its roofline fraction reads ok,
#: and a same-config run that slides down the ceiling reads as the
#: regression it is.  ``knee_qps`` (loadgen) is higher-is-better like
#: the throughput family; ``model_residual_pct`` (calibration drift)
#: and ``mutation_admitted_p99_ms`` (the live-mutation serving tail)
#: judge lower-is-better — curated_value() takes the residual's abs so
#: a sign flip around zero never reads as an improvement.
CURATED_FIELDS: Tuple[Tuple[str, str], ...] = _curated_fields()


def curated_value(rec: dict, fname: str):
    """One curated field off a history line: top-level first (bench
    hoists ``roofline_pct``/``knee_qps``/``device_phase_qps`` there),
    falling back into the line's ``roofline``/``loadgen_knee`` block —
    or, for ``device_phase_qps``, the winning selector's
    ``phase_breakdown.device_qps`` — for lines curated before the
    hoist (bench hoisted the device rate only off certified_pallas
    wins until the winning-mode hoist)."""
    v = rec.get(fname)
    if v is None and fname == "roofline_pct":
        block = rec.get("roofline")
        if isinstance(block, dict):
            v = block.get("roofline_pct")
    if v is None and fname == "knee_qps":
        block = rec.get("loadgen_knee")
        if isinstance(block, dict):
            v = block.get("knee_qps")
    if v is None and fname == "mutation_admitted_p99_ms":
        block = rec.get("mutation")
        if isinstance(block, dict):
            v = block.get("admitted_p99_ms")
    if v is None and fname == "device_phase_qps":
        sel = rec.get("selectors")
        if isinstance(sel, dict):
            entry = sel.get(rec.get("mode"))
            if isinstance(entry, dict):
                pb = entry.get("phase_breakdown")
                if isinstance(pb, dict):
                    v = pb.get("device_qps")
    if fname == "model_residual_pct":
        if v is None:
            block = rec.get("roofline")
            if isinstance(block, dict):
                cal = block.get("calibration")
                if isinstance(cal, dict):
                    v = cal.get("model_residual_pct")
        # drift magnitude: the residual is signed, the baseline judges
        # how FAR from zero the model sits either way
        if isinstance(v, (int, float)):
            v = abs(v)
    return v

#: verdict severity order (worst wins the overall verdict)
_SEVERITY = {"regress": 3, "warn": 2, "ok": 1, "no_baseline": 0}

_ROUND_RE = re.compile(r"_r(\d+)\.(?:jsonl|json)$")


def _file_round(path: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def baseline_key(rec: dict) -> Optional[str]:
    """(metric, backend, precision family) — like-for-like
    comparability.  Precision collapses to int8-vs-everything-else,
    mirroring the artifact refresher's curation split: int8 is
    different arithmetic and curates under its own key, while the
    f32-family precisions (f32 / bf16x3 / absent on pre-provenance
    history) are one comparable lineage."""
    metric = rec.get("metric")
    if not metric:
        return None
    backend = rec.get("backend") or "unknown"
    precision = "int8" if rec.get("precision") == "int8" else "default"
    return f"{metric}|{backend}|{precision}"


def iter_history_lines(repo_dir: str,
                       max_round: Optional[int] = None) -> Iterable[dict]:
    """Every parseable measurement record in the repo's bench history:
    curated ``TPU_BENCH_r*.jsonl`` lines plus the ``BENCH_r*.json``
    driver records' parsed/tail line.  ``max_round`` bounds the history
    to rounds STRICTLY BELOW it (so a round's own lines never seed the
    baseline they are judged against)."""
    for path in sorted(glob.glob(
            os.path.join(repo_dir, "TPU_BENCH_r*.jsonl"))):
        rnd = _file_round(path)
        if max_round is not None and (rnd is None or rnd >= max_round):
            continue
        try:
            lines = open(path).read().splitlines()
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                rec.setdefault("_source", os.path.basename(path))
                if rnd is not None:
                    rec.setdefault("measured_round", rnd)
                yield rec
    for path in sorted(glob.glob(os.path.join(repo_dir, "BENCH_r*.json"))):
        rnd = _file_round(path)
        if max_round is not None and (rnd is None or rnd >= max_round):
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        rec = doc.get("parsed")
        if not isinstance(rec, dict) or rec.get("value") is None:
            # fall back to the last JSON line embedded in the tail
            rec = None
            for line in str(doc.get("tail", "")).splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        cand = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(cand, dict) and cand.get("metric"):
                        rec = cand
        if isinstance(rec, dict):
            rec.setdefault("_source", os.path.basename(path))
            if rnd is not None:
                rec.setdefault("measured_round", rnd)
            yield rec


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def build_baselines(records: Iterable[dict],
                    min_samples: int = MIN_SAMPLES) -> dict:
    """``{baseline_key: {field: {median, mad, sigma, n, values}}}`` from
    the history, applying the stale guard and commit dedupe."""
    # key -> field -> {(commit, value) seen} and value list
    acc: Dict[str, Dict[str, dict]] = {}
    for rec in records:
        if not isinstance(rec, dict):
            continue
        if rec.get("stale") is True:
            continue  # republished number: never a fresh observation
        key = baseline_key(rec)
        if key is None or rec.get("value") is None:
            continue
        commit = rec.get("measured_at_commit")
        for fname, _direction in CURATED_FIELDS:
            v = curated_value(rec, fname)
            if not isinstance(v, (int, float)):
                continue
            slot = acc.setdefault(key, {}).setdefault(
                fname, {"values": [], "seen": set()})
            if commit and commit != "unknown(pre-provenance)":
                dedupe = (commit, float(v))
                if dedupe in slot["seen"]:
                    continue  # same measurement republished
                slot["seen"].add(dedupe)
            slot["values"].append(float(v))
    out: Dict[str, Dict[str, dict]] = {}
    for key, fields in acc.items():
        for fname, slot in fields.items():
            vals = slot["values"]
            if len(vals) < min_samples:
                continue
            med = _median(vals)
            mad = _median([abs(v - med) for v in vals])
            out.setdefault(key, {})[fname] = {
                "median": round(med, 4),
                "mad": round(mad, 4),
                "sigma": round(MAD_SCALE * mad, 4),
                "n": len(vals),
                "values": [round(v, 4) for v in sorted(vals)],
            }
    return out


def classify(value: float, base: dict, direction: str = "higher") -> dict:
    """One field's verdict against its baseline stats (see module
    docstring for the thresholds)."""
    med = base["median"]
    sigma = base["sigma"]
    if med == 0:
        return {"verdict": "no_baseline",
                "reason": "degenerate baseline (median 0)"}
    if direction == "higher":
        drop = (med - value) / abs(med)
    else:
        drop = (value - med) / abs(med)
    sigma_rel = sigma / abs(med)
    ok_bar = min(max(OK_SIGMAS * sigma_rel, OK_FLOOR), OK_CEIL)
    regress_bar = min(max(REGRESS_SIGMAS * sigma_rel, REGRESS_FLOOR),
                      REGRESS_CEIL)
    if drop <= ok_bar:
        verdict = "ok"
    elif drop >= regress_bar:
        verdict = "regress"
    else:
        verdict = "warn"
    return {
        "verdict": verdict,
        "value": round(float(value), 4),
        "baseline_median": med,
        "baseline_sigma": sigma,
        "baseline_n": base["n"],
        "drop_rel": round(drop, 4),
        # effect size in robust sigmas (None when the history was
        # perfectly tight — any drop is then infinitely surprising and
        # the relative floors carry the judgment alone)
        "effect_sigmas": (round(drop / sigma_rel, 2)
                          if sigma_rel > 0 else None),
        "ok_bar": round(ok_bar, 4),
        "regress_bar": round(regress_bar, 4),
    }


def verdict_for_line(rec: dict, repo_dir: Optional[str] = None,
                     baselines: Optional[dict] = None) -> dict:
    """The ``sentinel`` block a bench line carries: per curated field a
    classification, plus the overall (worst) verdict.  Either pass
    prebuilt ``baselines`` or a ``repo_dir`` to read history from."""
    if baselines is None:
        if repo_dir is None:
            raise ValueError("need repo_dir or baselines")
        baselines = build_baselines(iter_history_lines(repo_dir))
    key = baseline_key(rec)
    fields: Dict[str, dict] = {}
    overall = "no_baseline"
    base_fields = baselines.get(key, {}) if key else {}
    for fname, direction in CURATED_FIELDS:
        v = curated_value(rec, fname)
        if not isinstance(v, (int, float)):
            continue
        base = base_fields.get(fname)
        if base is None:
            fields[fname] = {"verdict": "no_baseline",
                             "reason": f"< {MIN_SAMPLES} comparable "
                                       f"history points"}
        else:
            fields[fname] = classify(float(v), base, direction)
        if _SEVERITY[fields[fname]["verdict"]] > _SEVERITY[overall]:
            overall = fields[fname]["verdict"]
    return {"verdict": overall, "baseline_key": key, "fields": fields}
