"""Measured-term calibration: reconcile device time against the
roofline model's terms and persist per-term scale factors the model
consults — MODEL_VERSION 3's measured half (ROADMAP open item 1).

The analytic model (:mod:`knn_tpu.obs.roofline`) predicts per-sweep
term times ``t_hbm``/``t_mxu``/``t_vpu`` from spec-sheet peaks.  The
thesis of TPU-KNN (arXiv:2206.14286) is only falsifiable when those
terms can be DECOMPOSED against measured kernel time — the PANDA-style
discipline (arXiv:1607.08220) of fitting cost-model constants to
measurement instead of assuming them.  This module is that loop:

- :func:`reconcile` takes one modeled block plus one measured sample
  (:mod:`knn_tpu.obs.traceread`: a device-trace busy time or a
  host-phase ``device_s``) and solves for per-term scale factors.
  The BINDING term absorbs the residual (the other terms are hidden
  under it in the combined-time formula, so the measurement carries no
  information about them — attributing their share would be
  fabrication); when no bound-term factor inside the sane clamp can
  reproduce the measurement, every term scales uniformly and the entry
  says so (``method: "uniform"``).  Either way the calibrated combined
  time REPRODUCES the measured device time by construction, so the
  calibrated ceiling equals the measured q/s up to arithmetic —
  ``model_residual_pct`` records how far the ANALYTIC model was off.
- Factors persist to a calibration store — ``KNN_TPU_CALIBRATION``
  JSON, atomic tmp+rename writes, mtime-memoized reads: the tune-cache
  discipline — keyed by
  ``device_kind|n|d|k|selector:precision:kernel|cal<MODEL_VERSION>``.  The
  trailing version token means a calibration fit under an older model's
  terms SELF-INVALIDATES (misses on lookup) instead of scaling terms it
  was never fit against, exactly like ``|rl``/``|kv`` in the tune
  cache key.
- :mod:`knn_tpu.obs.roofline` consults the overlay on every block
  (lazily, through :func:`lookup_for_block`): blocks gain
  ``calibration: {applied, factors, source, age_s, …}`` and a
  calibrated ``ceiling_qps`` beside ``ceiling_qps_analytic``.

Full provenance rides every entry (device_kind, shape key, config
label, commit, round, source ``device_trace``/``host_phase``) so a
curated artifact can say not just *that* the ceiling was calibrated
but *from which measurement*.  Everything here is jax-free.
Derivation + campaign runbook: docs/PERF.md "Calibration & measured
ceilings".
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Dict, Iterable, List, Optional

from knn_tpu.obs import names, registry, trace

#: env switch: path of the calibration store JSON; unset = no overlay
#: (every roofline block renders ``calibration: {applied: false}``)
CAL_ENV = "KNN_TPU_CALIBRATION"

#: store file schema version (guards future migrations, like the tune
#: cache's ``version`` field)
STORE_VERSION = 1

#: the model terms a factor can scale, in roofline term order
TERMS = ("hbm", "mxu", "vpu_select")

_TERM_OF_BOUND = {"hbm_bound": "hbm", "mxu_bound": "mxu",
                  "vpu_select_bound": "vpu_select"}

#: sane clamp on a single term's scale factor: outside it the
#: measurement is telling us something no per-term rate error explains
#: (wrong shape key, torn trace) and reconcile refuses loudly.  The
#: ceiling is deliberately generous — the CPU rehearsal reconciles an
#: INTERPRET-mode kernel against compiled-CPU generic peaks, which
#: legitimately sits 10-100x under the analytic terms
FACTOR_MIN, FACTOR_MAX = 1e-3, 1e4

#: stated tolerance (percent) between a calibrated ceiling and the
#: measured qps it was fit from — the campaign's acceptance gate; the
#: reconstruction is exact up to rounding, so this bound is generous
RESIDUAL_TOLERANCE_PCT = 2.0

#: measured-sample sources (traceread vocabulary)
SOURCES = ("device_trace", "host_phase")

_lock = threading.Lock()
#: path -> ((mtime_ns, size), entries) read memo (tune-cache pattern)
_read_memo: dict = {}


def store_path() -> Optional[str]:
    """The calibration store file, or None when ``KNN_TPU_CALIBRATION``
    is unset (no overlay — the analytic model stands alone)."""
    return os.environ.get(CAL_ENV) or None


def model_token() -> str:
    """``cal<MODEL_VERSION>`` — the version token baked into every
    store key: factors are a fit AGAINST one model version's terms, so
    when the model changes the persisted entry's key no longer matches
    and lookups fall back to analytic cleanly (the ``|rl``/``|kv``
    self-invalidation mechanism of the tune cache)."""
    from knn_tpu.obs.roofline import MODEL_VERSION

    return f"cal{MODEL_VERSION}"


def calibration_key(device_kind: Optional[str], n: int, d: int, k: int,
                    selector: str, precision: Optional[str],
                    kernel: Optional[str] = None) -> str:
    """The shape key one calibration is valid for — the tune-cache key
    discipline: any field mismatch MUST miss (a factor fit on one
    (kind, shape, precision, kernel) point says nothing about another —
    in particular, a campaign's tiled/streaming/fused arms at the SAME
    shape measure different machines and must never share an entry)."""
    kind = device_kind or "generic-cpu"
    kern = f":{kernel}" if kernel else ""
    return (f"{kind}|n{int(n)}|d{int(d)}|k{int(k)}|"
            f"{selector}:{precision or 'default'}{kern}|{model_token()}")


def key_for_block(block: dict) -> Optional[str]:
    """The store key a roofline block looks itself up under (from its
    own ``config``/``selector`` fields), or None when the block doesn't
    carry enough shape to key on."""
    cfg = block.get("config")
    sel = block.get("selector")
    if not isinstance(cfg, dict) or not sel:
        return None
    try:
        precision = (cfg.get("precision") if sel == "pallas"
                     else cfg.get("dtype"))
        return calibration_key(block.get("device_kind"), cfg["n"],
                               cfg["d"], cfg["k"], sel, precision,
                               kernel=(cfg.get("kernel")
                                       if sel == "pallas" else None))
    except (KeyError, TypeError, ValueError):
        return None


def load(path: Optional[str] = None) -> dict:
    """All store entries (empty when the file is absent/corrupt — a
    broken overlay degrades to the analytic model, never to an
    error)."""
    path = path or store_path()
    if not path:
        return {}
    try:
        st = os.stat(path)
    except OSError:
        return {}
    sig = (st.st_mtime_ns, st.st_size)
    with _lock:
        memo = _read_memo.get(path)
        if memo and memo[0] == sig:
            return memo[1]
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict) or \
                data.get("version") != STORE_VERSION:
            return {}
        entries = data.get("entries", {})
        if not isinstance(entries, dict):
            return {}
    except (OSError, json.JSONDecodeError):
        return {}
    with _lock:
        _read_memo[path] = (sig, entries)
    return entries


def get(key: str, path: Optional[str] = None) -> Optional[dict]:
    entry = load(path).get(key)
    return entry if isinstance(entry, dict) else None


def put(key: str, entry: dict, path: Optional[str] = None) -> str:
    """Insert/replace one entry; atomic write (tmp + rename).  Returns
    the path written.  Raises ValueError when no store path is
    configured — persisting a calibration nowhere is a caller bug, not
    a degradable condition."""
    path = path or store_path()
    if not path:
        raise ValueError(
            f"no calibration store configured (set {CAL_ENV} or pass "
            f"an explicit path)")
    with _lock:
        entries = {}
        try:
            with open(path) as f:
                data = json.load(f)
            if (isinstance(data, dict)
                    and data.get("version") == STORE_VERSION
                    and isinstance(data.get("entries"), dict)):
                entries = data["entries"]
        except (OSError, json.JSONDecodeError):
            pass
        prev = entries.get(key)
        if isinstance(prev, dict):
            entry = dict(entry,
                         samples=int(prev.get("samples", 1)) + 1)
        entries[key] = entry
        payload = {"version": STORE_VERSION, "entries": entries}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        _read_memo.pop(path, None)
    return path


def _combined_time(times: Dict[str, float],
                   select_overlapped: bool) -> float:
    """The roofline's combined-time formula over per-term times
    (``{hbm, mxu, vpu_select}`` keys).  Delegates to the ONE formula
    the ceiling itself uses (:func:`roofline._combined`) — the
    reconciler's factors are only sound when it solves against exactly
    that combination, so a second copy here would drift the moment a
    model version changes it."""
    from knn_tpu.obs.roofline import _combined

    return _combined({"hbm_bound": times["hbm"],
                      "mxu_bound": times["mxu"],
                      "vpu_select_bound": times["vpu_select"]},
                     select_overlapped)


def reconcile(block: dict, measured: dict, *,
              provenance: Optional[dict] = None) -> dict:
    """Decompose one measured device time against one modeled block's
    terms (module docstring for the solving discipline).  Returns the
    store entry: per-term ``factors`` + ``term_residual_pct``, the
    signed ``model_residual_pct`` the analytic model was off by, the
    measured sample's provenance, and the fit ``method``."""
    src = measured.get("source")
    if src not in SOURCES:
        raise ValueError(f"measured source {src!r} not in {SOURCES}")
    dev_s = measured.get("device_s")
    m_nq = measured.get("nq")
    if not isinstance(dev_s, (int, float)) or dev_s <= 0:
        raise ValueError(f"measured device_s {dev_s!r} must be > 0")
    if not isinstance(m_nq, int) or m_nq <= 0:
        raise ValueError(f"measured nq {m_nq!r} must be a positive int")
    terms = block.get("terms")
    cfg = block.get("config") or {}
    if not isinstance(terms, dict) or \
            block.get("bound_class") not in _TERM_OF_BOUND:
        raise ValueError("block is not a roofline model "
                         "(missing terms/bound_class)")
    times = {t: float(terms[t]["time_s"]) for t in TERMS}
    if any(v <= 0 for v in times.values()):
        raise ValueError(f"non-positive modeled term time: {times}")
    # attribute against the ANALYTIC binding term, re-derived from the
    # raw term times (a block that already consulted an earlier overlay
    # carries the CALIBRATED bound_class — fitting against that would
    # compound factors across rounds instead of re-fitting the model)
    bound = max(_TERM_OF_BOUND,
                key=lambda c: (times[_TERM_OF_BOUND[c]],
                               -list(_TERM_OF_BOUND).index(c)))
    overlapped = bool(block.get("select_overlapped"))
    nq_model = int(cfg.get("nq") or m_nq)
    # normalize the measurement to the model's sweep size
    measured_t = float(dev_s) * (nq_model / m_nq)
    modeled_t = _combined_time(times, overlapped)
    scale = measured_t / modeled_t
    if not (FACTOR_MIN <= scale <= FACTOR_MAX):
        raise ValueError(
            f"measured/modeled ratio {scale:.4g} outside the sane "
            f"clamp [{FACTOR_MIN}, {FACTOR_MAX}] — wrong shape key or "
            f"torn measurement, refusing to calibrate")
    bterm = _TERM_OF_BOUND[bound]
    factors = {t: 1.0 for t in TERMS}
    # solve the combined-time formula for the bound term's factor with
    # the hidden terms held at 1.0
    if overlapped:
        f_b = measured_t / times[bterm]
        solvable = f_b * times[bterm] >= max(
            v for t, v in times.items() if t != bterm)
    else:
        if bterm == "vpu_select":
            f_b = (measured_t - max(times["hbm"], times["mxu"])) \
                / times["vpu_select"]
            solvable = f_b > 0
        else:
            f_b = (measured_t - times["vpu_select"]) / times[bterm]
            other = "mxu" if bterm == "hbm" else "hbm"
            solvable = f_b > 0 and f_b * times[bterm] >= times[other]
    if solvable and FACTOR_MIN <= f_b <= FACTOR_MAX:
        factors[bterm] = f_b
        method = "bound_term"
    else:
        # the measurement sits where no single-term factor can put it
        # (e.g. measured under a hidden term): scale everything
        factors = {t: scale for t in TERMS}
        method = "uniform"
    cal_times = {t: times[t] * factors[t] for t in TERMS}
    cal_t = _combined_time(cal_times, overlapped)
    entry = {
        # 9 decimals: a uniform CPU-rehearsal factor can sit at 1e-3,
        # where 6-decimal rounding would visibly move the calibrated
        # ceiling away from the measurement it must reproduce
        "factors": {t: round(f, 9) for t, f in factors.items()},
        "method": method,
        "bound_class": bound,
        "select_overlapped": overlapped,
        "model_residual_pct": round((scale - 1.0) * 100.0, 2),
        "term_residual_pct": {
            t: round((factors[t] - 1.0) * 100.0, 2) for t in TERMS},
        "measured_qps": round(nq_model / measured_t, 2),
        "analytic_ceiling_qps": block.get("ceiling_qps_analytic")
        or block.get("ceiling_qps"),
        "calibrated_ceiling_qps": round(nq_model / cal_t, 1),
        "source": src,
        "model_version": block.get("model_version"),
        "samples": 1,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
        "measured_at_unix": round(time.time(), 3),
        "provenance": {
            "device_kind": block.get("device_kind"),
            "shape_key": key_for_block(block),
            "nq_model": nq_model, "nq_measured": m_nq,
            "device_s": round(float(dev_s), 6),
            **(provenance or {}),
        },
    }
    return entry


def apply_to_times(times: Dict[str, float],
                   factors: Dict[str, float]) -> Dict[str, float]:
    """Calibrated per-term times (missing factors default to 1.0)."""
    return {t: float(times[t]) * float(factors.get(t, 1.0))
            for t in times}


def entry_age_s(entry: dict) -> Optional[float]:
    ts = entry.get("measured_at_unix")
    if not isinstance(ts, (int, float)):
        return None
    return max(0.0, round(time.time() - float(ts), 1))


def lookup_for_block(block: dict,
                     path: Optional[str] = None) -> Optional[dict]:
    """The store entry covering this block's shape key, or None (no
    store configured, no entry, stale model token)."""
    key = key_for_block(block)
    if key is None:
        return None
    return get(key, path)


def publish(label: str, cal: dict) -> None:
    """Export one block's calibration verdict to the metrics registry
    (obs-gated, like every exporter): applied flag, entry age, and the
    analytic model's residual — the drift signal the sentinel's
    ``model_residual_pct`` baseline watches."""
    if not registry.enabled():
        return
    applied = bool(cal.get("applied"))
    registry.gauge(names.CALIBRATION_APPLIED, config=label).set(
        1.0 if applied else 0.0)
    if not applied:
        return
    age = cal.get("age_s")
    if isinstance(age, (int, float)):
        registry.gauge(names.CALIBRATION_AGE, config=label).set(
            float(age))
    res = cal.get("model_residual_pct")
    if isinstance(res, (int, float)):
        registry.gauge(names.CALIBRATION_RESIDUAL, config=label).set(
            float(res))
    trace.emit_event("calibration.publish", config=label,
                     source=cal.get("source"),
                     model_residual_pct=res)


def status() -> dict:
    """The /statusz ``calibration`` section: store location, entry
    count, and the worst per-term residual on file — the one-line
    answer to "is this process's roofline calibrated, and how wrong
    was the analytic model?"."""
    path = store_path()
    out: dict = {"store": path, "exists": False, "entries": 0,
                 "model_token": model_token(),
                 "worst_residual_pct": None}
    if not path:
        return out
    out["exists"] = os.path.exists(path)
    entries = load(path)
    # only entries fit against the CURRENT model version count — a
    # stale-token entry will never be applied, so reporting its
    # residual as live calibration state would overstate coverage
    live = {k: v for k, v in entries.items()
            if k.endswith(f"|{model_token()}") and isinstance(v, dict)}
    out["entries"] = len(live)
    worst = None
    worst_key = None
    for key, e in live.items():
        for t, pct in (e.get("term_residual_pct") or {}).items():
            if isinstance(pct, (int, float)) and (
                    worst is None or abs(pct) > abs(worst)):
                worst, worst_key = pct, f"{key}:{t}"
    out["worst_residual_pct"] = worst
    out["worst_residual_key"] = worst_key
    return out


def validate_calibration(cal) -> List[str]:
    """Structural validation of a block's ``calibration`` field (the
    refresher refuses malformed ones; ``perf_sentinel --lint`` sweeps
    history with this).  Returns error strings, empty when
    well-formed.  An absent overlay must still be EXPLICIT: the field
    is a dict with ``applied: false``, never missing-and-implied.
    A compat shim over the artifact-schema catalog
    (:mod:`knn_tpu.analysis.artifacts`, the ``calibration`` entry):
    the engine's canonical phrasing is normalized, this entry point
    keeps the historical strings so postmortem/doctor renderings stay
    stable."""
    from knn_tpu.analysis.artifacts import validate

    return validate("calibration", cal, style="legacy")


def validate_campaign_block(block) -> List[str]:
    """Structural validation of a bench/curated line's ``campaign``
    block (written by ``cli campaign``) — the refusal surface
    ``refresh_bench_artifacts.py`` applies so a malformed campaign
    artifact can never enter the curated history.  A compat shim over
    the artifact-schema catalog (the ``campaign`` entry), historical
    strings preserved like :func:`validate_calibration`."""
    from knn_tpu.analysis.artifacts import validate

    return validate("campaign", block, style="legacy")


def reset() -> None:
    """Drop the read memo (test isolation)."""
    with _lock:
        _read_memo.clear()
