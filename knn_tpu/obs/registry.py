"""Process-wide, thread-safe metrics registry.

Three instrument kinds (ops.metrics is the DISTANCE metric table; this
module is the observability one):

- :class:`Counter` — monotone float (float so second-counters like
  ``knn_tpu_jax_compile_seconds_total`` fit the same type),
- :class:`Gauge` — settable level,
- :class:`Histogram` — lifetime count/sum/min/max plus a BOUNDED sample
  window feeding p50/p95/p99 (a long-running service must not grow a
  per-observation list forever; the window percentiles are the
  operationally useful number, exactly serving.latency_summary's
  argument).

Every name must come from the catalog (knn_tpu.obs.names.CATALOG) with
matching label names — undocumented metrics are unregisterable by
construction, which is what lets ``scripts/lint_metric_names.py`` prove
the docs/OBSERVABILITY.md catalog complete.

Disabled mode (``KNN_TPU_OBS=0``): :func:`get_registry` returns a
no-op registry whose ``counter``/``gauge``/``histogram`` hand back ONE
shared do-nothing instrument — no allocation, no locking, no state —
so instrumented hot paths cost a dict-free method call and nothing
else, and results stay bitwise identical either way (instrumentation
never touches numerics; tests/test_obs.py pins both properties).
"""

from __future__ import annotations

import bisect
import os
import re
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

from knn_tpu.obs.names import CATALOG

#: the shape every registrable metric name must have (also enforced by
#: scripts/lint_metric_names.py over the catalog itself)
NAME_RE = re.compile(r"^knn_tpu_[a-z0-9_]+$")

#: env switch: "0"/"false"/"off" disables the whole subsystem (default on)
OBS_ENV = "KNN_TPU_OBS"

#: bounded histogram window (samples per labeled series)
DEFAULT_WINDOW = 4096

#: fixed log-spaced histogram bucket upper bounds, 4 per decade over
#: 1e-6..1e4 (covers microsecond latencies through multi-kilosecond
#: walls and the quant-bound epsilons).  FIXED — same bounds in every
#: process — is the whole point: cumulative counts over identical
#: bounds add across hosts, so fleet quantiles can be computed from the
#: merged distribution instead of unsoundly averaging per-host
#: percentiles (knn_tpu.obs.fleet).  An observation past the last
#: bound lands in the implicit +Inf overflow slot.
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    round(10.0 ** (-6 + i / 4.0), 10) for i in range(41))

#: worst-recent exemplars retained per histogram series (trace ids of
#: the samples that blew the tail — the histogram->trace join)
EXEMPLAR_CAP = 8

#: an exemplar ages out of the "worst RECENT" store after this long —
#: yesterday's spike must not pin today's slowest-requests table
EXEMPLAR_MAX_AGE_S = 600.0

#: env overrides for the two retention knobs above (a forensics-heavy
#: deployment keeps more/longer, a memory-tight one less) — re-resolved
#: by :func:`reset`, so tests see their monkeypatched values
EXEMPLAR_CAP_ENV = "KNN_TPU_OBS_EXEMPLAR_CAP"
EXEMPLAR_AGE_ENV = "KNN_TPU_OBS_EXEMPLAR_AGE_S"


def _resolve_exemplar_knobs() -> None:
    """Resolve the exemplar retention knobs from the environment (the
    module constants are the defaults).  Malformed values raise — a
    typo'd retention knob must not silently fall back."""
    global _exemplar_cap, _exemplar_age_s
    raw = os.environ.get(EXEMPLAR_CAP_ENV)
    if raw:
        try:
            cap = int(raw)
        except ValueError:
            cap = -1
        if cap < 0:
            raise ValueError(
                f"{EXEMPLAR_CAP_ENV}={raw!r} is not a non-negative int")
        _exemplar_cap = cap
    else:
        _exemplar_cap = EXEMPLAR_CAP
    raw = os.environ.get(EXEMPLAR_AGE_ENV)
    if raw:
        try:
            age = float(raw)
        except ValueError:
            age = -1.0
        if age <= 0:
            raise ValueError(
                f"{EXEMPLAR_AGE_ENV}={raw!r} is not a positive float")
        _exemplar_age_s = age
    else:
        _exemplar_age_s = EXEMPLAR_MAX_AGE_S


_exemplar_cap = EXEMPLAR_CAP
_exemplar_age_s = EXEMPLAR_MAX_AGE_S
_resolve_exemplar_knobs()


class Counter:
    """Monotone counter; ``inc`` only (negative increments refused).
    Thread-safety: guarded by ``self._lock``."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._v += amount

    def get(self) -> float:
        with self._lock:
            return self._v


class Gauge:
    """Settable level; ``set``/``inc``/``dec``.
    Thread-safety: guarded by ``self._lock``."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._v = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v -= amount

    def get(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Lifetime count/sum/min/max + a bounded recent-sample window the
    percentiles are computed over (see module docstring).

    Thread-safety: guarded by ``self._lock`` (machine-checked by the
    ``locked-mutation`` checker, knn_tpu.analysis).

    ``observe(value, exemplar=trace_id)`` additionally retains the
    trace ids of the WORST recent samples (at most :data:`EXEMPLAR_CAP`,
    aged out after :data:`EXEMPLAR_MAX_AGE_S`) — the histogram->trace
    join the tail-forensics layer (knn_tpu.obs.waterfall) reads, the
    Prometheus exporter emits as OpenMetrics-style exemplars, and the
    slowest-requests tables render.  Call sites without a trace id pay
    one ``is None`` check and nothing else."""

    __slots__ = ("_lock", "_count", "_sum", "_min", "_max", "_window",
                 "_wts", "_ex", "_bkt")

    def __init__(self, window: int = DEFAULT_WINDOW):
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        #: per-bucket observation counts over BUCKET_BOUNDS (last slot
        #: is the +Inf overflow); cumulated at export time so snapshots
        #: carry Prometheus-style ``le`` semantics while observe() pays
        #: one bisect + one increment
        self._bkt = [0] * (len(BUCKET_BOUNDS) + 1)
        self._window: deque = deque(maxlen=int(window))
        #: arrival timestamps parallel to _window, so the summary can
        #: say WHICH wall span its percentiles cover — a window
        #: quantile without its span is ambiguous between "the last
        #: second" and "since boot" (the window-vs-lifetime fix)
        self._wts: deque = deque(maxlen=int(window))
        #: worst recent exemplars, value-descending:
        #: (value, trace_id, wall ts, monotonic ts)
        self._ex: list = []

    def _note_exemplar(self, v: float, trace_id: str, mono: float) -> None:
        """Retain ``trace_id`` when ``v`` ranks among the worst recent
        samples.  Caller holds ``self._lock``."""
        cutoff = mono - _exemplar_age_s
        ex = [e for e in self._ex if e[3] >= cutoff]
        if len(ex) < _exemplar_cap or (ex and v > ex[-1][0]):
            ex.append((v, str(trace_id), time.time(), mono))
            ex.sort(key=lambda e: -e[0])
            del ex[_exemplar_cap:]
        self._ex = ex

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        v = float(value)
        t = time.monotonic()
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            self._bkt[bisect.bisect_left(BUCKET_BOUNDS, v)] += 1
            self._window.append(v)
            self._wts.append(t)
            if exemplar is not None:
                self._note_exemplar(v, exemplar, t)

    def exemplars(self) -> list:
        """Worst recent exemplars, value-descending:
        ``[{"value", "trace_id", "ts"}, ...]`` (``ts`` is wall time).
        Ages out on READ as well as on write — a series whose traffic
        stopped must not pin yesterday's spike forever."""
        cutoff = time.monotonic() - _exemplar_age_s
        with self._lock:
            if any(e[3] < cutoff for e in self._ex):
                self._ex = [e for e in self._ex if e[3] >= cutoff]
            ex = list(self._ex)
        return [{"value": v, "trace_id": tid, "ts": round(ts, 3)}
                for v, tid, ts, _ in ex]

    def observe_many(self, values) -> None:
        """Bulk observe (one lock acquisition) — the int8 quant-bound
        path records a whole query batch's epsilons at once."""
        vs = [float(v) for v in values]
        if not vs:
            return
        lo, hi = min(vs), max(vs)
        t = time.monotonic()
        with self._lock:
            self._count += len(vs)
            self._sum += sum(vs)
            if self._min is None or lo < self._min:
                self._min = lo
            if self._max is None or hi > self._max:
                self._max = hi
            for v in vs:
                self._bkt[bisect.bisect_left(BUCKET_BOUNDS, v)] += 1
            self._window.extend(vs)
            self._wts.extend([t] * len(vs))

    def get(self) -> Dict[str, float]:
        return self.summary()

    def summary(self) -> Dict[str, float]:
        """Lifetime count/sum/min/max + window p50/p95/p99/mean.  The
        window percentiles carry their provenance — ``window`` (sample
        count) and ``window_span_s`` (wall span from oldest to newest
        windowed sample) — so every consumer can label which window a
        quantile came from instead of conflating it with lifetime."""
        with self._lock:
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
            bkt = list(self._bkt)
            window = list(self._window)
            wts = list(self._wts)
        out: Dict[str, float] = {"count": count, "sum": total}
        if mn is not None:
            out["min"], out["max"] = mn, mx
        if count:
            # cumulative counts over BUCKET_BOUNDS (+Inf last) — the
            # mergeable form: identical fixed bounds in every process,
            # so fleet aggregation adds these element-wise and derives
            # quantiles from the MERGED distribution (never by
            # averaging per-host percentiles)
            cum, running = [], 0
            for c in bkt:
                running += c
                cum.append(running)
            out["buckets"] = cum
        ex = self.exemplars()
        if ex:
            # only exemplar-fed series grow the key: summaries of
            # histograms nobody passes trace ids to are unchanged
            out["exemplars"] = ex
        if window:
            # numpy only when there are samples: keeps the empty-series
            # snapshot path import-light
            import numpy as np

            arr = np.asarray(window, dtype=np.float64)
            out.update({
                "p50": float(np.percentile(arr, 50)),
                "p95": float(np.percentile(arr, 95)),
                "p99": float(np.percentile(arr, 99)),
                "mean": float(arr.mean()),
                "window": int(arr.size),
                "window_span_s": round(wts[-1] - wts[0], 3) if wts else 0.0,
            })
        return out


class _Noop:
    """The shared disabled-mode instrument: every method of every kind,
    doing nothing.  ONE instance (``NOOP``) serves all call sites — the
    no-op identity tests/test_obs.py pins."""

    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        pass

    def observe_many(self, values) -> None:
        pass

    def exemplars(self) -> list:
        return []

    def get(self):
        return 0.0

    def summary(self) -> dict:
        return {"count": 0, "sum": 0.0}


NOOP = _Noop()


def quantile_from_buckets(cum, q: float) -> Optional[float]:
    """The ``q``-quantile (0..1) of a cumulative bucket vector over
    :data:`BUCKET_BOUNDS` — the bucket's UPPER bound, i.e. a sound
    upper estimate quantized to the bucket grid.  This is the only
    valid way to state a fleet quantile: per-host percentiles do not
    average, but cumulative counts over identical bounds add, and the
    quantile of the sum is exact to bucket resolution.  Returns None
    for an empty vector; an overflow-bucket hit returns the last
    finite bound (the estimate saturates, it never invents +Inf)."""
    if not cum:
        return None
    total = cum[-1]
    if total <= 0:
        return None
    target = q * total
    for i, c in enumerate(cum):
        if c >= target and c > 0:
            return BUCKET_BOUNDS[min(i, len(BUCKET_BOUNDS) - 1)]
    return BUCKET_BOUNDS[-1]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Catalog-validated instrument store, keyed (name, label items).
    Thread-safety: guarded by ``self._lock``."""

    def __init__(self, *, window: int = DEFAULT_WINDOW):
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}
        self._window = int(window)

    # -- registration ------------------------------------------------------
    def _get(self, kind: str, name: str, labels: Dict[str, object]):
        spec = CATALOG.get(name)
        if spec is None or not NAME_RE.match(name):
            raise ValueError(
                f"metric {name!r} is not in the catalog "
                f"(knn_tpu.obs.names.CATALOG) — declare it there, with "
                f"docs, before instrumenting")
        want_kind, want_labels, _help = spec
        if want_kind != kind:
            raise ValueError(
                f"metric {name!r} is a {want_kind}, not a {kind}")
        if tuple(sorted(labels)) != tuple(sorted(want_labels)):
            raise ValueError(
                f"metric {name!r} takes labels {sorted(want_labels)}, "
                f"got {sorted(labels)}")
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            inst = self._series.get(key)
            if inst is None:
                inst = (_KINDS[kind](window=self._window)
                        if kind == "histogram" else _KINDS[kind]())
                self._series[key] = inst
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", name, labels)

    # -- inspection --------------------------------------------------------
    def snapshot(self) -> dict:
        """Every registered series, catalog metadata included — the ONE
        structure both exporters (Prometheus text, JSON file) render."""
        with self._lock:
            keys = list(self._series.items())
        out: dict = {}
        for (name, label_items), inst in keys:
            kind, _labels, help_ = CATALOG[name]
            m = out.setdefault(
                name, {"type": kind, "help": help_, "series": []})
            value = inst.summary() if kind == "histogram" else inst.get()
            m["series"].append({"labels": dict(label_items), "value": value})
        for m in out.values():  # deterministic export order
            m["series"].sort(key=lambda s: sorted(s["labels"].items()))
        return out


class _NoopRegistry(MetricsRegistry):
    """Disabled mode: every instrument request returns the ONE shared
    no-op after the same catalog validation (so a bad name fails fast in
    dev regardless of the env switch)."""

    def _get(self, kind, name, labels):
        spec = CATALOG.get(name)
        if (spec is not None and spec[0] == kind
                and tuple(sorted(labels)) == tuple(sorted(spec[1]))):
            return NOOP
        # invalid request: delegate for the precise error message (the
        # parent raises before it would ever allocate an instrument)
        return super()._get(kind, name, labels)

    def snapshot(self) -> dict:
        return {}


_state_lock = threading.Lock()
_registry: Optional[MetricsRegistry] = None


def _env_enabled() -> bool:
    return os.environ.get(OBS_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no")


def enabled() -> bool:
    """Whether the subsystem is live (resolved once, at first registry
    access; flip with :func:`reset`)."""
    return not isinstance(get_registry(), _NoopRegistry)


def get_registry() -> MetricsRegistry:
    global _registry
    reg = _registry
    if reg is None:
        with _state_lock:
            if _registry is None:
                _registry = (MetricsRegistry() if _env_enabled()
                             else _NoopRegistry())
            reg = _registry
    return reg


def reset(enabled: Optional[bool] = None) -> MetricsRegistry:
    """Swap in a fresh registry (clears every series); ``enabled`` None
    re-reads the env.  Tests use this for isolation; production code
    never needs it.  Note instruments handed out by the OLD registry
    keep working but stop being exported — re-fetch handles after a
    reset."""
    global _registry
    with _state_lock:
        want = _env_enabled() if enabled is None else bool(enabled)
        _resolve_exemplar_knobs()
        _registry = MetricsRegistry() if want else _NoopRegistry()
        return _registry


# -- convenience pass-throughs (the instrumented modules' whole API) -----
def counter(name: str, **labels) -> Counter:
    return get_registry().counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return get_registry().gauge(name, **labels)


def histogram(name: str, **labels) -> Histogram:
    return get_registry().histogram(name, **labels)


def snapshot() -> dict:
    return get_registry().snapshot()
