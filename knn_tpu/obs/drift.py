"""Query-distribution drift sketches + index-health gauges
(docs/OBSERVABILITY.md "Quality observability").

Recall regressions rarely start as recall regressions: they start as
the query distribution walking away from the one the index was trained
on (IVF centroids mis-assign, the probe set stops covering), or as the
index degrading structurally (one list absorbing the growth, the delta
tail swamping the trained base, tombstones diluting every scan).  Both
are visible BEFORE the audit sampler catches a wrong answer — this
module makes them gauges.

:class:`QueryDriftMonitor` freezes a train-time baseline (query-norm
histogram over quantile bin edges of the TRAINING rows' norms, plus
the k-means centroid-assignment histogram) and scores every live
batch's accumulated distribution against it with the population
stability index::

    PSI = sum_i (q_i - p_i) * ln(q_i / p_i)

(eps-smoothed; 0 = identical, > 0.2 is the classical "investigate"
bar, > 0.5 "act").  The sketches are O(bins) counters — no query is
retained — and the whole monitor is constructed ONLY when telemetry is
enabled (``KNN_TPU_OBS=0`` builds nothing, the pinned contract).

:func:`index_health` publishes the structural gauges from a snapshot's
geometry: list imbalance (max/mean trained-list size), delta-tail
fraction, tombstone density.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from knn_tpu.obs import names, registry

#: norm-histogram bins (quantile edges over the training norms)
NORM_BINS = 16
#: smoothing epsilon for PSI (zero-count bins must not blow up ln)
_EPS = 1e-6


def psi(expected: np.ndarray, observed: np.ndarray) -> float:
    """Population stability index between two count/fraction vectors
    of equal length (eps-smoothed, each renormalized)."""
    p = np.asarray(expected, np.float64) + _EPS
    q = np.asarray(observed, np.float64) + _EPS
    p = p / p.sum()
    q = q / q.sum()
    return float(np.sum((q - p) * np.log(q / p)))


class QueryDriftMonitor:
    """Streaming drift sketch against a frozen train-time baseline.

    ``train_norms`` are the L2 norms of the TRAINING rows (the
    baseline the norm sketch bins against); ``assign_baseline`` is the
    per-centroid training assignment count vector (k-means counts).
    Either may be omitted — the corresponding PSI is then not scored.
    """

    def __init__(self, train_norms: Optional[np.ndarray] = None,
                 assign_baseline: Optional[np.ndarray] = None,
                 nbins: int = NORM_BINS) -> None:
        self._norm_edges: Optional[np.ndarray] = None
        self._norm_base: Optional[np.ndarray] = None
        self._norm_counts: Optional[np.ndarray] = None
        if train_norms is not None and len(train_norms) > 0:
            tn = np.asarray(train_norms, np.float64)
            edges = np.unique(np.quantile(
                tn, np.linspace(0.0, 1.0, nbins + 1)[1:-1]))
            # interior edges only: the two outer bins are open-ended,
            # so out-of-range live norms land in a bin, never vanish
            self._norm_edges = edges
            base = np.bincount(np.searchsorted(edges, tn),
                               minlength=len(edges) + 1)
            self._norm_base = base.astype(np.float64)
            self._norm_counts = np.zeros(len(edges) + 1, np.float64)
        self._assign_base: Optional[np.ndarray] = None
        self._assign_counts: Optional[np.ndarray] = None
        if assign_baseline is not None and len(assign_baseline) > 0:
            ab = np.asarray(assign_baseline, np.float64)
            self._assign_base = ab
            self._assign_counts = np.zeros(len(ab), np.float64)
        self._queries = 0

    def observe(self, norms: Optional[np.ndarray] = None,
                assignments: Optional[np.ndarray] = None) -> None:
        """Fold one live batch into the sketches and publish the PSI
        gauges.  ``norms``: per-query L2 norms; ``assignments``:
        per-query nearest-centroid index."""
        n_q = 0
        if norms is not None and self._norm_edges is not None:
            ns = np.asarray(norms, np.float64).ravel()
            n_q = max(n_q, ns.shape[0])
            self._norm_counts += np.bincount(
                np.searchsorted(self._norm_edges, ns),
                minlength=self._norm_counts.shape[0])
            registry.gauge(names.DRIFT_NORM_PSI).set(
                psi(self._norm_base, self._norm_counts))
        if assignments is not None and self._assign_base is not None:
            asg = np.asarray(assignments, np.int64).ravel()
            n_q = max(n_q, asg.shape[0])
            self._assign_counts += np.bincount(
                np.clip(asg, 0, self._assign_base.shape[0] - 1),
                minlength=self._assign_base.shape[0])
            registry.gauge(names.DRIFT_ASSIGN_PSI).set(
                psi(self._assign_base, self._assign_counts))
        if n_q:
            self._queries += n_q
            registry.counter(names.DRIFT_QUERIES).inc(n_q)

    def status(self) -> dict:
        """JSON-safe sketch state for /statusz + doctor."""
        out = {"queries_observed": self._queries}
        if self._norm_base is not None:
            out["norm_psi"] = psi(self._norm_base, self._norm_counts)
            out["norm_bins"] = int(self._norm_counts.shape[0])
        if self._assign_base is not None:
            out["centroid_assign_psi"] = psi(self._assign_base,
                                             self._assign_counts)
            out["centroids"] = int(self._assign_base.shape[0])
        return out


def index_health(list_sizes: Optional[np.ndarray], tail_rows: int,
                 n_all: int, live_rows: int) -> dict:
    """Publish the structural index-health gauges from one snapshot's
    geometry and return the same numbers as a JSON-safe dict.

    - list imbalance: max/mean trained IVF list size (1.0 = balanced);
    - delta-tail fraction: unindexed tail rows / all rows — the slice
      every search brute-forces;
    - tombstone density: dead rows / all rows — the dilution of every
      byte streamed."""
    out = {}
    if list_sizes is not None and len(list_sizes) > 0:
        sizes = np.asarray(list_sizes, np.float64)
        mean = float(sizes.mean())
        imbalance = float(sizes.max() / mean) if mean > 0 else 0.0
        registry.gauge(names.INDEX_LIST_IMBALANCE).set(imbalance)
        out["list_imbalance"] = imbalance
    if n_all > 0:
        tail_fraction = float(tail_rows) / float(n_all)
        tombstone_density = float(n_all - live_rows) / float(n_all)
        registry.gauge(names.INDEX_TAIL_FRACTION).set(tail_fraction)
        registry.gauge(names.INDEX_TOMBSTONE_DENSITY).set(
            tombstone_density)
        out["delta_tail_fraction"] = tail_fraction
        out["tombstone_density"] = tombstone_density
    return out
