"""Exporters: Prometheus text format, atomic JSON snapshots, and a
stdlib-HTTP ``/metrics`` endpoint.

Rendering rules (one source: :func:`prometheus_text` over
``registry.snapshot()``):

- counters/gauges render as ``name{labels} value``;
- histograms render as Prometheus **summaries** — ``name{quantile="..."}``
  lines from the bounded-window percentiles plus lifetime ``_sum`` and
  ``_count`` (the window feeds quantiles, the lifetime pair feeds rate
  math, so a scraper gets both truths).

The HTTP server is intentionally boring: ``http.server`` threading
daemon, ``/metrics`` (text format) + ``/metrics.json`` (the snapshot),
no deps, no auth — bind it to localhost and let the scraper's side
handle the rest.  The JSON snapshot writer is atomic (tmp + rename,
the tuning-cache discipline) so a scraper of the file never reads a
torn write.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Optional

from knn_tpu.obs import ident, registry

#: summary quantiles exported from the histogram window
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_str(labels: dict, extra: Optional[tuple] = None) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items = items + [extra]
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_esc(str(v))}"' for k, v in items) + "}"


def prometheus_text(snapshot: Optional[dict] = None) -> str:
    """The whole registry in Prometheus text exposition format."""
    snap = registry.snapshot() if snapshot is None else snapshot
    lines = []
    for name in sorted(snap):
        m = snap[name]
        kind = m["type"]
        prom_kind = "summary" if kind == "histogram" else kind
        lines.append(f"# HELP {name} {m['help']}")
        lines.append(f"# TYPE {name} {prom_kind}")
        for s in m["series"]:
            ls, v = s["labels"], s["value"]
            if kind == "histogram":
                for q, key in _QUANTILES:
                    if key in v:
                        lines.append(
                            f"{name}{_labels_str(ls, ('quantile', q))} "
                            f"{v[key]}")
                if v.get("exemplars"):
                    # the worst retained sample's trace id, value, and
                    # wall timestamp in OpenMetrics exemplar syntax —
                    # but on a COMMENT line: neither exposition format
                    # allows inline exemplars on summary quantiles, and
                    # a text-0.0.4 scraper must keep parsing (comments
                    # other than HELP/TYPE are ignored)
                    ex = v["exemplars"][0]
                    lines.append(
                        f"# EXEMPLAR "
                        f"{name}{_labels_str(ls, ('quantile', '0.99'))} "
                        f'{{trace_id="{_esc(str(ex["trace_id"]))}"}} '
                        f'{ex["value"]} {ex["ts"]}')
                if v.get("buckets"):
                    # the mergeable form: cumulative counts over the
                    # fixed registry.BUCKET_BOUNDS grid, classic
                    # ``_bucket{le=...}`` lines — identical bounds in
                    # every process is what lets the fleet aggregator
                    # add them and take quantiles of the SUM
                    cum = v["buckets"]
                    for b, c in zip(registry.BUCKET_BOUNDS, cum):
                        lines.append(
                            f"{name}_bucket"
                            f"{_labels_str(ls, ('le', format(b, '.6g')))} "
                            f"{c}")
                    lines.append(
                        f"{name}_bucket{_labels_str(ls, ('le', '+Inf'))} "
                        f"{cum[-1]}")
                lines.append(f"{name}_sum{_labels_str(ls)} {v['sum']}")
                lines.append(f"{name}_count{_labels_str(ls)} {v['count']}")
            else:
                lines.append(f"{name}{_labels_str(ls)} {v}")
    return "\n".join(lines) + "\n"


def compact_snapshot(snapshot: Optional[dict] = None) -> dict:
    """The snapshot flattened for embedding (JobResult.metrics()["obs"],
    bench lines): ``{name: value}`` for unlabeled series, ``{name:
    {"k=v,...": value}}`` for labeled ones; histograms keep their
    summary dict."""
    snap = registry.snapshot() if snapshot is None else snapshot
    out: dict = {}
    for name, m in snap.items():
        series = m["series"]
        if len(series) == 1 and not series[0]["labels"]:
            out[name] = series[0]["value"]
        else:
            out[name] = {
                ",".join(f"{k}={v}" for k, v in sorted(s["labels"].items())):
                    s["value"]
                for s in series
            }
    return out


def write_json_snapshot(path: str, snapshot: Optional[dict] = None) -> dict:
    """Atomic JSON snapshot (tmp + rename): a scraper of the file can
    never observe a torn write.  Returns the written payload.  Embeds
    the health/self-diagnosis report, so ``knn_tpu.cli doctor
    --snapshot`` renders offline exactly what ``/statusz`` served
    live."""
    from knn_tpu.obs import health

    payload = {
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "written_at_unix": round(time.time(), 3),
        "pid": os.getpid(),
        "identity": ident.identity(),
        "enabled": registry.enabled(),
        "metrics": registry.snapshot() if snapshot is None else snapshot,
        "health": health.report(),
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return payload


def start_metrics_server(port: int, host: str = "127.0.0.1"):
    """Serve ``/metrics`` (Prometheus text), ``/metrics.json`` (the full
    snapshot), ``/healthz`` (liveness/readiness probe: 200 only once
    warmup completed and worker threads are live — knn_tpu.obs.health),
    ``/statusz`` (the full self-diagnosis report), ``/waterfallz``
    (per-request latency waterfalls + critical-path attribution —
    knn_tpu.obs.waterfall), and ``/fleetz`` (the merged cross-host
    fleet report over ``KNN_TPU_FLEET_MEMBERS`` — knn_tpu.obs.fleet)
    from a daemon
    thread; returns the server (``.shutdown()`` to stop;
    ``.server_address[1]`` for the bound port — pass port 0 to let the
    OS pick one)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib handler contract
            from knn_tpu.obs import health

            path = self.path.split("?", 1)[0]
            status = 200
            if path in ("/metrics", "/"):
                body = prometheus_text().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/metrics.json":
                body = json.dumps(
                    {"enabled": registry.enabled(),
                     "identity": ident.identity(),
                     "written_at_unix": round(time.time(), 3),
                     "metrics": registry.snapshot()},
                    indent=1, sort_keys=True).encode()
                ctype = "application/json"
            elif path == "/healthz":
                probe = health.probe()
                status = 200 if probe["ready"] else 503
                body = json.dumps(probe, sort_keys=True).encode()
                ctype = "application/json"
            elif path == "/statusz":
                body = json.dumps(health.report(), indent=1,
                                  sort_keys=True, default=str).encode()
                ctype = "application/json"
            elif path == "/waterfallz":
                from knn_tpu.obs import waterfall

                # the full forensics payload: every reconstructable
                # waterfall from the live ring, attribution, and the
                # slowest-requests table (cli `waterfall --port`)
                body = json.dumps(waterfall.live_report(), indent=1,
                                  sort_keys=True, default=str).encode()
                ctype = "application/json"
            elif path == "/fleetz":
                from knn_tpu.obs import fleet

                # the merged fleet report over KNN_TPU_FLEET_MEMBERS
                # (knn_tpu.obs.fleet) — partial collections render
                # loudly with their unreachable/skewed members listed
                body = json.dumps(fleet.live_fleet_report(), indent=1,
                                  sort_keys=True, default=str).encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # silence per-scrape stderr
            pass

    server = ThreadingHTTPServer((host, int(port)), Handler)
    server.daemon_threads = True
    t = threading.Thread(
        target=server.serve_forever, name="knn-obs-metrics", daemon=True)
    t.start()
    return server
