"""Per-request latency waterfalls reconstructed from the span stream —
the forensics layer that turns the flat trace events (knn_tpu.obs.trace)
back into "where did THIS request's time go".

Every aggregate latency surface the repo has (the p99 histograms, the
SLO burn rates, the roofline ceiling) answers "how bad is the tail";
none can answer "WHICH requests blew it, and on what segment".  The
serving layer already emits everything needed — per-request trace ids,
queue/admission/dispatch/compile/join/deliver spans, and the
``queue.dispatch`` events linking coalesced members to their batch-level
engine request — this module is the reconstruction:

- :func:`reconstruct` — events (the in-memory ring, a JSONL log, or a
  live endpoint's dump) -> one **waterfall** per request: ordered
  segments ``admission -> queue_wait -> dispatch -> compile -> device ->
  join -> deliver`` whose durations must TILE the request's measured
  arrival-to-result latency within a stated tolerance.  Any remainder is
  reported as an explicit ``unattributed`` segment — never silently
  absorbed into a neighbor — and segments summing past the total are
  reported as ``overlap_s`` (clock-skew truth-telling, the window-truth
  discipline of the latency summaries).
- :func:`attribute` — critical-path attribution across many waterfalls:
  which segment dominates at the p50 band vs the p99 tail, overall and
  per tenant / per bucket (the grouped view the per-tenant SLOs judge).
- :func:`device_vs_roofline` — the device segment of the tail compared
  against the analytic roofline ceiling (knn_tpu.obs.roofline), so a
  fat "device" segment that is really pipeline wait (implied q/s far
  under the ceiling) reads ``queued_behind_device``, not device-bound.
- :func:`slowest_table` — the worst recent requests by histogram
  exemplar (knn_tpu.obs.registry), each with its inline waterfall: the
  ``stats()``/``/statusz``/doctor "slowest recent requests" table.
- :func:`read_jsonl_events` — JSONL log reader that MERGES the rotated
  ``<path>.1`` generation before the live file, so a request whose
  spans straddle the rotation boundary still reconstructs.

Everything here is jax-free and read-only over copies (ring snapshots,
registry snapshots): reconstruction must be runnable offline from a
postmortem bundle (knn_tpu.obs.blackbox) or a scraped JSONL log on a
box with no accelerator.

Segment semantics (durations, never mixed-clock wall arithmetic):

- ``admission``  — submit-entry to queue-append (lock wait + the
  admission decision); carved OUT of queue_wait, which contains it.
- ``queue_wait`` — arrival to batch dispatch (micro-batching hold),
  minus the admission slice above.
- ``dispatch``   — the batch's pad/place/async-dispatch span, minus any
  inline compile carved out below ("coalesce-to-dispatch").
- ``compile``    — inline XLA compile(s) the batch paid (zero once
  warmed; the bucket ladder's whole point).
- ``device``     — the batch request span minus its dispatch and join
  spans: the in-flight window between dispatch return and result join.
  Under dispatch-ahead this INCLUDES waiting behind earlier in-flight
  batches — :func:`device_vs_roofline` is how that is told apart.
- ``join``       — time blocked on the device transfer in ``result()``.
- ``deliver``    — batch completion to THIS member's future resolution
  (scatter + head-of-line in the completer loop).

Direct (queue-less) engine requests reconstruct from their own spans
(dispatch/compile/device/join); queue-only segments are absent.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence

from knn_tpu.obs import names, registry, trace

#: absolute + relative completeness tolerance: segments must cover the
#: measured total to within max gap/overlap of
#: ``TOLERANCE_ABS_S + TOLERANCE_REL * total`` — stated, not implied
#: (span stamps bracket small unattributed strips: per-member span
#: recording in the batcher, the completer's batch stamp; on a loaded
#: CPU harness those are real milliseconds, never silently absorbed)
TOLERANCE_ABS_S = 0.010
TOLERANCE_REL = 0.10

#: canonical segment order (docstring above); ``unattributed`` rides
#: last when the known segments leave a gap
SEGMENTS = ("admission", "queue_wait", "dispatch", "compile", "device",
            "join", "deliver")

#: segments a direct (queue-less) engine request can carry
DIRECT_SEGMENTS = ("dispatch", "compile", "device", "join")

#: histograms whose exemplars feed the slowest-requests table
_EXEMPLAR_HISTS = (names.SERVING_REQUEST_LATENCY,
                   names.QUEUE_REQUEST_LATENCY,
                   names.TENANT_REQUEST_LATENCY)

#: implied-device-throughput floor (fraction of the roofline ceiling)
#: below which a dominant "device" segment is reclassified as pipeline
#: wait — compute that slow isn't compute
DEVICE_PCT_MIN = 0.25


def tolerance_s(total_s: float, *, abs_s: float = TOLERANCE_ABS_S,
                rel: float = TOLERANCE_REL) -> float:
    """The stated tiling tolerance for a request of ``total_s``."""
    return abs_s + rel * max(0.0, float(total_s))


# -- event sources ---------------------------------------------------------
def read_jsonl_events(path: str) -> List[dict]:
    """Events from a JSONL log, MERGING the rotated ``<path>.1``
    generation (older) before the live file — the EventLog rotation
    contract holds at most two generations, both valid JSONL, so a
    request whose spans straddle the rotation boundary reconstructs
    from the merge.  Malformed lines are loud errors (a silently
    skipped span would read as an unattributed gap)."""
    events: List[dict] = []
    found = False
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        found = True
        with open(p) as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError as e:
                    raise ValueError(f"{p}:{ln}: not JSON: {e}") from e
    if not found:
        raise FileNotFoundError(f"no event log at {path} (or {path}.1)")
    return events


def _index(events: Sequence[dict]):
    """(spans by trace id by span name, batch id -> member ids)."""
    spans: Dict[str, Dict[str, List[dict]]] = {}
    members: Dict[str, List[str]] = {}
    for e in events:
        if e.get("type") == "span" and e.get("trace_id"):
            spans.setdefault(e["trace_id"], {}).setdefault(
                e.get("span"), []).append(e)
        elif e.get("name") == "queue.dispatch" and e.get("batch_trace_id"):
            members.setdefault(e["batch_trace_id"], []).extend(
                e.get("member_trace_ids") or ())
    return spans, members


def _dur(spanmap: Dict[str, List[dict]], name: str) -> float:
    return float(sum(e.get("dur_s") or 0.0 for e in spanmap.get(name, ())))


def _attr(spanmap: Dict[str, List[dict]], key: str, *span_names):
    for name in span_names:
        for e in reversed(spanmap.get(name, ())):
            if e.get(key) is not None:
                return e[key]
    return None


def _build(trace_id: str, kind: str, total_s: float, raw: Dict[str, float],
           *, end_ts=None, tenant=None, rows=None, bucket=None, op=None,
           batch_trace_id=None) -> dict:
    """Assemble one waterfall: ordered nonnegative segments, the
    explicit unattributed remainder, and the completeness verdict."""
    order = SEGMENTS if kind == "queued" else DIRECT_SEGMENTS
    segments = [{"name": n, "dur_s": round(max(0.0, raw.get(n, 0.0)), 6)}
                for n in order]
    known = sum(s["dur_s"] for s in segments)
    gap = total_s - known
    tol = tolerance_s(total_s)
    unattributed = round(max(0.0, gap), 6)
    overlap = round(max(0.0, -gap), 6)
    if unattributed > 0.0:
        segments.append({"name": "unattributed", "dur_s": unattributed})
    return {
        "trace_id": trace_id,
        "kind": kind,
        "op": op,
        "tenant": tenant,
        "rows": rows,
        "bucket": bucket,
        "batch_trace_id": batch_trace_id,
        "total_s": round(total_s, 6),
        "segments": segments,
        "unattributed_s": unattributed,
        "overlap_s": overlap,
        "tolerance_s": round(tol, 6),
        "complete": bool(unattributed <= tol and overlap <= tol),
        "end_ts": end_ts,
    }


def reconstruct(events: Sequence[dict]) -> Dict[str, dict]:
    """One waterfall per REQUEST found in ``events`` (trace id ->
    waterfall).  Queued members reconstruct through their batch's
    engine-level spans (linked by ``batch_trace_id``); direct engine
    requests from their own; batch-internal engine requests are the
    plumbing, not roots, and are skipped.  Missing spans (rotated away,
    never emitted) surface as ``unattributed`` gap — ``complete`` goes
    false past the stated tolerance instead of fabricating segments."""
    spans, dispatch_members = _index(events)
    batch_ids = set(dispatch_members)
    for tid, sm in spans.items():
        for e in sm.get("serving.queued_request", ()):
            if e.get("batch_trace_id"):
                batch_ids.add(e["batch_trace_id"])
    out: Dict[str, dict] = {}
    for tid, sm in spans.items():
        qr_list = sm.get("serving.queued_request")
        if qr_list:
            qr = qr_list[-1]
            batch_id = qr.get("batch_trace_id")
            bm = spans.get(batch_id, {}) if batch_id else {}
            admission = _dur(sm, "serving.admission")
            raw = {
                "admission": admission,
                "queue_wait": max(
                    0.0, _dur(sm, "serving.queue_wait") - admission),
                "deliver": _dur(sm, "serving.deliver"),
            }
            b_disp = _dur(bm, "serving.dispatch")
            b_comp = _dur(bm, "serving.compile")
            b_join = _dur(bm, "serving.join")
            b_req = _dur(bm, "serving.request")
            raw["compile"] = b_comp
            raw["dispatch"] = max(0.0, b_disp - b_comp)
            raw["join"] = b_join
            raw["device"] = max(0.0, b_req - b_disp - b_join)
            out[tid] = _build(
                tid, "queued", float(qr.get("dur_s") or 0.0), raw,
                end_ts=qr.get("ts"),
                tenant=_attr(sm, "tenant", "serving.queued_request",
                             "serving.queue_wait", "serving.admission"),
                rows=_attr(sm, "rows", "serving.queued_request",
                           "serving.queue_wait"),
                bucket=(max(_attr(bm, "buckets", "serving.dispatch"))
                        if _attr(bm, "buckets", "serving.dispatch")
                        else None),
                op=_attr(sm, "op", "serving.queued_request"),
                batch_trace_id=batch_id)
            continue
        req_list = sm.get("serving.request")
        if req_list:
            # an engine-level request: a direct caller's, or the
            # batch-level request coalesced members rode (kind
            # "batch" — reconstructable for the slowest table, but
            # excluded from attribution so a batch never double-counts
            # against its members)
            req = req_list[-1]
            disp = _dur(sm, "serving.dispatch")
            comp = _dur(sm, "serving.compile")
            join = _dur(sm, "serving.join")
            total = float(req.get("dur_s") or 0.0)
            raw = {
                "compile": comp,
                "dispatch": max(0.0, disp - comp),
                "join": join,
                "device": max(0.0, total - disp - join),
            }
            out[tid] = _build(
                tid, "batch" if tid in batch_ids else "direct",
                total, raw, end_ts=req.get("ts"),
                tenant=_attr(sm, "tenant", "serving.request",
                             "serving.dispatch"),
                rows=_attr(sm, "rows", "serving.request",
                           "serving.dispatch"),
                bucket=(max(_attr(sm, "buckets", "serving.dispatch"))
                        if _attr(sm, "buckets", "serving.dispatch")
                        else None),
                op=_attr(sm, "op", "serving.request"))
    return out


# -- cross-host stitching (knn_tpu.parallel.multihost) ---------------------
def stitch_multihost(events: Sequence[dict]) -> Dict[str, dict]:
    """One CROSS-HOST waterfall per request from ``multihost.merge``
    spans (trace id -> waterfall).  The DCN merge path propagates one
    canonical trace id through the coordinator-KV exchange and every
    process emits a ``multihost.merge`` span under it carrying ALL
    per-host wall times — so a single host's event stream (or N merged
    JSONL streams) reconstructs the whole replica's request:

    - ``host<h>.local`` — host h's measured local search wall,
    - ``host<h>.wait``  — host h idle waiting for the straggler
      (``max(walls) - walls[h]``): the PR 12 straggler gap as explicit
      per-host segments instead of one max-minus-min scalar,
    - ``dcn_merge``     — exchange + host-side top-k merge.

    Every lane tiles ``local + wait + dcn_merge`` against the span's
    measured arrival-to-result total within :func:`tolerance_s`;
    shortfalls surface as ``unattributed_s``/``overlap_s`` and flip
    ``complete``, never get absorbed.  When several hosts' streams are
    merged, the span with the largest measured total is authoritative
    (its lane saw the full wait)."""
    by_tid: Dict[str, List[dict]] = {}
    for e in events:
        if (e.get("type") == "span" and e.get("span") == "multihost.merge"
                and e.get("trace_id")):
            by_tid.setdefault(e["trace_id"], []).append(e)
    out: Dict[str, dict] = {}
    for tid, evs in by_tid.items():
        e = max(evs, key=lambda x: float(x.get("dur_s") or 0.0))
        walls = [float(w) for w in (e.get("walls_s") or ())]
        if not walls:
            continue
        total = float(e.get("dur_s") or 0.0)
        max_wall = max(walls)
        straggler = e.get("straggler_host")
        if straggler is None:
            straggler = int(max(range(len(walls)), key=lambda h: walls[h]))
        merge_s = total - max_wall
        segments = []
        for h, w in enumerate(walls):
            segments.append({"name": f"host{h}.local", "host": h,
                             "dur_s": round(w, 6)})
            wait = max_wall - w
            if wait > 0:
                segments.append({"name": f"host{h}.wait", "host": h,
                                 "dur_s": round(wait, 6)})
        if merge_s > 0:
            segments.append({"name": "dcn_merge",
                             "dur_s": round(merge_s, 6)})
        # every lane sums to max_wall + max(0, merge_s); the residual
        # against the measured total is stated, never absorbed
        lane_total = max_wall + max(0.0, merge_s)
        gap = total - lane_total
        tol = tolerance_s(total)
        out[tid] = {
            "trace_id": tid,
            "kind": "multihost",
            "hosts": e.get("hosts", len(walls)),
            "reporting_host": e.get("host"),
            "straggler_host": int(straggler),
            "straggler_gap_s": round(max_wall - min(walls), 6),
            "total_s": round(total, 6),
            "segments": segments,
            "unattributed_s": round(max(0.0, gap), 6),
            "overlap_s": round(max(0.0, -gap), 6),
            "tolerance_s": round(tol, 6),
            "complete": bool(abs(gap) <= tol),
            "end_ts": e.get("ts"),
        }
    return out


# -- aggregation -----------------------------------------------------------
def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (numpy-free:
    attribution must run inside the jax-free CLI with zero deps)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _band_stats(band: List[dict]) -> Optional[dict]:
    """Mean per-segment share of total over a band of waterfalls, and
    the dominant segment (critical-path attribution)."""
    if not band:
        return None
    shares: Dict[str, float] = {}
    for w in band:
        total = w["total_s"] or 0.0
        if total <= 0:
            continue
        for s in w["segments"]:
            shares[s["name"]] = shares.get(s["name"], 0.0) \
                + s["dur_s"] / total
    n = sum(1 for w in band if (w["total_s"] or 0.0) > 0)
    if not n or not shares:
        return None
    shares = {k: round(v / n, 4) for k, v in shares.items()}
    dominant = max(shares, key=lambda k: shares[k])
    return {
        "requests": len(band),
        "mean_total_ms": round(
            sum(w["total_s"] for w in band) / len(band) * 1e3, 3),
        "share": dict(sorted(shares.items(), key=lambda kv: -kv[1])),
        "dominant": dominant,
    }


def _bands(ws: List[dict]) -> Optional[dict]:
    """p50-band vs p99-tail attribution for one group of waterfalls."""
    ws = [w for w in ws if (w["total_s"] or 0.0) > 0]
    if not ws:
        return None
    totals = sorted(w["total_s"] for w in ws)
    p50 = _percentile(totals, 50)
    p99 = _percentile(totals, 99)
    p50_band = [w for w in ws if w["total_s"] <= p50] or ws[:1]
    tail = [w for w in ws if w["total_s"] >= p99] \
        or [max(ws, key=lambda w: w["total_s"])]
    return {
        "requests": len(ws),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
        "p50_band": _band_stats(p50_band),
        "p99_band": _band_stats(tail),
    }


def attribute(waterfalls) -> dict:
    """Critical-path attribution across many requests: which segment
    dominates at the p50 band vs the p99 tail — overall, per tenant,
    and per bucket.  The number the "why is p99 40x p50 at the knee"
    question needs: a queue_wait-dominated tail is a scheduling
    problem, a device-dominated one a kernel (roofline) problem."""
    ws = (list(waterfalls.values()) if isinstance(waterfalls, dict)
          else list(waterfalls))
    # batch-level engine requests are plumbing their members already
    # account for — attributing both would double-count the batch
    ws = [w for w in ws if w and w.get("kind") != "batch"]
    out = {"requests": len(ws), "overall": _bands(ws),
           "incomplete": sum(1 for w in ws if not w.get("complete"))}
    by_tenant: Dict[str, List[dict]] = {}
    by_bucket: Dict[str, List[dict]] = {}
    for w in ws:
        if w.get("tenant") is not None:
            by_tenant.setdefault(str(w["tenant"]), []).append(w)
        if w.get("bucket") is not None:
            by_bucket.setdefault(str(w["bucket"]), []).append(w)
    out["by_tenant"] = {t: _bands(g) for t, g in sorted(by_tenant.items())}
    out["by_bucket"] = {b: _bands(g)
                        for b, g in sorted(by_bucket.items(),
                                           key=lambda kv: int(kv[0]))}
    return out


def device_vs_roofline(waterfalls, ceiling_qps: Optional[float] = None
                       ) -> dict:
    """Tell a device-bound tail from a queue-bound one: the p99 tail's
    dominant segment, plus the device segment's IMPLIED throughput
    (rows / device seconds) against the analytic roofline ceiling.  A
    dominant device segment whose implied q/s sits far under the
    ceiling is not compute — it is pipeline/queue wait wearing the
    device's clothes (``queued_behind_device``).  ``ceiling_qps``
    defaults to the best ceiling published in this process
    (knn_tpu.obs.roofline); None disables the percent and the verdict
    falls back to segment shares alone."""
    ws = (list(waterfalls.values()) if isinstance(waterfalls, dict)
          else list(waterfalls))
    ws = [w for w in ws if w and (w["total_s"] or 0.0) > 0
          and w.get("kind") != "batch"]
    if ceiling_qps is None:
        try:
            from knn_tpu.obs import roofline

            ceilings = [r.get("ceiling_qps")
                        for r in roofline.last_reports().values()
                        if r.get("ceiling_qps")]
            ceiling_qps = max(ceilings) if ceilings else None
        except Exception:  # pragma: no cover - attribution must not die
            ceiling_qps = None
    if not ws:
        return {"requests": 0, "ceiling_qps": ceiling_qps,
                "verdict": None}
    totals = sorted(w["total_s"] for w in ws)
    p99 = _percentile(totals, 99)
    tail = [w for w in ws if w["total_s"] >= p99] \
        or [max(ws, key=lambda w: w["total_s"])]
    stats = _band_stats(tail)
    dominant = stats["dominant"] if stats else None
    implied = sorted(
        w["rows"] / d for w in tail
        if w.get("rows")
        for d in [next((s["dur_s"] for s in w["segments"]
                        if s["name"] == "device"), 0.0)]
        if d > 0)
    device_qps = (round(_percentile(implied, 50), 2) if implied else None)
    pct = (round(device_qps / ceiling_qps, 4)
           if device_qps and ceiling_qps else None)
    if dominant in ("device", "join"):
        verdict = ("queued_behind_device"
                   if pct is not None and pct < DEVICE_PCT_MIN
                   else "device_bound")
    elif dominant in ("queue_wait", "admission"):
        verdict = "queue_bound"
    elif dominant is None:
        verdict = None
    else:
        verdict = "host_bound"
    return {
        "requests": len(ws),
        "tail_requests": len(tail),
        "tail_dominant_segment": dominant,
        "tail_device_qps": device_qps,
        "ceiling_qps": ceiling_qps,
        "tail_device_roofline_pct": pct,
        "verdict": verdict,
    }


# -- the slowest-requests table -------------------------------------------
def slowest_table(*, top: int = 8, with_waterfalls: bool = True,
                  events: Optional[Sequence[dict]] = None,
                  waterfalls: Optional[Dict[str, dict]] = None
                  ) -> List[dict]:
    """Worst recent requests by latency-histogram exemplar (the trace
    ids the bounded exemplar stores retained), deduped across the
    serving/queue/tenant histograms, worst first.  With
    ``with_waterfalls`` each row carries its inline waterfall when the
    event ring (or the supplied ``events``/``waterfalls``) still holds
    the request's spans."""
    snap = registry.snapshot()
    best: Dict[str, dict] = {}
    for name in _EXEMPLAR_HISTS:
        m = snap.get(name)
        if not m:
            continue
        for s in m["series"]:
            for ex in (s["value"] or {}).get("exemplars", ()):
                tid = ex.get("trace_id")
                if not tid:
                    continue
                row = best.get(tid)
                if row is None or ex["value"] > row["latency_s"]:
                    best[tid] = {
                        "trace_id": tid,
                        "latency_s": ex["value"],
                        "latency_ms": round(ex["value"] * 1e3, 3),
                        "ts": ex.get("ts"),
                        "source": name,
                        **({"tenant": s["labels"]["tenant"]}
                           if "tenant" in s["labels"] else {}),
                    }
    rows = sorted(best.values(), key=lambda r: -r["latency_s"])[:int(top)]
    if rows and with_waterfalls:
        if waterfalls is None:
            evts = (trace.get_event_log().recent()
                    if events is None else events)
            waterfalls = reconstruct(evts)
        for r in rows:
            r["waterfall"] = waterfalls.get(r["trace_id"])
    return rows


def live_report(events: Optional[Sequence[dict]] = None) -> dict:
    """The full forensics payload over the live ring (or ``events``):
    every reconstructable waterfall, the critical-path attribution, the
    device-vs-roofline verdict, and the slowest-requests table — what
    ``/waterfallz`` serves and a postmortem bundle embeds."""
    evts = trace.get_event_log().recent() if events is None else events
    wfs = reconstruct(evts)
    stitched = stitch_multihost(evts)
    return {
        "generated_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "requests": len(wfs),
        "waterfalls": wfs,
        "attribution": attribute(wfs),
        "device_vs_roofline": device_vs_roofline(wfs),
        "slowest": slowest_table(events=evts, waterfalls=wfs),
        # cross-host waterfalls stitched from multihost.merge spans —
        # absent (None) when no DCN merge ran in this process
        "multihost": ({"requests": len(stitched), "waterfalls": stitched}
                      if stitched else None),
    }


# -- rendering (shared by `cli waterfall` and doctor) ----------------------
_BAR_WIDTH = 28


def render_waterfall(w: dict) -> str:
    """One request's waterfall as an indented text bar chart."""
    head = (f"{w.get('trace_id')}: total "
            f"{(w.get('total_s') or 0.0) * 1e3:.3f} ms  "
            f"[{w.get('kind')}]")
    for key in ("tenant", "rows", "bucket", "op"):
        if w.get(key) is not None:
            head += f" {key}={w[key]}"
    if not w.get("complete"):
        head += (f"  INCOMPLETE (gap {w.get('unattributed_s')}s, "
                 f"overlap {w.get('overlap_s')}s, "
                 f"tolerance {w.get('tolerance_s')}s)")
    lines = [head]
    total = w.get("total_s") or 0.0
    for s in w.get("segments", ()):
        frac = s["dur_s"] / total if total > 0 else 0.0
        bar = "#" * max(1 if s["dur_s"] > 0 else 0,
                        int(round(frac * _BAR_WIDTH)))
        lines.append(f"  {s['name']:<13} {s['dur_s'] * 1e3:>10.3f} ms "
                     f"{frac * 100:5.1f}%  {bar}")
    return "\n".join(lines)


def render_attribution(agg: dict, dvr: Optional[dict] = None) -> str:
    """The aggregated critical-path story as text."""
    lines = [f"attribution over {agg.get('requests', 0)} request(s)"
             + (f" ({agg['incomplete']} incomplete)"
                if agg.get("incomplete") else "")]

    def _one(label, bands, indent="  "):
        if not bands:
            return
        for band in ("p50_band", "p99_band"):
            st = bands.get(band)
            if not st:
                continue
            # re-sort by share: a JSON round-trip (sort_keys) may have
            # alphabetized the dict a live endpoint served
            ranked = sorted(st["share"].items(), key=lambda kv: -kv[1])
            shares = ", ".join(f"{k}={v * 100:.0f}%"
                               for k, v in ranked[:4])
            lines.append(
                f"{indent}{label} {band.replace('_band', '')}: dominant "
                f"{st['dominant']} (mean {st['mean_total_ms']} ms over "
                f"{st['requests']} req: {shares})")

    _one("overall", agg.get("overall"))
    for t, bands in (agg.get("by_tenant") or {}).items():
        _one(f"tenant {t}", bands, indent="    ")
    for b, bands in (agg.get("by_bucket") or {}).items():
        _one(f"bucket {b}", bands, indent="    ")
    if dvr and dvr.get("verdict"):
        pct = dvr.get("tail_device_roofline_pct")
        lines.append(
            f"  tail verdict: {dvr['verdict']} (dominant "
            f"{dvr.get('tail_dominant_segment')}, device "
            f"{dvr.get('tail_device_qps')} q/s"
            + (f" = {pct * 100:.1f}% of {dvr.get('ceiling_qps')} q/s "
               f"ceiling" if pct is not None else "") + ")")
    return "\n".join(lines)
