"""Opt-in device trace capture — the deep-dive companion of the
roofline model.

The roofline names WHICH resource bounds a config; an on-chip XLA
trace shows WHERE inside the program the time actually goes (the
round-5 finding: the remaining gap needs on-chip profiling, not
another geometry sweep).  :func:`device_trace` wraps a code block in
``jax.profiler.trace`` (TensorBoard-loadable) and records the capture
as a ``profiler.trace`` telemetry event, so the emitted bench line /
tuning entry can carry its trace directory.

Gating — OFF by default, two ways in:

- ``KNN_TPU_PROFILE_DIR=<dir>``: the ambient env gate.  Honored only
  while telemetry is enabled (``KNN_TPU_OBS=0`` makes it a no-op,
  like every other obs surface).
- an explicit ``base_dir`` argument (bench's ``--trace-dir`` /
  ``KNN_BENCH_TRACE``): an explicit flag is an explicit request and
  captures regardless of the obs switch (only the telemetry event is
  skipped when obs is off).

JAX imports lazily inside the context — this module stays importable
(and a no-op) in jax-free consumers."""

from __future__ import annotations

import contextlib
import os
import re
import threading
import time
from typing import Dict, Iterator, Optional

from knn_tpu.obs import registry, trace

#: env gate: a directory under which each capture gets its own
#: ``<section>`` subdirectory
PROFILE_ENV = "KNN_TPU_PROFILE_DIR"

_SECTION_RE = re.compile(r"[^A-Za-z0-9._-]+")

_cap_lock = threading.Lock()
#: sanitized section -> last capture directory in this process.
#: Introspection only (doctor/tests ask "what did this process
#: capture, where?"); the reconciler matches events to configs by the
#: on-disk convention (traceread.read_section resolves
#: ``<dir>/<sanitized section>``), never through this map.  Bounded:
#: sections are config shapes, finite in practice.
_CAPTURES: Dict[str, str] = {}
_CAPTURES_MAX = 64


def captures() -> Dict[str, str]:
    """Every section captured in this process and its trace directory
    (newest last).  Process-local introspection; event→config matching
    itself rides the capture-directory convention traceread reads."""
    with _cap_lock:
        return dict(_CAPTURES)


def reset_captures() -> None:
    """Drop the capture registry (test isolation)."""
    with _cap_lock:
        _CAPTURES.clear()


def profile_dir() -> Optional[str]:
    """The ambient capture directory, or None when unset or telemetry
    is disabled."""
    if not registry.enabled():
        return None
    return os.environ.get(PROFILE_ENV) or None


def sanitize_section(section: str) -> str:
    """Filesystem-safe capture name (cache keys carry ``|`` and
    spaces)."""
    return _SECTION_RE.sub("_", section).strip("_") or "trace"


@contextlib.contextmanager
def device_trace(section: str,
                 base_dir: Optional[str] = None) -> Iterator[Optional[str]]:
    """Capture an XLA device trace of the wrapped block under
    ``<dir>/<section>``; yields the trace directory, or None when no
    gate is open (the caller can skip its extra instrumented run
    entirely)."""
    d = base_dir if base_dir is not None else profile_dir()
    if not d:
        yield None
        return
    path = os.path.join(d, sanitize_section(section))
    import jax

    t0 = time.perf_counter()
    with jax.profiler.trace(path):
        yield path
    with _cap_lock:
        _CAPTURES.pop(sanitize_section(section), None)
        _CAPTURES[sanitize_section(section)] = path
        while len(_CAPTURES) > _CAPTURES_MAX:
            _CAPTURES.pop(next(iter(_CAPTURES)))
    trace.emit_event("profiler.trace", section=sanitize_section(section),
                     trace_dir=path,
                     dur_s=round(time.perf_counter() - t0, 4))
