"""Jax-free reader for captured profiler artifacts — the parsing half
of the measured-ceiling loop (ROADMAP open item 1).

PR 6 built trace *capture* (:mod:`knn_tpu.obs.profiler` wraps
``jax.profiler.trace`` and writes a TensorBoard-loadable artifact under
``<dir>/<section>``); nothing in the repo could *read* one back.  This
module parses the two measured-time sources the calibration layer
(:mod:`knn_tpu.obs.calibrate`) reconciles against the roofline model:

- **device traces** — the trace-viewer ``*.trace.json.gz`` event
  stream the profiler leaves under
  ``<section>/plugins/profile/<run>/*.trace.json.gz``: gzipped Chrome
  trace JSON whose ``ph == "M"`` metadata events name each pid's track
  (``/device:TPU:0 ...``) and whose ``ph == "X"`` complete events carry
  per-kernel ``ts``/``dur`` in microseconds.  Device busy time is the
  INTERVAL UNION of the device tracks' complete events (two kernels
  overlapping on one track must not double-bill), so the sample is the
  chip's measured wall occupancy, directly comparable to the model's
  per-sweep term times.
- **host-side phase records** — the ``phase_breakdown`` block a bench
  line carries (``device_s`` measured by fenced ``perf_counter`` around
  the already-compiled program) and the waterfall's device segments.
  CPU-testable: tier-1 exercises the identical reconcile loop against
  these without a TPU (``cli campaign --rehearse``).

Event→config matching rides the capture convention: the profiler
writes each capture under its SANITIZED section name (the bench mode /
tuning cache key), so :func:`read_section` resolves a section back to
its artifact — a trace can never be reconciled against a config that
did not produce it.  Malformed artifacts raise :class:`TraceReadError`
LOUDLY (a silently-empty trace would calibrate the model against
nothing and call it measured).

Everything here is stdlib-only: gzip + json + glob.  No JAX import,
ever — the campaign's rehearse mode and the offline doctor both parse
on machines with no accelerator runtime.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Dict, List, Optional, Tuple

#: trace-viewer artifact glob under a capture directory (the layout
#: ``jax.profiler.trace`` writes: plugins/profile/<run>/<host>.trace.json.gz)
TRACE_GLOB = os.path.join("**", "*.trace.json.gz")

#: substrings that mark a metadata-named pid track as a DEVICE track
#: (XLA names them "/device:TPU:0", "/device:GPU:0", "TPU:0 (chip …)")
DEVICE_TRACK_MARKERS = ("/device:", "TPU", "GPU")

#: the two measured-time sources the reconciler accepts
SOURCES = ("device_trace", "host_phase")


class TraceReadError(ValueError):
    """A profiler artifact that cannot be parsed into a measured
    sample — raised LOUDLY: a malformed trace must never calibrate."""


def find_trace_files(root: str) -> List[str]:
    """Every ``*.trace.json.gz`` under ``root`` (sorted), or ``root``
    itself when it already names one.  Empty list when the directory
    exists but holds no artifact (the caller decides whether that is an
    error); :class:`TraceReadError` when ``root`` does not exist."""
    if os.path.isfile(root):
        return [root]
    if not os.path.isdir(root):
        raise TraceReadError(f"trace location {root!r} does not exist")
    return sorted(glob.glob(os.path.join(root, TRACE_GLOB),
                            recursive=True))


def read_trace_events(path: str) -> List[dict]:
    """The ``traceEvents`` list of one trace-viewer artifact.  Accepts
    gzipped or plain JSON; everything malformed raises
    :class:`TraceReadError` with the reason."""
    try:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt") as f:
            doc = json.load(f)
    except OSError as e:
        raise TraceReadError(f"{path}: unreadable: {e}") from e
    except (json.JSONDecodeError, UnicodeDecodeError, EOFError) as e:
        raise TraceReadError(
            f"{path}: not trace-viewer JSON: {e}") from e
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise TraceReadError(
            f"{path}: no traceEvents list — not a trace-viewer "
            f"artifact")
    return doc["traceEvents"]


def process_names(events: List[dict]) -> Dict[int, str]:
    """pid -> track name from the ``ph == "M"`` ``process_name``
    metadata events."""
    out: Dict[int, str] = {}
    for e in events:
        if not isinstance(e, dict):
            continue
        if e.get("ph") == "M" and e.get("name") == "process_name":
            args = e.get("args") or {}
            name = args.get("name")
            pid = e.get("pid")
            if isinstance(pid, int) and isinstance(name, str):
                out[pid] = name
    return out


def device_pids(events: List[dict]) -> Dict[int, str]:
    """The pids whose metadata track name looks like a DEVICE track
    (:data:`DEVICE_TRACK_MARKERS`).  Empty on host-only traces (CPU
    captures have no device lanes — the caller falls back to all
    tracks, flagged)."""
    return {pid: name for pid, name in process_names(events).items()
            if any(m in name for m in DEVICE_TRACK_MARKERS)}


def complete_events(events: List[dict],
                    pids: Optional[set] = None) -> List[dict]:
    """The ``ph == "X"`` complete events (the per-kernel ts/dur
    samples), optionally restricted to ``pids``."""
    out = []
    for e in events:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        if not isinstance(e.get("ts"), (int, float)) or \
                not isinstance(e.get("dur"), (int, float)):
            continue
        if pids is not None and e.get("pid") not in pids:
            continue
        out.append(e)
    return out


def _interval_union_s(evts: List[dict]) -> float:
    """Seconds covered by the union of the events' [ts, ts+dur)
    microsecond intervals — overlapping kernels on one track bill
    once."""
    iv: List[Tuple[float, float]] = sorted(
        (float(e["ts"]), float(e["ts"]) + float(e["dur"]))
        for e in evts)
    total = 0.0
    cur_a = cur_b = None
    for a, b in iv:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total / 1e6


def summarize_events(events: List[dict]) -> dict:
    """One artifact's measured-time summary: device busy seconds (the
    busiest device track's interval union — the term the roofline's
    per-sweep times model), kernel-event count, and whether real device
    tracks were matched (host-only CPU traces fall back to every track,
    flagged ``device_tracks_matched: false`` so a calibration can say
    which fidelity it was fit from)."""
    dev = device_pids(events)
    matched = bool(dev)
    tracks = dev or {
        pid: name for pid, name in process_names(events).items()}
    per_track = {}
    for pid in tracks or {e.get("pid") for e in events
                          if isinstance(e, dict)}:
        evts = complete_events(events, pids={pid})
        if evts:
            per_track[pid] = {
                "name": tracks.get(pid, str(pid)),
                "events": len(evts),
                "busy_s": round(_interval_union_s(evts), 6),
            }
    if not per_track:
        raise TraceReadError(
            "trace holds no complete (ph=X) events on any track — "
            "nothing measured to reconcile against")
    busiest = max(per_track.values(), key=lambda t: t["busy_s"])
    return {
        "device_tracks_matched": matched,
        "tracks": per_track,
        "kernel_events": sum(t["events"] for t in per_track.values()),
        "device_busy_s": busiest["busy_s"],
        "busiest_track": busiest["name"],
    }


def read_section(base_dir: str, section: str) -> dict:
    """Parse the capture the profiler wrote for ``section`` under
    ``base_dir`` — the event→config match: the profiler's capture
    convention (``<dir>/<sanitized section>``) ties each artifact to
    the config label that produced it, so a section that never captured
    raises instead of silently matching another config's kernels.
    Returns the :func:`summarize_events` summary plus the artifact
    paths."""
    from knn_tpu.obs.profiler import sanitize_section

    sect = sanitize_section(section)
    root = os.path.join(base_dir, sect)
    files = find_trace_files(root)
    if not files:
        raise TraceReadError(
            f"capture dir {root!r} holds no *.trace.json.gz artifact "
            f"(profiler ran but the runtime wrote no trace?)")
    # one capture = one timestamped run dir (plugins/profile/<run>/,
    # one artifact per host inside it).  Re-running into the same base
    # dir leaves the older runs on disk — merging them would union
    # stale kernel intervals into the sample (disjoint ts epochs, so
    # busy times ADD) and calibrate against a measurement the machine
    # never produced.  Only the NEWEST run's files enter.
    by_run: Dict[str, List[str]] = {}
    for p in files:
        by_run.setdefault(os.path.dirname(p), []).append(p)
    runs_found = len(by_run)
    if runs_found > 1:
        newest = max(by_run, key=lambda r: (os.path.getmtime(r), r))
        files = sorted(by_run[newest])
    merged: List[dict] = []
    for path in files:
        merged.extend(read_trace_events(path))
    summary = summarize_events(merged)
    summary["section"] = sect
    summary["trace_files"] = files
    summary["runs_found"] = runs_found
    return summary


def sample_from_trace(base_dir: str, section: str, *, nq: int) -> dict:
    """A measured sample (the reconciler's input) from a captured
    device trace: ``device_s`` is the busiest device track's interval
    union over the traced sweep of ``nq`` queries."""
    summary = read_section(base_dir, section)
    dev_s = summary["device_busy_s"]
    if dev_s <= 0:
        raise TraceReadError(
            f"section {section!r}: zero device busy time in the trace")
    return {
        "source": "device_trace",
        "device_s": dev_s,
        "nq": int(nq),
        "qps": round(nq / dev_s, 2),
        "section": summary["section"],
        "trace_files": summary["trace_files"],
        "kernel_events": summary["kernel_events"],
        "device_tracks_matched": summary["device_tracks_matched"],
    }


def sample_from_phases(phase_breakdown: dict, *, nq: int) -> dict:
    """A measured sample from a bench line's host-side
    ``phase_breakdown`` — the CPU-testable fallback source.  Only the
    fenced ``device_s`` phase enters: the structured ``transport``
    field (bench satellite) says whether the h2d/d2h phases rode the
    dev relay — relay latency is HARNESS time and must never land in a
    device-term residual, which is exactly why the old prose ``note``
    was not machine-usable."""
    if not isinstance(phase_breakdown, dict):
        raise TraceReadError(
            f"phase_breakdown is {type(phase_breakdown).__name__}, "
            f"not dict")
    dev_s = phase_breakdown.get("device_s")
    if not isinstance(dev_s, (int, float)) or dev_s <= 0:
        raise TraceReadError(
            f"phase_breakdown carries no positive device_s "
            f"({dev_s!r}) — nothing measured to reconcile against")
    transport = phase_breakdown.get("transport")
    excluded = None
    if isinstance(transport, dict) and \
            transport.get("kind") == "dev_relay" and \
            not transport.get("latency_corrected"):
        # relay transfer phases exist on the line but are excluded
        # from the device sample by construction; record what was
        # dropped so the provenance is auditable
        excluded = {
            k: phase_breakdown.get(k)
            for k in ("h2d_queries_s", "d2h_transfer_s")
            if isinstance(phase_breakdown.get(k), (int, float))
        } or None
    return {
        "source": "host_phase",
        "device_s": float(dev_s),
        "nq": int(nq),
        "qps": round(nq / float(dev_s), 2),
        "transport": transport,
        "relay_phases_excluded_s": excluded,
    }
