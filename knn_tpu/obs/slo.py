"""Declarative SLOs evaluated with multi-window burn rates over the
registry — the judgment layer the raw counters/histograms feed.

An **objective** is either

- a ``ratio`` (bad-event counter / total counter, e.g. serving errors
  per request) with an availability ``target``: the error budget is
  ``1 - target``, and the **burn rate** over a window is the window's
  error ratio divided by that budget (burn 1.0 = spending the budget
  exactly as fast as the SLO allows; burn 6.0 = six times too fast); or
- a ``quantile`` (a bounded-window histogram percentile, e.g. request
  p99 latency) against an absolute ``threshold``; its "burn rate" is
  value/threshold, reported under the pseudo-window ``hist``.

Counters in the registry are CUMULATIVE, so windowed ratios need
history: each :meth:`SLOEngine.evaluate` appends one timestamped sample
of every referenced counter to a bounded ring and computes deltas
against the sample closest to each window's far edge (the actual span
used is reported next to the requested one — window truth is always
labeled, never implied; the same contract the latency summaries
follow).  An objective **breaches** when EVERY configured window is
CONFIRMABLE (its actual span has reached at least ``MIN_SPAN_FRACTION``
of its requested span — one second of cold-start history must never
page the 600 s window) and burns at or above the objective's
``burn_threshold`` (ratio default 6x budget; quantile default 1x
threshold) — the classic multi-window guard: the slow window proves
sustained damage, the fast window proves it is still happening, so a
long-healed spike cannot page and a fresh spike cannot page off one
noisy minute.  The ring is thinned to one sample per
``slow_span / (SAMPLE_RING/2)`` seconds, so fast stats() polling can
never starve the slow window of stored history; evaluation itself is
serialized under one lock, so concurrent callers can never double-emit
a transition alert.

Breach state is EDGE-TRIGGERED: the healthy->breached transition emits
exactly one ``slo.alert`` event (``state="firing"``) into the trace
ring / JSONL sink, increments ``knn_tpu_slo_breach_transitions_total``,
and sets ``knn_tpu_slo_breached{objective}``; recovery emits one
``state="resolved"`` event and clears the gauge.  Re-evaluating a
still-breached objective re-reports it but never re-alerts.

Disabled mode (``KNN_TPU_OBS=0``): :func:`get_slo_engine` returns ONE
shared inert engine whose ``evaluate()`` returns ``{}`` — no samples,
no gauges, no events, no allocation on any caller's path.

Objectives are configurable via ``KNN_TPU_SLO_CONFIG`` (a JSON file:
``[{"name": ..., "kind": ..., ...}, ...]`` replacing the defaults);
:func:`load_objectives` validates every entry against the metric
catalog, and ``scripts/perf_sentinel.py --lint`` runs that validation
in CI without timing anything.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

from knn_tpu.obs import names, registry, trace

#: env var naming a JSON objectives file (unset = DEFAULT_OBJECTIVES)
CONFIG_ENV = "KNN_TPU_SLO_CONFIG"

#: (label, span seconds) — the fast window confirms a breach is live,
#: the slow one that it is sustained
DEFAULT_WINDOWS: Tuple[Tuple[str, float], ...] = (
    ("fast", 60.0), ("slow", 600.0))

#: counter-sample ring bound: at one evaluate per scrape (~15 s) this
#: holds over an hour of history, enough for the slow window
SAMPLE_RING = 256

#: a window may only CONFIRM a breach once its actual span reaches this
#: fraction of the requested span — a cold-start engine whose whole
#: history is one second old must not page the 600 s window off that
#: second (the exact failure multi-window burn rates exist to prevent)
MIN_SPAN_FRACTION = 0.5


@dataclass(frozen=True)
class Objective:
    """One declarative SLO.  ``kind="ratio"``: ``num``/``den`` are
    catalog counter names (all label series summed) and ``target`` is
    the availability goal (budget = 1 - target).  ``kind="quantile"``:
    ``hist`` is a catalog histogram name and ``threshold`` the absolute
    bound (seconds for the latency objectives) on ``quantile``.

    ``group_by`` names a label (e.g. ``"tenant"``) to evaluate the
    objective PER LABEL VALUE instead of over the summed surface: each
    value gets its own burn rates, breach state, and edge-triggered
    alert (reported as ``<name>:<value>``), so one tenant's burn pages
    that tenant, not the fleet."""

    name: str
    kind: str  # "ratio" | "quantile"
    num: Optional[str] = None
    den: Optional[str] = None
    target: Optional[float] = None
    hist: Optional[str] = None
    quantile: str = "p99"
    threshold: Optional[float] = None
    #: breach when every window burns at >= this multiple of budget
    #: (ratio default 6.0); for quantile objectives, value/threshold at
    #: >= this multiple (default 1.0 — the threshold IS the line).
    #: None = the kind's default.
    burn_threshold: Optional[float] = None
    #: evaluate per value of this label instead of summed (see above)
    group_by: Optional[str] = None

    @property
    def effective_burn_threshold(self) -> float:
        if self.burn_threshold is not None:
            return self.burn_threshold
        return 6.0 if self.kind == "ratio" else 1.0

    def validate(self) -> None:
        from knn_tpu.obs.names import CATALOG

        if self.kind == "ratio":
            for role, metric in (("num", self.num), ("den", self.den)):
                if metric not in CATALOG:
                    raise ValueError(
                        f"SLO {self.name!r}: {role}={metric!r} is not a "
                        f"catalog metric")
                if CATALOG[metric][0] != "counter":
                    raise ValueError(
                        f"SLO {self.name!r}: {role}={metric!r} must be a "
                        f"counter, is a {CATALOG[metric][0]}")
            if not (self.target is not None and 0.0 < self.target < 1.0):
                raise ValueError(
                    f"SLO {self.name!r}: ratio target must be in (0, 1), "
                    f"got {self.target}")
        elif self.kind == "quantile":
            if self.hist not in CATALOG:
                raise ValueError(
                    f"SLO {self.name!r}: hist={self.hist!r} is not a "
                    f"catalog metric")
            if CATALOG[self.hist][0] != "histogram":
                raise ValueError(
                    f"SLO {self.name!r}: hist={self.hist!r} must be a "
                    f"histogram, is a {CATALOG[self.hist][0]}")
            if self.quantile not in ("p50", "p95", "p99"):
                raise ValueError(
                    f"SLO {self.name!r}: quantile must be p50/p95/p99, "
                    f"got {self.quantile!r}")
            if not (self.threshold is not None and self.threshold > 0):
                raise ValueError(
                    f"SLO {self.name!r}: quantile threshold must be > 0, "
                    f"got {self.threshold}")
        else:
            raise ValueError(
                f"SLO {self.name!r}: kind must be 'ratio' or 'quantile', "
                f"got {self.kind!r}")
        if self.burn_threshold is not None and self.burn_threshold <= 0:
            raise ValueError(
                f"SLO {self.name!r}: burn_threshold must be > 0")
        if self.group_by is not None:
            from knn_tpu.obs.names import CATALOG

            metrics = ((self.num, self.den) if self.kind == "ratio"
                       else (self.hist,))
            for metric in metrics:
                if self.group_by not in CATALOG[metric][1]:
                    raise ValueError(
                        f"SLO {self.name!r}: group_by={self.group_by!r} "
                        f"is not a label of {metric!r} "
                        f"(labels: {sorted(CATALOG[metric][1])})")


#: the serving-stack defaults the ISSUE names: availability, tail
#: latency, queue wait, and the certified path's quality rates
DEFAULT_OBJECTIVES: Tuple[Objective, ...] = (
    Objective(name="serving_availability", kind="ratio",
              num=names.SERVING_ERRORS, den=names.SERVING_REQUESTS,
              target=0.999),
    Objective(name="serving_request_p99", kind="quantile",
              hist=names.SERVING_REQUEST_LATENCY, quantile="p99",
              threshold=1.0),
    Objective(name="queue_wait_p95", kind="quantile",
              hist=names.QUEUE_WAIT, quantile="p95",
              threshold=0.1),
    Objective(name="certified_fallback_rate", kind="ratio",
              num=names.CERTIFIED_FALLBACKS, den=names.CERTIFIED_QUERIES,
              target=0.95),
    Objective(name="certified_false_alarm_rate", kind="ratio",
              num=names.CERTIFIED_FALSE_ALARMS, den=names.CERTIFIED_QUERIES,
              target=0.99),
    # per-tenant attribution: the grouped objectives evaluate one burn
    # rate PER TENANT over the tenant-labeled serving metrics, so a
    # single tenant's burst pages as <name>:<tenant>, not globally.
    # Tenant-free processes produce no tenant series -> empty groups,
    # zero cost.
    Objective(name="tenant_availability", kind="ratio",
              num=names.TENANT_ERRORS, den=names.TENANT_REQUESTS,
              target=0.999, group_by="tenant"),
    Objective(name="tenant_request_p99", kind="quantile",
              hist=names.TENANT_REQUEST_LATENCY, quantile="p99",
              threshold=1.0, group_by="tenant"),
    # audited quality: deficient (recall@k < 1) audited queries per
    # replayed query, per tenant — the shadow audit sampler
    # (knn_tpu.obs.audit) feeds both counters; audit-free processes
    # produce no series -> empty groups, zero cost.  A breach writes
    # a postmortem bundle embedding the failing audit records.
    Objective(name="audit_recall", kind="ratio",
              num=names.AUDIT_DEFICIENT, den=names.AUDIT_REPLAYED,
              target=0.999, group_by="tenant"),
)


def load_objectives(path: Optional[str] = None) -> Tuple[Objective, ...]:
    """The configured objectives: ``path`` (or ``KNN_TPU_SLO_CONFIG``)
    names a JSON list replacing the defaults; every entry is validated
    against the catalog.  Raises ``ValueError`` on any bad entry — the
    lint gate (perf_sentinel --lint) runs this so a broken config fails
    in CI, not at serve time."""
    path = path or os.environ.get(CONFIG_ENV)
    if not path:
        objs = DEFAULT_OBJECTIVES
    else:
        with open(path) as f:
            raw = json.load(f)
        if not isinstance(raw, list) or not raw:
            raise ValueError(
                f"SLO config {path}: expected a non-empty JSON list")
        objs = tuple(Objective(**entry) for entry in raw)
    seen = set()
    for o in objs:
        if o.name in seen:
            raise ValueError(f"duplicate SLO objective name {o.name!r}")
        seen.add(o.name)
        o.validate()
    return objs


def _summed(snapshot: dict, name: str) -> float:
    """Sum of every label series of a counter (SLOs judge the whole
    surface; per-label drill-down is what the raw metric is for)."""
    m = snapshot.get(name)
    if not m:
        return 0.0
    return float(sum(s["value"] for s in m["series"]))


def _summed_by(snapshot: dict, name: str, label: str) -> Dict[str, float]:
    """Per-label-value sums of a counter — the grouped objectives'
    read: {label value: sum over the series carrying it}."""
    m = snapshot.get(name)
    out: Dict[str, float] = {}
    if not m:
        return out
    for s in m["series"]:
        val = s["labels"].get(label)
        if val is None:
            continue
        out[val] = out.get(val, 0.0) + float(s["value"])
    return out


def _group_key(name: str, label: str, value: str) -> str:
    """Composite sample-ring key for one label value of a grouped
    counter (the ring stores flat {key: float} samples either way)."""
    return f"{name}|{label}={value}"


def _hist_summary(snapshot: dict, name: str,
                  only: Optional[Tuple[str, str]] = None) -> Optional[dict]:
    """Merged summary across a histogram's label series (max of the
    quantiles — the conservative read for a threshold objective —
    plus combined window metadata).  ``only=(label, value)`` restricts
    the merge to series carrying that label value (grouped
    objectives)."""
    m = snapshot.get(name)
    if not m:
        return None
    merged: Optional[dict] = None
    for s in m["series"]:
        if only is not None and s["labels"].get(only[0]) != only[1]:
            continue
        v = s["value"]
        if "p50" not in v:
            continue
        if merged is None:
            merged = dict(v)
        else:
            for q in ("p50", "p95", "p99"):
                merged[q] = max(merged[q], v[q])
            merged["window"] = merged.get("window", 0) + v.get("window", 0)
            spans = [x for x in (merged.get("window_span_s"),
                                 v.get("window_span_s")) if x is not None]
            if spans:
                merged["window_span_s"] = max(spans)
    return merged


class SLOEngine:
    """Evaluates the objectives against the live registry; owns the
    counter-sample ring the burn-rate windows delta against.

    Thread-safety: guarded by ``self._lock`` (one lock over the whole
    read-evaluate-transition-append pass — see :meth:`evaluate`;
    machine-checked by the ``locked-mutation`` checker,
    knn_tpu.analysis)."""

    def __init__(self, objectives: Optional[Sequence[Objective]] = None,
                 windows: Sequence[Tuple[str, float]] = DEFAULT_WINDOWS,
                 clock=time.monotonic):
        self.objectives = tuple(
            load_objectives() if objectives is None else objectives)
        self.windows = tuple(windows)
        self._clock = clock
        self._lock = threading.Lock()
        #: (monotonic t, {counter name: summed value})
        self._samples: deque = deque(maxlen=SAMPLE_RING)
        #: thin the ring so it always spans the slowest window even
        #: under fast polling (a 10 Hz stats() dashboard must not cap
        #: the stored history at ring/10 seconds): keep at most one
        #: sample per interval, sized so half the ring covers the
        #: slowest window
        max_span = max((s for _, s in self.windows), default=600.0)
        self._min_sample_gap = max_span / (SAMPLE_RING // 2)
        self._breached: Dict[str, bool] = {}
        #: firing transitions collected DURING an evaluation pass (under
        #: the lock) and handed to the flight recorder AFTER it: the
        #: recorder re-reads health/metrics state whose own code paths
        #: evaluate SLOs, so invoking it lock-held would deadlock
        self._fired: list = []

    # -- window machinery --------------------------------------------------
    def _ratio_counters(self):
        """(counter name, group_by label or None) pairs the sample ring
        must track — grouped objectives store one composite key per
        label value instead of one summed key."""
        out = set()
        for o in self.objectives:
            if o.kind == "ratio":
                out.add((o.num, o.group_by))
                out.add((o.den, o.group_by))
        return out

    @staticmethod
    def _window_base(samples, now: float, span: float):
        """The sample the window deltas against: the NEWEST one at least
        ``span`` old (effective span >= requested — a stale-history
        evaluation dilutes toward lifetime truth instead of inventing a
        window it has no data for), else the OLDEST available."""
        base = None
        for t, vals in samples:
            if now - t >= span:
                base = (t, vals)
            else:
                break
        return base if base is not None else (
            samples[0] if samples else None)

    # -- evaluation --------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation pass: returns the ``slo`` report section and,
        on breach-state transitions, emits the alert events / bumps the
        transition counter.  ``now`` is injectable for deterministic
        tests; production callers leave it None."""
        if not registry.enabled():
            return {}
        now = self._clock() if now is None else float(now)
        snap = registry.snapshot()
        registry.counter(names.SLO_EVALUATIONS).inc()
        current: Dict[str, float] = {}
        for name, group_by in self._ratio_counters():
            if group_by is None:
                current[name] = _summed(snap, name)
            else:
                for val, s in _summed_by(snap, name, group_by).items():
                    current[_group_key(name, group_by, val)] = s
        report: dict = {"objectives": {}, "breached": [],
                        "evaluated_at": round(time.time(), 3)}
        # ONE lock over read-evaluate-transition-append: concurrent
        # evaluations (serving threads' stats(), the HTTP handlers)
        # must serialize here, or two of them could both observe a
        # healthy->breached edge and double-emit the alert the
        # exactly-once contract forbids
        with self._lock:
            samples = list(self._samples)
            for o in self.objectives:
                if o.group_by is not None:
                    entry = self._eval_grouped(o, samples, current, snap,
                                               now)
                    report["objectives"][o.name] = entry
                    for gval in entry["breached"]:
                        report["breached"].append(f"{o.name}:{gval}")
                    continue
                if o.kind == "ratio":
                    entry = self._eval_ratio(o, samples, current, now)
                else:
                    entry = self._eval_quantile(o, snap)
                report["objectives"][o.name] = entry
                self._transition(o, o.name, entry)
                if entry["breached"]:
                    report["breached"].append(o.name)
            # thinned append: bound the ring's TIME span from below so
            # fast polling cannot starve the slow window of history
            if (not self._samples
                    or now - self._samples[-1][0] >= self._min_sample_gap):
                self._samples.append((now, current))
            fired, self._fired = self._fired, []
        # flight recorder OUTSIDE the lock: one bundle per firing
        # transition (knn_tpu.obs.blackbox; no-op without
        # KNN_TPU_POSTMORTEM_DIR).  Edge-triggering above guarantees a
        # still-breached re-evaluation never lands here again.
        if fired:
            from knn_tpu.obs import blackbox

            for key, detail in fired:
                blackbox.on_breach(key, detail, slo_report=report)
        return report

    def _eval_grouped(self, o: Objective, samples, current, snap,
                      now) -> dict:
        """One evaluation per label value of ``o.group_by``: each value
        gets the full window/burn machinery under the composite
        objective key ``<name>:<value>`` (its own gauges, breach state,
        and edge-triggered alert carrying the group label).  No series
        for the label yet -> empty groups, nothing evaluated."""
        groups: Dict[str, dict] = {}
        if o.kind == "ratio":
            # discover groups from num AND den series: a tenant with
            # traffic but zero errors has no numerator series yet and
            # must still be evaluated (and read healthy)
            vals = set()
            for name in (o.num, o.den):
                prefix = _group_key(name, o.group_by, "")
                vals.update(key[len(prefix):] for key in current
                            if key.startswith(prefix))
            for val in sorted(vals):
                groups[val] = self._eval_ratio(
                    o, samples, current, now,
                    num_key=_group_key(o.num, o.group_by, val),
                    den_key=_group_key(o.den, o.group_by, val),
                    objective_label=f"{o.name}:{val}")
        else:
            m = snap.get(o.hist) or {}
            vals = sorted({s["labels"].get(o.group_by)
                           for s in m.get("series", ())} - {None})
            for val in vals:
                groups[val] = self._eval_quantile(
                    o, snap, only=(o.group_by, val),
                    objective_label=f"{o.name}:{val}")
        breached = []
        for val, entry in groups.items():
            self._transition(o, f"{o.name}:{val}", entry,
                             extra={o.group_by: val})
            if entry["breached"]:
                breached.append(val)
        return {"kind": o.kind, "group_by": o.group_by,
                "groups": groups, "breached": sorted(breached)}

    def _eval_ratio(self, o: Objective, samples, current, now, *,
                    num_key: Optional[str] = None,
                    den_key: Optional[str] = None,
                    objective_label: Optional[str] = None) -> dict:
        budget = 1.0 - o.target
        threshold = o.effective_burn_threshold
        num_key = o.num if num_key is None else num_key
        den_key = o.den if den_key is None else den_key
        objective_label = (o.name if objective_label is None
                           else objective_label)
        windows = {}
        confirms = []
        for label, span in self.windows:
            base = self._window_base(samples, now, span)
            if base is None:
                windows[label] = {"requested_s": span, "span_s": None,
                                  "ratio": None, "burn_rate": None,
                                  "confirmable": False}
                continue
            t0, vals0 = base
            actual = now - t0
            dn = current.get(num_key, 0.0) - vals0.get(num_key, 0.0)
            dd = current.get(den_key, 0.0) - vals0.get(den_key, 0.0)
            # bad events with NO denominator growth is the worst ratio,
            # not a healthy zero: a caller whose every request fails
            # before the success-side counter increments (errors grow,
            # requests don't) must breach, not hide behind div-by-zero
            ratio = (dn / dd) if dd > 0 else (1.0 if dn > 0 else 0.0)
            burn = ratio / budget if budget > 0 else 0.0
            # a window with too little history may not CONFIRM a
            # breach: one second of data must not page the 600 s
            # window (spans LONGER than requested are fine — they
            # dilute toward lifetime truth, the conservative side)
            confirmable = actual >= MIN_SPAN_FRACTION * span
            if confirmable:
                confirms.append(burn >= threshold)
            windows[label] = {
                "requested_s": span,
                "span_s": round(actual, 3),
                "confirmable": confirmable,
                "num_delta": dn, "den_delta": dd,
                "ratio": round(ratio, 6), "burn_rate": round(burn, 3),
            }
            registry.gauge(names.SLO_BURN_RATE, objective=objective_label,
                           window=label).set(burn)
        breached = (len(confirms) == len(self.windows)
                    and all(confirms))
        return {"kind": "ratio", "target": o.target, "budget": budget,
                "burn_threshold": threshold,
                "num": o.num, "den": o.den,
                "windows": windows, "breached": breached}

    def _eval_quantile(self, o: Objective, snap, *,
                       only: Optional[Tuple[str, str]] = None,
                       objective_label: Optional[str] = None) -> dict:
        s = _hist_summary(snap, o.hist, only=only)
        value = None if s is None else s.get(o.quantile)
        burn = None if value is None else value / o.threshold
        threshold = o.effective_burn_threshold  # quantile default 1.0
        if burn is not None:
            registry.gauge(
                names.SLO_BURN_RATE,
                objective=(o.name if objective_label is None
                           else objective_label),
                window="hist").set(burn)
        # which window the quantile came from rides the entry — the
        # number is meaningless without its sample count and wall span
        return {"kind": "quantile", "hist": o.hist,
                "quantile": o.quantile, "threshold_s": o.threshold,
                "burn_threshold": threshold,
                "value_s": None if value is None else round(value, 6),
                "burn_rate": None if burn is None else round(burn, 3),
                "window_samples": None if s is None else s.get("window"),
                "window_span_s": None if s is None else s.get(
                    "window_span_s"),
                "breached": bool(burn is not None
                                 and burn >= threshold)}

    def _transition(self, o: Objective, key: str, entry: dict,
                    extra: Optional[dict] = None) -> None:
        """Edge-triggered breach bookkeeping for one objective (or one
        GROUP of a grouped objective — ``key`` is ``name:value`` then,
        and ``extra`` carries the group label into the alert event).
        Caller holds ``self._lock`` (evaluate()'s single pass)."""
        was = self._breached.get(key, False)
        is_now = entry["breached"]
        registry.gauge(names.SLO_BREACHED, objective=key).set(
            1.0 if is_now else 0.0)
        if is_now == was:
            return
        self._breached[key] = is_now
        detail = {k: entry[k] for k in ("windows", "value_s", "burn_rate",
                                        "window_samples", "window_span_s")
                  if k in entry}
        if extra:
            detail.update(extra)
        if is_now:
            registry.counter(names.SLO_BREACH_TRANSITIONS,
                             objective=key).inc()
            trace.emit_event("slo.alert", objective=key,
                             state="firing", kind=o.kind, **detail)
            # queue the flight-recorder dump for after the lock drops
            self._fired.append((key, detail))
        else:
            trace.emit_event("slo.alert", objective=key,
                             state="resolved", kind=o.kind, **detail)

    def active_breaches(self):
        with self._lock:
            return sorted(n for n, b in self._breached.items() if b)


class _NoopSLOEngine:
    """Disabled-mode stand-in: ONE shared inert engine (the registry's
    no-op discipline) — evaluate allocates nothing and returns {}."""

    __slots__ = ()
    objectives: Tuple[Objective, ...] = ()

    def evaluate(self, now: Optional[float] = None) -> dict:
        return {}

    def active_breaches(self):
        return []


NOOP_SLO = _NoopSLOEngine()

_state_lock = threading.Lock()
_engine = None


def get_slo_engine() -> SLOEngine:
    """The process-wide SLO engine (objectives from the env config or
    the defaults); the shared no-op when the subsystem is disabled."""
    global _engine
    if not registry.enabled():
        return NOOP_SLO
    eng = _engine
    if eng is None or isinstance(eng, _NoopSLOEngine):
        with _state_lock:
            if _engine is None or isinstance(_engine, _NoopSLOEngine):
                _engine = SLOEngine()
            eng = _engine
    return eng


def reset_slo_engine(objectives: Optional[Sequence[Objective]] = None):
    """Swap in a fresh engine (clears samples + breach state); tests."""
    global _engine
    with _state_lock:
        _engine = (SLOEngine(objectives)
                   if registry.enabled() else NOOP_SLO)
        return _engine


def slo_report(now: Optional[float] = None) -> dict:
    """Evaluate-and-report: the ``slo`` section ServingEngine.stats()
    and JobResult.metrics() embed ({} when disabled)."""
    return get_slo_engine().evaluate(now=now)


# -- fleet evaluation (knn_tpu.obs.fleet) ----------------------------------
# The fleet plane merges N processes' telemetry into one surface
# (counters summed, histogram buckets added element-wise); these
# functions evaluate the SAME objectives over that merged surface.
# Two deliberate differences from the per-process engine:
#
# - LIFETIME ratios, not windowed burn rates: the fleet aggregator has
#   no cross-process sample ring, so a ratio objective judges the
#   merged lifetime num/den against the error budget directly.
# - quantiles come ONLY from the merged cumulative buckets
#   (registry.quantile_from_buckets over the element-wise sum) — never
#   from combining per-host percentiles.  _hist_summary's
#   max-of-quantiles is the conservative SINGLE-PROCESS read; across a
#   fleet it would overstate every host but the worst, and averaging
#   would be meaningless.

_FLEET_QFRAC = {"p50": 0.50, "p95": 0.95, "p99": 0.99}


def _fleet_counter_sum(counters: dict, name: str,
                       only: Optional[Tuple[str, str]] = None) -> float:
    total = 0.0
    for s in counters.get(name, ()):
        if only is not None and s["labels"].get(only[0]) != only[1]:
            continue
        total += float(s["value"])
    return total


def _fleet_label_values(counters: dict, name: str, label: str):
    vals = set()
    for s in counters.get(name, ()):
        v = s["labels"].get(label)
        if v is not None:
            vals.add(v)
    return vals


def _fleet_quantile(hists: dict, name: str, q: str,
                    only: Optional[Tuple[str, str]] = None
                    ) -> Tuple[Optional[float], float]:
    """(quantile, count) of the merged bucket vectors across the
    name's matching label series — sums the cumulative vectors first,
    takes the quantile of the SUM."""
    merged: Optional[list] = None
    count = 0.0
    for s in hists.get(name, ()):
        if only is not None and s["labels"].get(only[0]) != only[1]:
            continue
        cum = s.get("buckets")
        if not cum:
            continue
        count += float(s.get("count", 0))
        merged = (list(cum) if merged is None
                  else [a + b for a, b in zip(merged, cum)])
    if merged is None:
        return None, count
    return registry.quantile_from_buckets(
        merged, _FLEET_QFRAC.get(q, 0.99)), count


def _eval_fleet_one(o: Objective, counters: dict, hists: dict,
                    only: Optional[Tuple[str, str]] = None) -> dict:
    if o.kind == "ratio":
        num = _fleet_counter_sum(counters, o.num, only)
        den = _fleet_counter_sum(counters, o.den, only)
        ratio = (num / den) if den > 0 else None
        budget = 1.0 - o.target
        breached = bool(ratio is not None and budget > 0
                        and ratio > budget)
        return {"kind": "ratio", "source": "fleet_lifetime",
                "num": num, "den": den,
                "value": None if ratio is None else round(ratio, 6),
                "budget": round(budget, 6), "breached": breached}
    value, count = _fleet_quantile(hists, o.hist, o.quantile, only)
    threshold = o.effective_burn_threshold
    breached = bool(value is not None and o.threshold
                    and value / o.threshold >= threshold)
    return {"kind": "quantile", "source": "merged_buckets",
            "hist": o.hist, "quantile": o.quantile,
            "threshold_s": o.threshold,
            "value": None if value is None else round(value, 9),
            "samples": count, "breached": breached}


def evaluate_fleet(counters: dict, hists: dict,
                   objectives: Optional[Sequence[Objective]] = None
                   ) -> dict:
    """Stateless fleet SLO evaluation over the merged report's
    ``counters``/``histograms`` sections (knn_tpu.obs.fleet.merge).
    Grouped objectives expand per label value, ``name:value`` keys like
    the per-process engine."""
    objs = load_objectives() if objectives is None else tuple(objectives)
    out: dict = {"source": "fleet", "objectives": {}}
    for o in objs:
        if o.group_by is None:
            out["objectives"][o.name] = _eval_fleet_one(
                o, counters, hists)
            continue
        surface = o.den if o.kind == "ratio" else None
        values = (_fleet_label_values(counters, surface, o.group_by)
                  if surface is not None else
                  {s["labels"].get(o.group_by)
                   for s in hists.get(o.hist, ())
                   if s["labels"].get(o.group_by) is not None})
        for v in sorted(values):
            out["objectives"][f"{o.name}:{v}"] = _eval_fleet_one(
                o, counters, hists, only=(o.group_by, v))
    out["breached"] = sorted(
        k for k, e in out["objectives"].items() if e["breached"])
    return out


class FleetSLOEngine:
    """Edge-triggered breach bookkeeping over successive fleet
    evaluations (the /fleetz poll loop): :meth:`observe` takes one
    ``evaluate_fleet`` report and returns the [(key, detail)] list of
    healthy->breached transitions — exactly one firing per edge, like
    the per-process engine.  The caller (knn_tpu.obs.fleet.observe)
    turns each into a ``fleet.alert`` event + a fleet postmortem
    bundle embedding every member snapshot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._breached: Dict[str, bool] = {}

    def observe(self, fleet_slo: dict) -> list:
        fired = []
        with self._lock:
            for key in sorted(fleet_slo.get("objectives", {})):
                entry = fleet_slo["objectives"][key]
                was = self._breached.get(key, False)
                is_now = bool(entry["breached"])
                entry["state"] = "breached" if is_now else "healthy"
                if is_now == was:
                    continue
                self._breached[key] = is_now
                if is_now:
                    fired.append((key, entry))
        return fired

    def active_breaches(self):
        with self._lock:
            return sorted(n for n, b in self._breached.items() if b)
