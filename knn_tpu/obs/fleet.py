"""Fleet observability plane: N processes' telemetry merged into ONE
cross-host report — jax-free, like every offline obs surface.

Every obs surface so far is per-process: ``/metrics.json`` snapshots one
registry, ``/statusz`` diagnoses one process, a waterfall reconstructs
one host's spans.  A multi-host replica (knn_tpu.parallel.multihost) is
N of those — and "what is the fleet's p99" is NOT answerable from N
per-process p99s (percentiles do not average; the mean of two p99s is a
number with no operational meaning).  This module is the sound merge:

- **counters sum.**  Lifetime monotone counts add across processes —
  the fleet served ``sum(requests)`` requests, full stop.  Members are
  summed in sorted key order, so the same member set always produces
  the bitwise-identical total.
- **gauges keep their host.**  A queue depth averaged across hosts is
  fiction; the fleet report keeps every gauge PER HOST plus min / max /
  argmax rollups, so "which host" survives the merge.
- **quantiles merge through buckets, never through percentiles.**
  Every histogram exports cumulative counts over the ONE fixed
  ``registry.BUCKET_BOUNDS`` grid; identical bounds in every process
  means the cumulative vectors add element-wise, and the fleet
  quantile is taken from the SUM (``registry.quantile_from_buckets`` —
  a sound upper estimate).  The per-host window quantiles are carried
  too, labeled per host; they are never combined.

Collection reads live ``/metrics.json`` + ``/statusz`` (+
``/waterfallz`` for stitched cross-host waterfalls) from the
``KNN_TPU_FLEET_MEMBERS`` host:port list, or offline snapshot files
written by ``export.write_json_snapshot`` (``cli fleet
--snapshot-dir``).  Every payload is keyed by its identity stamp
(knn_tpu.obs.ident).

Degraded modes are LOUD, never silently narrower numbers:

- an unreachable endpoint / unreadable or torn snapshot lists the
  member under ``unreachable`` with the reason;
- a snapshot older than the newest by more than ``KNN_TPU_FLEET_STALE_S``
  seconds is refused as stale (an older collection round summed in
  would silently understate every counter) and listed under
  ``unreachable`` with a ``stale`` reason;
- a member whose ``catalog_version`` differs from ours is refused
  under ``skewed`` — summing a counter whose meaning changed between
  catalog versions would silently produce nonsense;
- any of these flips ``partial`` true; ``cli fleet`` exits 2 on a
  partial fleet.

Fleet SLO: the merged counters/buckets feed
``slo.FleetSLOEngine`` (lifetime ratios; quantiles ONLY from merged
buckets).  Edge-triggered fleet alerts write a postmortem bundle
embedding EVERY member's snapshot plus the stitched cross-host
waterfalls, next to the per-process bundles (knn_tpu.obs.blackbox).

Served by ``/fleetz`` (knn_tpu.obs.export) and ``python -m knn_tpu.cli
fleet``.  Schema: docs/OBSERVABILITY.md "Fleet observability".
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from knn_tpu.obs import names, registry, trace

#: comma/space-separated ``host:port`` list of member metric endpoints
MEMBERS_ENV = "KNN_TPU_FLEET_MEMBERS"

#: refuse members whose snapshot is older than the newest by more than
#: this many seconds (an older collection round merged in would
#: silently understate the fleet)
STALE_ENV = "KNN_TPU_FLEET_STALE_S"
DEFAULT_STALE_S = 120.0

#: per-member HTTP timeout for live collection
DEFAULT_TIMEOUT_S = 3.0

#: fleet report schema version (the ``fleet`` artifact block pins it)
FLEET_VERSION = 1

_QS = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


def fleet_members() -> List[str]:
    """The configured member endpoints (``KNN_TPU_FLEET_MEMBERS``)."""
    raw = os.environ.get(MEMBERS_ENV, "")
    return [m for m in re.split(r"[,\s]+", raw) if m]


def stale_threshold_s() -> float:
    try:
        return float(os.environ.get(STALE_ENV, DEFAULT_STALE_S))
    except ValueError:
        return DEFAULT_STALE_S


# -- collection ------------------------------------------------------------
def _http_json(url: str, timeout_s: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def _member_record(member: str, *, identity=None, metrics=None,
                   health=None, written_at_unix=None, stitched=None,
                   error: Optional[str] = None) -> dict:
    return {"member": member, "identity": identity or {},
            "metrics": metrics or {}, "health": health,
            "written_at_unix": written_at_unix, "stitched": stitched,
            "error": error}


def collect_live(members: Sequence[str],
                 timeout_s: float = DEFAULT_TIMEOUT_S) -> List[dict]:
    """One record per configured endpoint: ``/metrics.json`` (identity +
    metrics), ``/statusz`` (health, incl. the multihost section), and
    best-effort ``/waterfallz`` (stitched cross-host waterfalls).  A
    failing member degrades to an ``error`` record — collection never
    raises on an unreachable fleet."""
    out = []
    for m in members:
        base = m if "://" in m else f"http://{m}"
        try:
            snap = _http_json(base + "/metrics.json", timeout_s)
            if not isinstance(snap, dict) or "metrics" not in snap:
                raise ValueError("no metrics section in /metrics.json")
        except Exception as e:  # noqa: BLE001 — degrade, never raise
            out.append(_member_record(
                m, error=f"{type(e).__name__}: {e}"))
            continue
        health = stitched = None
        try:
            health = _http_json(base + "/statusz", timeout_s)
        except Exception:  # noqa: BLE001 — statusz is best-effort
            pass
        try:
            wf = _http_json(base + "/waterfallz", timeout_s)
            stitched = (wf.get("multihost") or {}).get("waterfalls")
        except Exception:  # noqa: BLE001 — waterfalls are best-effort
            pass
        out.append(_member_record(
            m, identity=snap.get("identity"), metrics=snap["metrics"],
            health=health, written_at_unix=snap.get("written_at_unix"),
            stitched=stitched))
    return out


def collect_snapshot_files(paths: Sequence[str]) -> List[dict]:
    """One record per snapshot file (``export.write_json_snapshot``
    payloads).  Unreadable / torn / shapeless files degrade to
    ``error`` records — the merge lists them loudly instead of summing
    a partial fleet silently."""
    out = []
    for p in paths:
        try:
            with open(p) as f:
                payload = json.load(f)
            if not isinstance(payload, dict) or "metrics" not in payload:
                raise ValueError("not a metrics snapshot (no metrics)")
        except Exception as e:  # noqa: BLE001 — degrade, never raise
            out.append(_member_record(
                os.path.basename(p), error=f"{type(e).__name__}: {e}"))
            continue
        out.append(_member_record(
            os.path.basename(p), identity=payload.get("identity"),
            metrics=payload["metrics"], health=payload.get("health"),
            written_at_unix=payload.get("written_at_unix")))
    return out


def collect_snapshot_dir(d: str) -> Tuple[List[dict], Dict[str, dict]]:
    """Offline collection from a directory: every ``*.json`` is a member
    snapshot; every ``*.jsonl`` (+ rotated ``.jsonl.1``) is an event log
    whose ``multihost.merge`` spans are stitched into cross-host
    waterfalls (knn_tpu.obs.waterfall.stitch_multihost)."""
    from knn_tpu.obs import waterfall

    snaps = sorted(f for f in os.listdir(d) if f.endswith(".json"))
    members = collect_snapshot_files(
        [os.path.join(d, f) for f in snaps])
    events: List[dict] = []
    for f in sorted(os.listdir(d)):
        if f.endswith(".jsonl"):
            try:
                events.extend(
                    waterfall.read_jsonl_events(os.path.join(d, f)))
            except Exception:  # noqa: BLE001 — logs are best-effort
                pass
    return members, waterfall.stitch_multihost(events)


# -- the merge -------------------------------------------------------------
def _member_key(rec: dict) -> str:
    ident = rec.get("identity") or {}
    host = ident.get("host")
    if host is not None:
        return f"{host}/{ident.get('process_index', 0)}"
    return str(rec["member"])


def merge(collected: Sequence[dict], *,
          stale_s: Optional[float] = None,
          stitched: Optional[Dict[str, dict]] = None) -> dict:
    """The fleet report over collected member records (module
    docstring).  Publishes the ``knn_tpu_fleet_*`` gauges when
    telemetry is on."""
    stale_s = stale_threshold_s() if stale_s is None else float(stale_s)
    ours = names.catalog_version()
    unreachable: List[dict] = []
    skewed: List[dict] = []
    ok: List[Tuple[str, dict]] = []
    for rec in collected:
        if rec.get("error"):
            unreachable.append(
                {"member": rec["member"], "reason": rec["error"]})
            continue
        cv = (rec.get("identity") or {}).get("catalog_version")
        if cv is not None and cv != ours:
            skewed.append({"member": rec["member"],
                           "catalog_version": cv, "expected": ours})
            continue
        ok.append((_member_key(rec), rec))
    # duplicate keys (two snapshots of one process) keep the newest
    by_key: Dict[str, dict] = {}
    for key, rec in ok:
        prev = by_key.get(key)
        if prev is None or ((rec.get("written_at_unix") or 0)
                            >= (prev.get("written_at_unix") or 0)):
            by_key[key] = rec
    # stale refusal: a member more than stale_s older than the newest
    # is a different collection round — summing it in would silently
    # understate every counter
    stamps = {k: r["written_at_unix"] for k, r in by_key.items()
              if r.get("written_at_unix") is not None}
    staleness = (round(max(stamps.values()) - min(stamps.values()), 3)
                 if stamps else 0.0)
    if stamps:
        newest = max(stamps.values())
        for k in sorted(by_key):
            ts = stamps.get(k)
            if ts is not None and newest - ts > stale_s:
                unreachable.append({
                    "member": by_key[k]["member"],
                    "reason": (f"stale snapshot: {round(newest - ts, 3)}s "
                               f"older than the newest member "
                               f"(threshold {stale_s}s)")})
                del by_key[k]
        stamps = {k: v for k, v in stamps.items() if k in by_key}
        staleness = (round(max(stamps.values()) - min(stamps.values()), 3)
                     if stamps else 0.0)
    keys = sorted(by_key)  # deterministic merge order
    counters, gauges, hists = _merge_metrics(keys, by_key)
    wfs = dict(stitched or {})
    for k in keys:
        for tid, w in (by_key[k].get("stitched") or {}).items():
            prev = wfs.get(tid)
            if prev is None or ((w.get("total_s") or 0)
                                > (prev.get("total_s") or 0)):
                wfs[tid] = w
    mh = _merge_multihost(keys, by_key)
    partial = bool(unreachable or skewed)
    # the FULL report is a superset of the validated `fleet` artifact
    # block — artifact_block() is the schema's emitter, so the version
    # stamp rides outside this literal
    report = {
        "generated_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "enabled": True,
        "catalog_version": ours,
        "partial": partial,
        "member_count": len(keys),
        "expected": len(collected),
        "members": [{
            "key": k,
            "member": by_key[k]["member"],
            "identity": by_key[k].get("identity") or {},
            "written_at_unix": by_key[k].get("written_at_unix"),
        } for k in keys],
        "unreachable": unreachable,
        "skewed": skewed,
        "staleness_s": staleness,
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
        "multihost": mh,
        # cross-host waterfalls stitched from multihost.merge spans
        "waterfalls": wfs or None,
    }
    report["fleet_version"] = FLEET_VERSION
    from knn_tpu.obs import slo

    report["slo"] = slo.evaluate_fleet(counters, hists)
    _publish_gauges(report)
    return report


def _merge_metrics(keys, by_key):
    """counters sum / gauges keep-per-host / histograms bucket-merge —
    the one place the three instrument kinds' merge semantics live."""
    counters: Dict[str, list] = {}
    gauges: Dict[str, list] = {}
    hists: Dict[str, list] = {}
    # (name, sorted-labels) -> {member key: series value}
    series: Dict[Tuple[str, tuple], Dict[str, dict]] = {}
    kinds: Dict[str, str] = {}
    for k in keys:
        for name, m in (by_key[k].get("metrics") or {}).items():
            kinds[name] = m.get("type", "gauge")
            for s in m.get("series", ()):
                lk = (name, tuple(sorted(s["labels"].items())))
                series.setdefault(lk, {})[k] = s
    for (name, litems) in sorted(series):
        labels = dict(litems)
        per = series[(name, litems)]
        kind = kinds[name]
        if kind == "counter":
            per_host = {k: float(per[k]["value"]) for k in sorted(per)}
            counters.setdefault(name, []).append({
                "labels": labels,
                # sorted-key order: the same member set always sums to
                # the bitwise-identical total
                "value": sum(per_host[k] for k in sorted(per_host)),
                "per_host": per_host,
            })
        elif kind == "gauge":
            per_host = {k: float(per[k]["value"]) for k in sorted(per)}
            argmax = max(sorted(per_host), key=lambda k: per_host[k])
            gauges.setdefault(name, []).append({
                "labels": labels,
                "per_host": per_host,
                "min": min(per_host.values()),
                "max": per_host[argmax],
                "argmax": argmax,
            })
        else:  # histogram
            hists.setdefault(name, []).append(
                _merge_hist_series(labels, per))
    return counters, gauges, hists


def _merge_hist_series(labels: dict, per: Dict[str, dict]) -> dict:
    """One histogram label-series across members: lifetime count/sum
    add; cumulative bucket vectors add element-wise (identical
    ``registry.BUCKET_BOUNDS`` in every process — catalog-version
    skew is refused before we get here); the FLEET quantiles come from
    the merged vector ONLY.  The per-host window quantiles ride along
    labeled by host — they are never combined (max-of-quantiles is the
    single-process conservative read in slo._hist_summary; across a
    fleet it would overstate every host but the worst)."""
    merged_cum: Optional[List[float]] = None
    window: Dict[str, dict] = {}
    count = 0.0
    total = 0.0
    for k in sorted(per):
        v = per[k]["value"]
        count += float(v.get("count", 0))
        total += float(v.get("sum", 0.0))
        cum = v.get("buckets")
        if cum:
            merged_cum = (list(cum) if merged_cum is None
                          else [a + b for a, b in zip(merged_cum, cum)])
        window[k] = {q: v[q] for q, _ in _QS if q in v}
        if "count" in v:
            window[k]["count"] = v["count"]
    fleet_q = None
    if merged_cum is not None:
        fleet_q = {q: registry.quantile_from_buckets(merged_cum, frac)
                   for q, frac in _QS}
        fleet_q["source"] = "merged_buckets"
    return {"labels": labels, "count": count, "sum": round(total, 9),
            "buckets": merged_cum, "fleet_quantiles": fleet_q,
            "window_quantiles_per_host": window}


def _merge_multihost(keys, by_key) -> Optional[dict]:
    """The fleet's straggler verdict from the members' /statusz
    multihost sections: name the argmax host (by its last DCN-merge
    local wall) instead of reporting one max-minus-min scalar."""
    sections = {}
    for k in keys:
        mh = (by_key[k].get("health") or {}).get("multihost")
        if mh:
            sections[k] = mh
    if not sections:
        return None
    # the authoritative section: every process records the same walls,
    # so any one suffices — take the newest-stamped member's
    auth_key = max(sorted(sections),
                   key=lambda k: by_key[k].get("written_at_unix") or 0)
    auth = dict(sections[auth_key])
    walls = auth.get("host_walls_s") or []
    straggler = auth.get("straggler_host")
    if straggler is None and walls:
        straggler = max(range(len(walls)), key=lambda i: walls[i])
    # map the straggler process index back to a member key when one of
    # the merged members IS that process
    straggler_key = None
    for k in keys:
        ident = by_key[k].get("identity") or {}
        if ident.get("process_index") == straggler:
            straggler_key = k
            break
    return {
        "reported_by": auth_key,
        "host_walls_s": walls,
        "straggler_host": straggler,
        "straggler_member": straggler_key,
        "straggler_gap_s": auth.get("straggler_gap_s"),
        "per_member": sections,
    }


def _publish_gauges(report: dict) -> None:
    if not registry.enabled():
        return
    registry.gauge(names.FLEET_MEMBERS).set(float(report["member_count"]))
    registry.gauge(names.FLEET_UNREACHABLE).set(
        float(len(report["unreachable"]) + len(report["skewed"])))
    registry.gauge(names.FLEET_MERGE_STALENESS).set(
        float(report["staleness_s"]))
    mh = report.get("multihost") or {}
    straggler_key = mh.get("straggler_member")
    if straggler_key is not None:
        for m in report["members"]:
            registry.gauge(names.FLEET_STRAGGLER_HOST,
                           host=m["key"]).set(
                1.0 if m["key"] == straggler_key else 0.0)


# -- fleet SLO edge + postmortems ------------------------------------------
_engine_lock = threading.Lock()
_engine = None


def _get_fleet_engine():
    global _engine
    with _engine_lock:
        if _engine is None:
            from knn_tpu.obs import slo

            _engine = slo.FleetSLOEngine()
        return _engine


def reset_fleet_engine() -> None:
    """Drop the edge state (tests)."""
    global _engine
    with _engine_lock:
        _engine = None


def observe(report: dict, collected: Sequence[dict]) -> None:
    """Feed one merged report through the edge-triggered fleet SLO
    engine; each healthy->breached transition emits one ``fleet.alert``
    event and writes one fleet postmortem bundle embedding EVERY
    member's snapshot plus the stitched cross-host waterfalls."""
    fired = _get_fleet_engine().observe(report.get("slo") or {})
    for key, detail in fired:
        trace.emit_event("fleet.alert", objective=key, state="firing",
                         **{k: v for k, v in detail.items()
                            if k != "state"
                            and isinstance(v, (int, float, str, bool))})
        _write_fleet_bundle(key, detail, report, collected)


def _write_fleet_bundle(objective: str, detail: dict, report: dict,
                        collected: Sequence[dict]) -> Optional[str]:
    """One fleet postmortem bundle per firing transition, next to the
    per-process bundles (same dir, same retention, ``fleet_`` objective
    prefix in the filename) — atomic, failure-proof."""
    from knn_tpu.obs import blackbox

    d = blackbox.postmortem_dir()
    if d is None or not registry.enabled():
        return None
    try:
        payload = {
            "version": blackbox.BUNDLE_VERSION,
            "kind": "fleet",
            "written_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "pid": os.getpid(),
            "objective": objective,
            "state": "firing",
            "breach_detail": detail,
            "fleet": report,
            # every member's raw collection record: the per-host truth
            # behind the merged numbers
            "members": {str(rec["member"]): {
                "identity": rec.get("identity"),
                "metrics": rec.get("metrics"),
                "health": rec.get("health"),
                "written_at_unix": rec.get("written_at_unix"),
                "error": rec.get("error"),
            } for rec in collected},
            "waterfalls": report.get("waterfalls"),
        }
        os.makedirs(d, exist_ok=True)
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", objective)[:56]
        fname = (f"postmortem-"
                 f"{time.strftime('%Y%m%dT%H%M%S', time.gmtime())}"
                 f"-0000-fleet_{safe}.json")
        path = os.path.join(d, fname)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f, sort_keys=True, default=str)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return path
    except Exception as e:  # noqa: BLE001 — recorder must never raise
        try:
            trace.emit_event("postmortem.error", objective=objective,
                             error=f"{type(e).__name__}: {e}")
        except Exception:  # pragma: no cover - double fault
            pass
        return None


# -- entry points ----------------------------------------------------------
def fleet_report(members: Optional[Sequence[str]] = None, *,
                 snapshot_dir: Optional[str] = None,
                 snapshot_files: Optional[Sequence[str]] = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 stale_s: Optional[float] = None) -> dict:
    """Collect + merge + edge-evaluate, one call: live endpoints
    (``members``, default ``KNN_TPU_FLEET_MEMBERS``) or offline
    snapshots (``snapshot_dir`` / ``snapshot_files``)."""
    stitched: Dict[str, dict] = {}
    if snapshot_dir is not None:
        collected, stitched = collect_snapshot_dir(snapshot_dir)
    elif snapshot_files is not None:
        collected = collect_snapshot_files(snapshot_files)
    else:
        members = fleet_members() if members is None else list(members)
        if not members:
            return {"enabled": False, "fleet_version": FLEET_VERSION,
                    "reason": f"{MEMBERS_ENV} not set and no snapshot "
                              f"source given"}
        collected = collect_live(members, timeout_s)
    report = merge(collected, stale_s=stale_s, stitched=stitched)
    observe(report, collected)
    return report


def live_fleet_report() -> dict:
    """What ``/fleetz`` serves: the merged report over
    ``KNN_TPU_FLEET_MEMBERS``, or a loud disabled/unconfigured stub.
    ``KNN_TPU_OBS=0`` turns the whole plane off — no collection, no
    merge, no gauges."""
    if not registry.enabled():
        return {"enabled": False, "fleet_version": FLEET_VERSION,
                "reason": "telemetry disabled (KNN_TPU_OBS=0)"}
    if not fleet_members():
        return {"enabled": False, "fleet_version": FLEET_VERSION,
                "reason": f"{MEMBERS_ENV} not set"}
    return fleet_report()


def artifact_block(report: dict) -> dict:
    """The validated ``fleet`` artifact block (one BlockSchema entry in
    knn_tpu/analysis/artifacts.py drives validator / refusal / sweep /
    docs lockstep): the merged report's flat, bounded headline shape —
    what bench lines and ``cli fleet --json`` carry instead of the full
    report."""
    if not report.get("enabled", True):
        return {"fleet_version": FLEET_VERSION,
                "member_count": 0,
                "error": report.get("reason")}
    mh = report.get("multihost") or {}
    return {
        "fleet_version": FLEET_VERSION,
        "catalog_version": report["catalog_version"],
        "member_count": report["member_count"],
        "expected_members": report["expected"],
        "unreachable_count": len(report["unreachable"]),
        "skewed_count": len(report["skewed"]),
        "partial": report["partial"],
        "staleness_s": report["staleness_s"],
        "straggler_host": mh.get("straggler_host"),
        "straggler_gap_s": mh.get("straggler_gap_s"),
        "stitched_requests": len(report.get("waterfalls") or {}),
        "slo_breached": len((report.get("slo") or {}).get("breached")
                            or ()),
    }


def render_text(report: dict) -> str:
    """The ``cli fleet`` text rendering (jax-free, offline-capable)."""
    if not report.get("enabled", True):
        return f"fleet: disabled ({report.get('reason')})"
    lines = [
        f"fleet report v{report['fleet_version']} "
        f"@ {report['generated_at']}  catalog {report['catalog_version']}",
        f"  members merged: {report['member_count']}/{report['expected']}"
        + ("  PARTIAL" if report["partial"] else "")
        + f"  staleness {report['staleness_s']}s",
    ]
    for m in report["members"]:
        ident = m["identity"]
        lines.append(
            f"    {m['key']}  ({m['member']}, "
            f"process {ident.get('process_index')}/"
            f"{ident.get('process_count')}, "
            f"device {ident.get('device_kind')})")
    for u in report["unreachable"]:
        lines.append(f"  UNREACHABLE {u['member']}: {u['reason']}")
    for s in report["skewed"]:
        lines.append(
            f"  SKEWED {s['member']}: catalog {s['catalog_version']} "
            f"!= expected {s['expected']}")
    mh = report.get("multihost")
    if mh:
        lines.append(
            f"  multihost: straggler host{mh.get('straggler_host')}"
            f" ({mh.get('straggler_member')})"
            f" gap {mh.get('straggler_gap_s')}s"
            f" walls {mh.get('host_walls_s')}")
    slo_rep = report.get("slo") or {}
    for key in sorted(slo_rep.get("objectives", {})):
        o = slo_rep["objectives"][key]
        lines.append(
            f"  slo {key}: {o.get('state', '?')}"
            f"  value={o.get('value')}"
            + (f"  fleet_{o.get('quantile')}={o.get('value')}"
               f" (merged buckets)" if o.get("kind") == "quantile"
               else ""))
    counters = report.get("counters", {})
    for name in sorted(counters):
        for s in counters[name]:
            lbl = ",".join(f"{k}={v}"
                           for k, v in sorted(s["labels"].items()))
            lines.append(
                f"  {name}{{{lbl}}} = {s['value']}  "
                f"(sum of {len(s['per_host'])} member(s))")
    hists = report.get("histograms", {})
    for name in sorted(hists):
        for s in hists[name]:
            fq = s.get("fleet_quantiles")
            if not fq:
                continue
            lbl = ",".join(f"{k}={v}"
                           for k, v in sorted(s["labels"].items()))
            lines.append(
                f"  {name}{{{lbl}}} fleet p50/p95/p99 = "
                f"{fq['p50']}/{fq['p95']}/{fq['p99']} "
                f"(merged buckets, n={int(s['count'])})")
    wfs = report.get("waterfalls")
    if wfs:
        from knn_tpu.obs import waterfall

        lines.append(f"  stitched cross-host waterfalls: {len(wfs)}")
        worst = max(wfs.values(),
                    key=lambda w: w.get("total_s") or 0.0)
        lines.extend("  " + ln for ln in
                     waterfall.render_waterfall(worst).splitlines())
    return "\n".join(lines)
