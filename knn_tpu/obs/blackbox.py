"""Flight recorder: on every edge-triggered SLO breach, capture ONE
postmortem bundle — the forensic state an operator would have wanted
logging on for, written at the moment the breach fires instead.

The SLO engine (knn_tpu.obs.slo) is edge-triggered: each
healthy->breached transition emits exactly one firing alert.  This
module rides that edge — :func:`on_breach` is invoked once per firing
transition (AFTER the engine's evaluation lock is released) and writes
one bounded bundle to ``KNN_TPU_POSTMORTEM_DIR``:

- the structured event ring (every span/event still held in memory —
  the raw material the waterfalls reconstruct from),
- the full metrics snapshot and the /statusz self-diagnosis report
  (built from the SAME evaluation pass that fired — no re-evaluation,
  no second transition),
- the slowest-requests exemplar table with their inline waterfalls,
  plus the critical-path attribution and device-vs-roofline verdict
  over every reconstructable request,
- the SLO report and the breach detail that fired,
- the telemetry-relevant environment (``KNN_TPU_*`` / ``KNN_BENCH_*``
  knobs), pid, and a schema version.

Disciplines:

- **at most one bundle per breach transition** — the caller is the
  edge, and a re-evaluated still-breached objective never calls here;
- **atomic** — tmp + ``os.replace``, the tune-cache/snapshot rule, so
  a reader never sees a torn bundle;
- **retention-capped** — ``KNN_TPU_POSTMORTEM_KEEP`` (default 8)
  newest bundles survive; older ones are pruned after each write, so a
  flapping objective cannot fill a disk;
- **failure-proof** — everything is wrapped: a full disk or unwritable
  directory degrades to a ``postmortem.error`` event, never an
  exception into the stats()/scrape path that ran the evaluation;
- **off by default** — no ``KNN_TPU_POSTMORTEM_DIR`` (or
  ``KNN_TPU_OBS=0``) means no work at all: one env lookup per
  transition, nothing else.

Bundles are plain JSON, readable offline by the jax-free
``python -m knn_tpu.cli waterfall --bundle <path>`` and listed in
``/statusz`` (``postmortems`` section).  Schema: docs/OBSERVABILITY.md
"Flight recorder / postmortems".
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
from typing import List, Optional

from knn_tpu.obs import names, registry, trace

#: directory bundles land in; unset = flight recorder disabled
DIR_ENV = "KNN_TPU_POSTMORTEM_DIR"

#: how many bundles survive pruning (newest kept)
KEEP_ENV = "KNN_TPU_POSTMORTEM_KEEP"
DEFAULT_KEEP = 8

#: bundle schema version (bump on shape changes so offline readers can
#: tell a malformed bundle from an old one)
BUNDLE_VERSION = 1

_FNAME_RE = re.compile(r"^postmortem-\d{8}T\d{6}-\d{4}-.*\.json$")

_seq_lock = threading.Lock()
_seq = 0
#: reentrancy guard: building a bundle reads health/waterfall state
#: that may itself evaluate metrics — a nested transition during the
#: dump must not recurse into a second dump on the same thread
_busy = threading.local()


def postmortem_dir() -> Optional[str]:
    return os.environ.get(DIR_ENV) or None


def keep_count() -> int:
    try:
        return max(1, int(os.environ.get(KEEP_ENV, DEFAULT_KEEP)))
    except ValueError:
        return DEFAULT_KEEP


def enabled() -> bool:
    """Recorder armed: a destination is configured AND telemetry is on
    (the bundle is nothing but telemetry; KNN_TPU_OBS=0 disarms it like
    every other obs surface)."""
    return postmortem_dir() is not None and registry.enabled()


def on_breach(objective: str, detail: dict,
              slo_report: Optional[dict] = None) -> Optional[str]:
    """The SLO engine's edge hook: write one bundle for this firing
    transition.  Returns the bundle path (None when disabled, busy, or
    the write failed — failures degrade to a ``postmortem.error``
    event, never an exception into the evaluating caller)."""
    if not enabled():
        return None
    if getattr(_busy, "v", False):
        return None
    _busy.v = True
    try:
        path = _write_bundle(objective, detail, slo_report)
        registry.counter(names.POSTMORTEMS_WRITTEN,
                         objective=objective).inc()
        trace.emit_event("postmortem.write", objective=objective,
                         path=path)
        return path
    except Exception as e:  # noqa: BLE001 — recorder must never raise
        try:
            trace.emit_event("postmortem.error", objective=objective,
                             error=f"{type(e).__name__}: {e}")
        except Exception:  # pragma: no cover - double fault
            pass
        return None
    finally:
        _busy.v = False


def _audit_evidence() -> Optional[dict]:
    """The audit sampler's evidence section, failure-proof: a broken
    audit layer must not take the flight recorder down with it."""
    try:
        from knn_tpu.obs import audit

        return audit.get_auditor().evidence()
    except Exception as e:  # noqa: BLE001 — recorder must never raise
        return {"error": f"{type(e).__name__}: {e}"}


def _write_bundle(objective: str, detail: dict,
                  slo_report: Optional[dict]) -> str:
    global _seq
    from knn_tpu.obs import health, waterfall

    d = postmortem_dir()
    os.makedirs(d, exist_ok=True)
    events = trace.get_event_log().recent()
    wfs = waterfall.reconstruct(events)
    slowest = waterfall.slowest_table(events=events, waterfalls=wfs)
    payload = {
        "version": BUNDLE_VERSION,
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "pid": os.getpid(),
        "objective": objective,
        "state": "firing",
        "breach_detail": detail,
        "slo": slo_report,
        # the statusz report REUSES the evaluation pass that fired
        # (slo_section=...) — a re-evaluation here could observe and
        # fire a second transition mid-dump — and the slowest table
        # built above, so the ring is reconstructed once, not twice
        "statusz": health.report(slo_section=slo_report,
                                 slowest=slowest),
        "metrics": registry.snapshot(),
        "events": events,
        "slowest": slowest,
        "attribution": waterfall.attribute(wfs),
        "device_vs_roofline": waterfall.device_vs_roofline(wfs),
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith(("KNN_TPU_", "KNN_BENCH_",
                                 "JAX_PLATFORMS"))},
        # the shadow audit sampler's evidence: summary + the bounded
        # ring of failing audit records — for a quality-SLO breach
        # this IS the postmortem (which requests served wrong answers,
        # vs what the oracle says)
        "audit": _audit_evidence(),
    }
    # measured-term calibration state: the statusz report already
    # carries the section (health's failure-proof probe) — hoist it
    # top-level so postmortem readers judging "device bound vs model
    # wrong" find it beside device_vs_roofline, without a second
    # store read
    payload["calibration"] = (payload["statusz"] or {}).get(
        "calibration")
    with _seq_lock:
        _seq += 1
        seq = _seq
    safe_obj = re.sub(r"[^A-Za-z0-9_.-]", "_", objective)[:64]
    fname = (f"postmortem-{time.strftime('%Y%m%dT%H%M%S', time.gmtime())}"
             f"-{seq:04d}-{safe_obj}.json")
    path = os.path.join(d, fname)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, sort_keys=True, default=str)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    _prune(d)
    return path


def _bundles_in(d: str) -> List[str]:
    try:
        entries = os.listdir(d)
    except OSError:
        return []
    # timestamp-then-sequence filenames sort chronologically
    return sorted(f for f in entries if _FNAME_RE.match(f))


def _prune(d: str) -> None:
    keep = keep_count()
    bundles = _bundles_in(d)
    for f in bundles[:-keep] if len(bundles) > keep else []:
        try:
            os.unlink(os.path.join(d, f))
        except OSError:  # pragma: no cover - racing reader/cleaner
            pass


def status() -> dict:
    """The ``/statusz`` ``postmortems`` section: where bundles go, how
    many survive pruning, and what is on disk right now."""
    d = postmortem_dir()
    out: dict = {"dir": d, "keep": keep_count(), "bundles": []}
    if d is None:
        return out
    for f in _bundles_in(d):
        p = os.path.join(d, f)
        try:
            st = os.stat(p)
        except OSError:
            continue
        out["bundles"].append({
            "file": f,
            "bytes": int(st.st_size),
            "modified_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(st.st_mtime)),
        })
    return out


def read_bundle(path: str) -> dict:
    """Load + structurally sanity-check a bundle (offline readers)."""
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict) or "version" not in payload:
        raise ValueError(f"{path}: not a postmortem bundle (no version)")
    return payload
