"""Live health introspection: liveness/readiness probes and the
self-diagnosis report behind ``/healthz``, ``/statusz``, and the
jax-free ``doctor`` CLI subcommand.

Serving components REGISTER here (weakly — a collected engine drops out
of the report instead of pinning itself alive): ``ServingEngine``
registers at construction and marks ops warmed in :meth:`warmup`;
``QueryQueue`` registers its worker threads.  The probes then answer
the two questions a load balancer asks:

- **live** (``/healthz`` exists at all): the process is up and the obs
  subsystem can answer — always true once this module is importable.
- **ready** (``/healthz`` returns 200): at least one registered engine
  has COMPLETED ``warmup()`` (no live request will pay an inline XLA
  compile) and every open queue's batcher/completer threads are alive
  (a dead worker thread hangs every later request — the one failure
  readiness exists to catch before traffic does).

``/statusz`` (and ``doctor``) render :func:`report` — readiness plus
self-diagnosis: device inventory (only when JAX is ALREADY initialized
in the process; a status probe must never trigger a backend init),
per-engine warmup/bucket/compile state, queue depth vs capacity and
worker liveness, tune-cache status, active SLO breaches, and the last
N alert events from the trace ring.  :func:`write
<knn_tpu.obs.export.write_json_snapshot>` embeds the same report in the
atomic snapshot, so ``doctor --snapshot`` renders the identical
structure offline.

Disabled mode (``KNN_TPU_OBS=0``): registration is skipped (no obs
objects ride the serving hot path) and the report says so — the health
surface is part of the telemetry opt-in, exactly like the exporters.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import weakref
from typing import List, Optional

from knn_tpu.obs import ident, names, registry, roofline, slo, trace

#: alert events included in the report (newest last)
REPORT_ALERTS = 20

_lock = threading.Lock()
_engines: List[weakref.ref] = []
_queues: List[weakref.ref] = []
_indexes: List[weakref.ref] = []


def register_engine(engine) -> None:
    """Called by ServingEngine.__init__ (no-op when obs is disabled)."""
    if not registry.enabled():
        return
    with _lock:
        _engines[:] = [r for r in _engines if r() is not None]
        if not any(r() is engine for r in _engines):
            _engines.append(weakref.ref(engine))


def register_queue(queue) -> None:
    """Called by QueryQueue.__init__ (no-op when obs is disabled)."""
    if not registry.enabled():
        return
    with _lock:
        _queues[:] = [r for r in _queues if r() is not None]
        if not any(r() is queue for r in _queues):
            _queues.append(weakref.ref(queue))


def register_index(index) -> None:
    """Called by MutableIndex.__init__ (no-op when obs is disabled)."""
    if not registry.enabled():
        return
    with _lock:
        _indexes[:] = [r for r in _indexes if r() is not None]
        if not any(r() is index for r in _indexes):
            _indexes.append(weakref.ref(index))


def reset() -> None:
    """Drop every registration (test isolation)."""
    with _lock:
        _engines.clear()
        _queues.clear()
        _indexes.clear()


def _live_components():
    with _lock:
        engines = [e for e in (r() for r in _engines) if e is not None]
        queues = [q for q in (r() for r in _queues) if q is not None]
    return engines, queues


def probe() -> dict:
    """The /healthz payload: ``ready`` is the 200-vs-503 verdict, the
    reasons say why not."""
    engines, queues = _live_components()
    reasons = []
    if not registry.enabled():
        reasons.append("telemetry disabled (KNN_TPU_OBS=0): health "
                       "introspection is part of the obs opt-in")
    if not engines:
        reasons.append("no ServingEngine registered")
    warmed = [e for e in engines if getattr(e, "warmed_ops", ())]
    if engines and not warmed:
        reasons.append("no registered engine has completed warmup()")
    for q in queues:
        if getattr(q, "_closed", False):
            continue  # a deliberately closed queue is not a failure
        for tname in ("_batcher_t", "_completer_t"):
            t = getattr(q, tname, None)
            if t is not None and not t.is_alive():
                reasons.append(
                    f"queue worker thread {tname.strip('_')} is dead")
    ready = not reasons
    if registry.enabled():
        registry.gauge(names.HEALTH_READY).set(1.0 if ready else 0.0)
    return {"live": True, "ready": ready, "reasons": reasons}


def _device_inventory() -> dict:
    """Device list WITHOUT triggering a backend init: only consult JAX
    when something else in the process already imported it."""
    if "jax" not in sys.modules:
        return {"available": False,
                "reason": "jax not imported in this process"}
    try:
        import jax
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return {"available": False,
                    "reason": "jax imported but no backend initialized"}
        devs = jax.devices()
        return {
            "available": True,
            "backend": jax.default_backend(),
            "count": len(devs),
            "kinds": sorted({getattr(d, "device_kind", str(d))
                             for d in devs}),
        }
    except Exception as e:  # noqa: BLE001 - introspection must not raise
        return {"available": False,
                "reason": f"{type(e).__name__}: {e}"}


def _engine_status(e) -> dict:
    try:
        # the report's top level already ran ONE SLO evaluation; each
        # engine contributes raw stats only (no per-engine re-pass —
        # it would inflate knn_tpu_slo_evaluations_total per scrape)
        st = e.stats(include_slo=False)
    except TypeError:  # engine-like object without the kwarg
        st = e.stats()
    except Exception as ex:  # noqa: BLE001
        return {"error": f"{type(ex).__name__}: {ex}"}
    # the resolved autotuner winner's roofline verdict (tuning.
    # resolve_full surfaces it off the cache entry): which bound class
    # this engine's certified path would be attacking
    tun = st.get("tuning") or {}
    rl = {fld: tun.get(fld)
          for fld in ("roofline_pct", "bound_class",
                      "roofline_ceiling_qps")
          if tun.get(fld) is not None}
    return {
        "warmed_ops": sorted(getattr(e, "warmed_ops", ())),
        "buckets": st.get("buckets"),
        "executables": st.get("executables"),
        "compile_count": st.get("compile_count"),
        "requests_total": st.get("requests_total"),
        "queries_total": st.get("queries_total"),
        "errors_total": st.get("errors_total"),
        "latency_ms": st.get("latency_ms"),
        "roofline": rl or None,
    }


def _queue_status(q) -> dict:
    # racy-but-safe reads of the queue's own backlog (list len / int):
    # a status probe must never contend for the dispatch condvar
    depth_req = len(getattr(q, "_pending", ()))
    depth_rows = int(getattr(q, "_pending_rows", 0))
    ctrl = getattr(q, "_ctrl", None)
    out = {
        "op": getattr(q, "op", None),
        "closed": bool(getattr(q, "_closed", False)),
        "max_wait_ms": round(getattr(q, "max_wait_s", 0.0) * 1e3, 3),
        "capacity_rows": getattr(q, "max_rows", None),
        "depth_requests": depth_req,
        "depth_rows": depth_rows,
        "rows_utilization": (round(depth_rows / q.max_rows, 4)
                             if getattr(q, "max_rows", 0) else None),
        # outstanding = queued + in flight: what admission's depth
        # bound and wait estimate actually judge
        "outstanding_requests": int(getattr(q, "_out_req", 0)),
        "batcher_alive": q._batcher_t.is_alive(),
        "completer_alive": q._completer_t.is_alive(),
    }
    if ctrl is not None:
        try:
            out["admission"] = ctrl.stats()
        except Exception as ex:  # noqa: BLE001 — probe must not die on it
            out["admission"] = {"error": f"{type(ex).__name__}: {ex}"}
    return out


def _tune_cache_status() -> dict:
    try:
        from knn_tpu.tuning.cache import default_cache_path

        path = default_cache_path()
        out = {"path": path, "exists": os.path.exists(path)}
        if out["exists"]:
            import json

            with open(path) as f:
                data = json.load(f)
            out["entries"] = len(data.get("entries", {}))
            out["version"] = data.get("version")
        return out
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}


def _slowest_requests() -> list:
    """The slowest-requests exemplar table with inline waterfalls
    (knn_tpu.obs.waterfall) — never fatal: a status probe must render
    even when the forensics layer cannot."""
    try:
        from knn_tpu.obs import waterfall

        return waterfall.slowest_table()
    except Exception as e:  # noqa: BLE001 - introspection must not raise
        return [{"error": f"{type(e).__name__}: {e}"}]


def _postmortems() -> dict:
    try:
        from knn_tpu.obs import blackbox

        return blackbox.status()
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}


def _quality_status() -> dict:
    """The shadow audit sampler's quality section (knn_tpu.obs.audit)
    plus drift sketches from every registered IVF index — never fatal,
    and never ARMS anything: a disabled sampler reports itself
    disabled without starting a worker."""
    try:
        from knn_tpu.obs import audit

        out = audit.status()
        with _lock:
            indexes = [i for i in (r() for r in _indexes)
                       if i is not None]
        drifts = []
        for idx in indexes:
            mon = getattr(idx, "_drift", None)
            if mon is not None:
                try:
                    drifts.append(mon.status())
                except Exception as e:  # noqa: BLE001
                    drifts.append({"error": f"{type(e).__name__}: {e}"})
        if drifts:
            out["drift"] = drifts
        return out
    except Exception as e:  # noqa: BLE001 - introspection must not raise
        return {"error": f"{type(e).__name__}: {e}"}


def _calibration_status() -> dict:
    """The measured-term calibration store's state (worst per-term
    residual included) — never fatal: a broken store must not take the
    status probe down with it."""
    try:
        from knn_tpu.obs import calibrate

        return calibrate.status()
    except Exception as e:  # noqa: BLE001 - introspection must not raise
        return {"error": f"{type(e).__name__}: {e}"}


def report(slo_section: Optional[dict] = None,
           slowest: Optional[list] = None) -> dict:
    """The full /statusz payload (see module docstring).  Everything in
    it is JSON-serializable; ``doctor`` renders the same structure.

    ``slo_section`` injects an ALREADY-COMPUTED SLO report instead of
    evaluating a fresh pass — the flight recorder passes the evaluation
    that fired it, so building a postmortem bundle can never observe
    (and re-fire on) a second transition mid-dump.  ``slowest``
    likewise injects a prebuilt slowest-requests table so the bundle
    path reconstructs the event ring once, not per consumer."""
    pr = probe()
    if slo_section is None:
        slo_section = slo.slo_report()
    alerts = [e for e in trace.get_event_log().recent()
              if e.get("name") == "slo.alert"][-REPORT_ALERTS:]
    engines, queues = _live_components()
    return {
        "generated_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "pid": os.getpid(),
        # who this process is (host, process_index/count, device kind,
        # coordinator, commit, catalog version) — the fleet aggregator
        # keys members and detects catalog skew off this stamp
        "identity": ident.identity(),
        "obs_enabled": registry.enabled(),
        "liveness": {"live": pr["live"]},
        "readiness": {"ready": pr["ready"], "reasons": pr["reasons"]},
        "devices": _device_inventory(),
        "engines": [_engine_status(e) for e in engines],
        "queues": [_queue_status(q) for q in queues],
        "tune_cache": _tune_cache_status(),
        # every roofline attribution published in this process
        # (autotuner winners, warm-cache resolves): the named gap per
        # config, rendered by /statusz and doctor
        "roofline": roofline.last_reports(),
        # the measured-term calibration store: whether this process's
        # roofline verdicts are calibrated, and the worst per-term
        # residual on file (knn_tpu.obs.calibrate)
        "calibration": _calibration_status(),
        "slo": slo_section,
        "active_breaches": (slo_section.get("breached", [])
                            if slo_section else []),
        "alerts": alerts,
        # tail forensics: the worst recent requests (histogram
        # exemplars) with inline waterfalls, and the flight recorder's
        # bundle inventory (knn_tpu.obs.{waterfall,blackbox})
        "slowest_requests": (_slowest_requests() if slowest is None
                             else slowest),
        "postmortems": _postmortems(),
        # multi-host serving: the last cross-host merge's straggler
        # attribution (per-host walls, gap, DCN volume/strategy) —
        # None until a MultiHostKNN merge ran in this process
        "multihost": _multihost_status(),
        # mutable indexes registered in this process (knn_tpu.index):
        # epoch / delta-tail / tombstone / compaction state — the
        # write-path health beside the read-path numbers above
        "index": _index_status(),
        # quality observability: the shadow audit sampler's state
        # (sampled/replayed/deficient/dropped) and any registered
        # index's drift sketches (knn_tpu.obs.{audit,drift})
        "quality": _quality_status(),
    }


def _index_status() -> list:
    with _lock:
        indexes = [i for i in (r() for r in _indexes) if i is not None]
    out = []
    for idx in indexes:
        try:
            out.append(idx.stats())
        except Exception as e:  # noqa: BLE001 - probe must not die on it
            out.append({"error": f"{type(e).__name__}: {e}"})
    return out


def _multihost_status() -> Optional[dict]:
    """The parallel.multihost last-merge report, import-guarded so a
    jax-free doctor render of a snapshot never pays (or breaks on) the
    jax import."""
    try:
        from knn_tpu.parallel import multihost

        return multihost.last_report()
    except Exception:  # noqa: BLE001 — introspection must not kill /statusz
        return None


def report_from_snapshot(payload: dict) -> dict:
    """Recover a report from an atomic JSON snapshot (export.
    write_json_snapshot embeds ``health``; pre-health snapshots degrade
    to what the metrics alone can say)."""
    if "health" in payload:
        return payload["health"]
    metrics = payload.get("metrics", {})
    ready_series = metrics.get(names.HEALTH_READY, {}).get("series", [])
    ready = bool(ready_series and ready_series[0]["value"] == 1.0)
    return {
        "generated_at": payload.get("written_at"),
        "pid": payload.get("pid"),
        "obs_enabled": payload.get("enabled"),
        "liveness": {"live": None},
        "readiness": {
            "ready": ready if ready_series else None,
            "reasons": ["snapshot predates the health section — "
                        "readiness derived from the "
                        + names.HEALTH_READY + " gauge only"],
        },
        "devices": {"available": False,
                    "reason": "not recorded in this snapshot"},
        "engines": [], "queues": [],
        "tune_cache": {}, "roofline": {}, "calibration": {}, "slo": {},
        "multihost": None, "index": [], "quality": {},
        "active_breaches": [], "alerts": [],
        "slowest_requests": [], "postmortems": {},
    }


def render_text(rep: dict) -> str:
    """Human-readable rendering of a report dict — shared by ``doctor``
    against both a live /statusz fetch and an offline snapshot, so the
    two sources print identically for identical state."""
    lines = []
    ready = rep.get("readiness", {}).get("ready")
    verdict = {True: "READY", False: "NOT READY", None: "UNKNOWN"}[ready]
    lines.append(f"health: {verdict}   (pid {rep.get('pid')}, "
                 f"generated {rep.get('generated_at')}, "
                 f"obs_enabled={rep.get('obs_enabled')})")
    for r in rep.get("readiness", {}).get("reasons", []):
        lines.append(f"  reason: {r}")
    dev = rep.get("devices", {})
    if dev.get("available"):
        lines.append(f"devices: {dev['count']}x {','.join(dev['kinds'])} "
                     f"({dev['backend']})")
    else:
        lines.append(f"devices: unavailable ({dev.get('reason')})")
    for i, e in enumerate(rep.get("engines", [])):
        lat = e.get("latency_ms") or {}
        lines.append(
            f"engine[{i}]: warmed={e.get('warmed_ops')} "
            f"buckets={e.get('buckets')} "
            f"executables={e.get('executables')} "
            f"compiles={e.get('compile_count')} "
            f"requests={e.get('requests_total')} "
            f"errors={e.get('errors_total')} "
            f"p99_ms={lat.get('p99')} "
            f"(window {lat.get('window_samples')} samples / "
            f"{lat.get('window_span_s')}s)")
    for i, q in enumerate(rep.get("queues", [])):
        lines.append(
            f"queue[{i}]: op={q.get('op')} closed={q.get('closed')} "
            f"depth={q.get('depth_requests')}req/"
            f"{q.get('depth_rows')}rows of {q.get('capacity_rows')} "
            f"(util {q.get('rows_utilization')}) "
            f"batcher={'up' if q.get('batcher_alive') else 'DOWN'} "
            f"completer={'up' if q.get('completer_alive') else 'DOWN'}")
    tc = rep.get("tune_cache", {})
    if tc:
        lines.append(f"tune_cache: {tc.get('path')} "
                     f"exists={tc.get('exists')} "
                     f"entries={tc.get('entries')}")
    for cfg, r in (rep.get("roofline") or {}).items():
        pct = r.get("roofline_pct")
        pct_s = f"{pct * 100:.1f}% of " if pct is not None else ""
        est = " [estimated peaks]" if r.get("estimated") else ""
        cal_s = (" [calibrated]" if r.get("calibration_applied")
                 else "")
        lines.append(f"roofline {cfg}: {pct_s}"
                     f"{r.get('ceiling_qps')} q/s ceiling "
                     f"({r.get('bound_class')}){est}{cal_s}")
    cal = rep.get("calibration") or {}
    if cal.get("store"):
        worst = cal.get("worst_residual_pct")
        worst_s = (f", worst term residual {worst}% "
                   f"({cal.get('worst_residual_key')})"
                   if worst is not None else "")
        lines.append(f"calibration: {cal.get('entries')} entr"
                     f"{'y' if cal.get('entries') == 1 else 'ies'} at "
                     f"{cal['store']} [{cal.get('model_token')}]"
                     f"{worst_s}")
    elif cal.get("error"):
        # a store that CANNOT report is not the same as no store: the
        # operator set KNN_TPU_CALIBRATION and deserves the failure,
        # not a claim that it is unset
        lines.append(f"calibration: status unavailable "
                     f"({cal['error']})")
    elif cal:
        lines.append("calibration: no store configured "
                     "(KNN_TPU_CALIBRATION unset) — roofline verdicts "
                     "are analytic only")
    for i, ix in enumerate(rep.get("index") or []):
        if "error" in ix:
            lines.append(f"index[{i}]: status unavailable "
                         f"({ix['error']})")
            continue
        lc = ix.get("last_compaction") or {}
        lines.append(
            f"index[{i}]: epoch={ix.get('epoch')} "
            f"rows={ix.get('rows')} tail={ix.get('tail_rows')}"
            f"/{ix.get('tail_capacity')} "
            f"tombstones={ix.get('tombstones')}/{ix.get('budget')} "
            f"live={ix.get('live_rows')} "
            f"compactions={ix.get('compactions')}"
            + (f" (last swap {lc.get('swap_s')}s)" if lc else "")
            + (" compactor=up" if ix.get("compactor_alive") else ""))
    qual = rep.get("quality") or {}
    if qual.get("enabled"):
        dropped = qual.get("dropped") or {}
        drop_s = (f" dropped={dropped}" if dropped else "")
        lines.append(
            f"quality: audit rate={qual.get('rate')} "
            f"sampled={qual.get('sampled_requests')} "
            f"replayed={qual.get('replayed_queries')}q "
            f"deficient={qual.get('deficient_queries')} "
            f"last_recall@k={qual.get('last_recall_at_k')}{drop_s}")
    elif qual and "error" not in qual:
        lines.append("quality: audit sampler off "
                     "(KNN_TPU_AUDIT_RATE unset)")
    for i, dr in enumerate(qual.get("drift") or []):
        lines.append(
            f"drift[{i}]: queries={dr.get('queries_observed')} "
            f"norm_psi={dr.get('norm_psi')} "
            f"assign_psi={dr.get('centroid_assign_psi')}")
    mh = rep.get("multihost")
    if mh:
        walls = mh.get("host_walls_s") or []
        sh = mh.get("straggler_host")
        # the named slow host: per-host walls (not just max-min) are in
        # the report, so the argmax renders here and the fleet view can
        # attribute the gap to a member
        sh_s = f" straggler=host{sh}" if sh is not None else ""
        lines.append(
            f"multihost: {mh.get('hosts')} host(s) "
            f"[{mh.get('transport')}] dcn_merge={mh.get('dcn_merge')} "
            f"bytes={mh.get('dcn_merge_bytes')} "
            f"straggler_gap={mh.get('straggler_gap_s')}s{sh_s} "
            f"(walls {', '.join(str(w) for w in walls)})")
    breaches = rep.get("active_breaches", [])
    lines.append(f"slo breaches: {', '.join(breaches) if breaches else 'none'}")
    def _slo_line(name, o, indent="  "):
        state = "BREACHED" if o.get("breached") else "ok"
        if o.get("kind") == "quantile":
            return (f"{indent}slo {name}: {state} {o.get('quantile')}="
                    f"{o.get('value_s')}s (threshold "
                    f"{o.get('threshold_s')}s, window "
                    f"{o.get('window_samples')} samples / "
                    f"{o.get('window_span_s')}s)")
        burns = {w: d.get("burn_rate")
                 for w, d in (o.get("windows") or {}).items()}
        return (f"{indent}slo {name}: {state} burn={burns} "
                f"(target {o.get('target')})")

    for o_name, o in (rep.get("slo", {}).get("objectives", {}) or {}).items():
        if o.get("group_by") is not None:
            # grouped objective: one line per label value (the
            # per-tenant drill-down), a summary line when idle
            groups = o.get("groups") or {}
            if not groups:
                lines.append(f"  slo {o_name}: no {o.get('group_by')} "
                             f"traffic")
                continue
            breached = o.get("breached") or []
            lines.append(f"  slo {o_name} (per {o.get('group_by')}): "
                         f"{len(breached)}/{len(groups)} breached")
            for gval, gentry in sorted(groups.items()):
                lines.append(_slo_line(f"{o_name}:{gval}", gentry,
                                       indent="    "))
            continue
        lines.append(_slo_line(o_name, o))
    alerts = rep.get("alerts", [])
    if alerts:
        lines.append(f"last {len(alerts)} alert event(s):")
        for a in alerts:
            lines.append(f"  [{a.get('ts')}] {a.get('objective')} "
                         f"{a.get('state')}")
    slowest = [r for r in rep.get("slowest_requests") or []
               if "trace_id" in r]
    if slowest:
        lines.append(f"slowest recent request(s) ({len(slowest)}):")
        from knn_tpu.obs import waterfall as _wf

        for r in slowest:
            tag = f"  {r.get('latency_ms')} ms  {r.get('trace_id')}"
            if r.get("tenant") is not None:
                tag += f"  tenant={r['tenant']}"
            lines.append(tag)
            if r.get("waterfall"):
                for ln in _wf.render_waterfall(r["waterfall"]).splitlines():
                    lines.append("    " + ln)
    pm = rep.get("postmortems") or {}
    if pm.get("dir"):
        lines.append(f"postmortems: {pm['dir']} "
                     f"({len(pm.get('bundles') or [])} bundle(s), "
                     f"keep {pm.get('keep')})")
        for b in pm.get("bundles") or []:
            lines.append(f"  {b.get('file')} ({b.get('bytes')} B, "
                         f"{b.get('modified_at')})")
    return "\n".join(lines) + "\n"
