"""Runtime instrumented-lock harness: record lock-acquisition order
across threads and prove the order graph acyclic (deadlock detection).

The ``locked-mutation`` checker proves writes happen under A lock; it
cannot prove two locks are always taken in the same ORDER — the
classic deadlock (thread 1 holds A wants B, thread 2 holds B wants A)
is a cross-thread property no single method shows.  This harness is
the runtime complement: tests wrap the real locks of the thread-safe
classes (engine, queue, registry, SLO engine, phase timer) in
:class:`InstrumentedLock`, run the existing 8-thread hammer scenarios,
and assert :func:`find_cycle` returns None — every edge ``A -> B``
("a thread acquired B while holding A") recorded during the run, with
a witness stack of names, and a cycle in that graph is a lock-order
inversion that WILL deadlock under the right interleaving even if this
run got lucky.

Stdlib-only; zero coupling to the classes it instruments (tests swap
``obj._lock``/``obj._cond`` attributes — the ``with``-statement
protocol is all that's required).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple


class LockOrderRecorder:
    """The shared order graph a group of instrumented locks feeds.

    Thread-safety: guarded by ``self._lock`` (its own plain lock —
    never instrumented, held only for dict updates, so it cannot
    participate in the graphs it records).
    """

    def __init__(self):
        self._lock = threading.Lock()
        #: (held_name, acquired_name) -> first witness thread name
        self._edges: Dict[Tuple[str, str], str] = {}
        self._held = threading.local()

    def _stack(self) -> List[str]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = []
            self._held.stack = st
        return st

    def note_acquire(self, name: str) -> None:
        st = self._stack()
        tname = threading.current_thread().name
        with self._lock:
            for held in st:
                if held != name:
                    self._edges.setdefault((held, name), tname)
        st.append(name)

    def note_release(self, name: str) -> None:
        st = self._stack()
        # release order may differ from acquire order (with-blocks can
        # interleave via explicit acquire/release); remove the newest
        # matching entry
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                return

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._lock:
            return dict(self._edges)

    def order_graph(self) -> Dict[str, Set[str]]:
        graph: Dict[str, Set[str]] = {}
        for (a, b), _tname in self.edges().items():
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        return graph

    def find_cycle(self) -> Optional[List[str]]:
        """A lock-name cycle in the acquisition-order graph, or None.
        Any cycle is reportable: ``A -> B -> A`` means some thread
        acquired B holding A and some (possibly other) thread acquired
        A holding B — a deadlock waiting for its interleaving."""
        graph = self.order_graph()
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        path: List[str] = []

        def visit(n: str) -> Optional[List[str]]:
            color[n] = GREY
            path.append(n)
            for m in sorted(graph[n]):
                if color[m] == GREY:
                    return path[path.index(m):] + [m]
                if color[m] == WHITE:
                    cyc = visit(m)
                    if cyc is not None:
                        return cyc
            path.pop()
            color[n] = BLACK
            return None

        for n in sorted(graph):
            if color[n] == WHITE:
                cyc = visit(n)
                if cyc is not None:
                    return cyc
        return None

    def assert_acyclic(self) -> None:
        cyc = self.find_cycle()
        if cyc is not None:
            edges = self.edges()
            witness = {f"{a}->{b}": edges.get((a, b), "?")
                       for a, b in zip(cyc, cyc[1:])}
            raise AssertionError(
                f"lock-order cycle {' -> '.join(cyc)} "
                f"(witness threads: {witness}) — a deadlock under the "
                f"right interleaving")


class InstrumentedLock:
    """A drop-in ``with``-protocol wrapper over any lock-like object
    (Lock, RLock, Condition) that reports acquisition order to a
    :class:`LockOrderRecorder`.  Condition extras (wait/notify) proxy
    through, so ``QueryQueue._cond`` instruments like the plain locks.
    """

    def __init__(self, name: str, recorder: LockOrderRecorder,
                 inner=None):
        self.name = name
        self.recorder = recorder
        self.inner = inner if inner is not None else threading.Lock()

    def acquire(self, *a, **kw):
        got = self.inner.acquire(*a, **kw)
        if got:
            self.recorder.note_acquire(self.name)
        return got

    def release(self):
        self.recorder.note_release(self.name)
        return self.inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition pass-throughs (wait releases and re-acquires the inner
    # lock without changing which NAME this thread holds — correct for
    # ordering: the protected region is still "under" this lock)
    def wait(self, timeout=None):
        return self.inner.wait(timeout)

    def wait_for(self, predicate, timeout=None):
        return self.inner.wait_for(predicate, timeout)

    def notify(self, n=1):
        return self.inner.notify(n)

    def notify_all(self):
        return self.inner.notify_all()


def instrument(recorder: LockOrderRecorder, **named_objects) -> None:
    """Swap each object's lock attribute for an instrumented wrapper:
    ``instrument(rec, engine=engine, queue=queue)`` wraps
    ``engine._lock`` as ``"engine"`` and ``queue._cond`` as
    ``"queue"`` (whichever of ``_lock``/``_cond`` the object has)."""
    for name, obj in named_objects.items():
        for attr in ("_lock", "_cond"):
            inner = getattr(obj, attr, None)
            if inner is not None:
                setattr(obj, attr,
                        InstrumentedLock(name, recorder, inner))
                break
        else:
            raise ValueError(
                f"{name}: object has neither _lock nor _cond")
