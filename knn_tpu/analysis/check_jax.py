"""``jax-hygiene`` — host syncs, wall clocks, and unhashable static
args, caught at lint time.

Three rule families over ``knn_tpu/`` (library code only — scripts/
are session drivers where wall-clock reads are the point):

1. **Wall clock**: ``time.time()`` anywhere is a finding.  Durations
   in this repo come from ``time.perf_counter``/``time.monotonic``
   (wall time is not monotonic: NTP steps corrupt a latency
   measurement exactly once, unreproducibly).  The few legitimate
   uses — display timestamps that are never differenced — carry
   suppression entries with that justification, so every NEW wall
   clock read has to argue its case.

2. **Hot-path host syncs**: inside a function marked
   ``@hot_path`` (knn_tpu.analysis.annotations), calls that force a
   host round-trip or materialize device data —
   ``.block_until_ready()``, ``jax.device_get``, ``.item()``,
   ``.tolist()``, ``np.asarray``/``np.array``/``np.ascontiguousarray``,
   ``float(...)``/``int(...)`` of a non-trivial expression — are
   findings.  The async dispatch pipeline is the serving layer's whole
   throughput story; one stray sync serializes it silently.  The
   decorator's ``allow=("np.asarray", ...)`` tuple whitelists specific
   calls AT the annotation (e.g. host-side input coercion), keeping
   the exemption next to the code it exempts.

3. **Unhashable static args** (same-file analysis): a call site that
   passes a list/dict/set display (or comprehension) to a parameter
   the callee declares in ``jax.jit(..., static_argnames=...)`` raises
   ``TypeError`` at runtime — or, with a tuple rebuilt per call from
   varying contents, recompiles silently.  Also flagged: a jitted
   function whose static parameter has a mutable default.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from knn_tpu.analysis.core import Context, Finding, checker

#: call names forbidden inside @hot_path functions (dotted-tail match).
#: ``time.time`` is deliberately absent: the wall-clock rule already
#: flags every read ONCE, everywhere — listing it here would double-
#: report the same call inside hot paths, and a hot-path ``allow``
#: tuple must never be able to whitelist a wall clock (that exemption
#: requires a justified suppression entry)
HOT_FORBIDDEN = (
    ".block_until_ready",
    "jax.device_get",
    ".item",
    ".tolist",
    "np.asarray",
    "np.array",
    "np.ascontiguousarray",
    "jnp.asarray",
)

_MUTABLE_DISPLAYS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp, ast.GeneratorExp)


def _call_name(func: ast.AST) -> str:
    """Render a call target as a dotted name: ``time.time``,
    ``.block_until_ready`` (unknown receiver), ``float``."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        base = func.value
        if isinstance(base, ast.Name):
            return f"{base.id}.{func.attr}"
        return f".{func.attr}"
    return ""


def _matches(name: str, pattern: str) -> bool:
    if pattern.startswith("."):
        return name.endswith(pattern) or name == pattern.lstrip(".")
    return name == pattern or name.endswith("." + pattern)


def _hot_path_allow(dec: ast.AST) -> Optional[Tuple[str, ...]]:
    """The ``allow`` tuple when ``dec`` is a hot_path decorator (bare
    or called), else None."""
    target = dec.func if isinstance(dec, ast.Call) else dec
    name = _call_name(target)
    if not (name == "hot_path" or name.endswith(".hot_path")):
        return None
    allow: List[str] = []
    if isinstance(dec, ast.Call):
        for kw in dec.keywords:
            if kw.arg == "allow" and isinstance(
                    kw.value, (ast.Tuple, ast.List)):
                allow.extend(
                    e.value for e in kw.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
    return tuple(allow)


def _jit_static_names(call: ast.Call) -> Optional[Set[str]]:
    """The static_argnames set when ``call`` is a ``jax.jit``
    (or ``functools.partial(jax.jit, ...)``) invocation, else None."""
    name = _call_name(call.func)
    inner = call
    if name.endswith("partial") and call.args and \
            isinstance(call.args[0], (ast.Name, ast.Attribute)) and \
            _matches(_call_name(call.args[0]), "jax.jit"):
        inner = call
    elif not _matches(name, "jax.jit"):
        return None
    out: Set[str] = set()
    for kw in inner.keywords:
        if kw.arg == "static_argnames" and isinstance(
                kw.value, (ast.Tuple, ast.List)):
            out.update(e.value for e in kw.value.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, str))
        elif kw.arg == "static_argnames" and isinstance(
                kw.value, ast.Constant) and isinstance(
                kw.value.value, str):
            out.add(kw.value.value)
    return out


def _scan_hot_path(relpath: str, fn: ast.FunctionDef,
                   allow: Sequence[str],
                   findings: List[Finding]) -> None:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if not name:
            continue
        hit = None
        for pat in HOT_FORBIDDEN:
            if _matches(name, pat):
                hit = pat
                break
        if hit is None and name in ("float", "int") and node.args and \
                isinstance(node.args[0], (ast.Call, ast.Subscript)):
            hit = name  # float(x.something()) — likely a device fetch
        if hit is None:
            continue
        if any(_matches(name, a) or a == hit for a in allow):
            continue
        findings.append(Finding(
            checker="jax-hygiene", path=relpath, line=node.lineno,
            symbol=fn.name,
            message=f"host-sync call {name}() inside "
                    f"@hot_path function {fn.name}",
            fix_hint="move it off the dispatch path, or whitelist it "
                     "at the annotation: @hot_path(allow=(...,)) with "
                     "the reason in the surrounding code"))


def _scan_static_args(relpath: str, tree: ast.Module,
                      findings: List[Finding]) -> None:
    static_of: Dict[str, Set[str]] = {}
    # pass 1: jitted defs (decorator form) + jit-wrapping assignments
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    names = _jit_static_names(dec)
                    if names:
                        static_of[node.name] = names
                        # a static param with a mutable default can
                        # never be hashed at the default either
                        args = node.args
                        params = args.posonlyargs + args.args + \
                            args.kwonlyargs
                        defaults = ([None] * (len(args.posonlyargs)
                                              + len(args.args)
                                              - len(args.defaults))
                                    + list(args.defaults)
                                    + list(args.kw_defaults))
                        for p, dflt in zip(params, defaults):
                            if p.arg in names and isinstance(
                                    dflt, _MUTABLE_DISPLAYS):
                                findings.append(Finding(
                                    checker="jax-hygiene",
                                    path=relpath, line=node.lineno,
                                    symbol=node.name,
                                    message=f"static arg {p.arg!r} of "
                                            f"jitted {node.name} has "
                                            f"an unhashable default",
                                    fix_hint="use a tuple / None"))
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            names = _jit_static_names(node.value)
            if names and node.value.args and \
                    isinstance(node.value.args[0], ast.Name):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        static_of[t.id] = names
    if not static_of:
        return
    # pass 2: call sites passing mutable displays to static params
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Name):
            continue
        names = static_of.get(node.func.id)
        if not names:
            continue
        for kw in node.keywords:
            if kw.arg in names and isinstance(kw.value,
                                              _MUTABLE_DISPLAYS):
                findings.append(Finding(
                    checker="jax-hygiene", path=relpath,
                    line=node.lineno, symbol=node.func.id,
                    message=f"call passes an unhashable "
                            f"{type(kw.value).__name__.lower()} to "
                            f"static arg {kw.arg!r} of jitted "
                            f"{node.func.id} — TypeError at trace "
                            f"time (or a silent recompile per call)",
                    fix_hint="pass a tuple / scalar; static args must "
                             "hash stably across calls"))


@checker("jax-hygiene",
         "wall clocks, hot-path host syncs, unhashable static args")
def check_jax(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for relpath in ctx.py_files():
        if not relpath.startswith("knn_tpu"):
            continue  # scripts/bench are session drivers, out of scope
        tree = ctx.parse(relpath)
        if tree is None:
            continue
        # 1. wall-clock reads
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    _call_name(node.func) == "time.time":
                findings.append(Finding(
                    checker="jax-hygiene", path=relpath,
                    line=node.lineno, symbol="time.time",
                    message="wall-clock read time.time() — durations "
                            "must come from perf_counter/monotonic",
                    fix_hint="if this is a display timestamp that is "
                             "never differenced, suppress with that "
                             "justification"))
        # 2. hot-path host syncs
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    allow = _hot_path_allow(dec)
                    if allow is not None:
                        _scan_hot_path(relpath, node, allow, findings)
                        break
        # 3. static-arg hygiene
        _scan_static_args(relpath, tree, findings)
    return findings
