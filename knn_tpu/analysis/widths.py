"""ONE shared per-precision operand byte-width table.

Before PR 17 the db-operand stream widths lived three times over —
``obs.roofline.DB_ELEM_BYTES`` (the cost model), ``analysis.vmem.DB_PARTS``
(the launch budget), and ``analysis.hbm``'s itemsize arithmetic (the
placement budget) — pinned against each other by tests but still three
places to edit.  With the sub-int8 arms (int4 nibble-packed rows, PQ
byte codes whose row width depends on ``ceil(d / dsub)``) a drifted
mirror would mis-price exactly the byte term those arms exist to
shrink, so the widths now live HERE and all three consumers import
them; tests/test_analysis.py pins the identity (``is``, not ``==``) so
a re-forked table can't reappear.

Jax-free on purpose: every consumer is a jax-free analysis/obs module.

Layout provenance (what the kernels actually stream,
``ops.pallas_knn._bin_candidates``):

- ``bf16x3``  : precomputed bf16 hi+lo db parts, 2+2 B/elem.
- ``bf16x3f`` : one 3x-wide bf16 contraction, 6 B/elem.
- ``int8``    : per-row symmetric int8 rows, 1 B/elem.
- ``int4``    : per-row symmetric 4-bit rows packed two-nibbles-per-byte
  (``ops.quantize.pack_nibbles``), 0.5 B/elem — the db-stream halving
  the PR 17 roofline target prices.  Dims pad to DIM_CHUNK first, so
  bytes/row = ``ceil_to(d, 128) / 2`` exactly.
- ``pq``      : one byte code per subspace, ``ceil(d / dsub)`` B/row
  (``ops.pq``); per-element width is shape-dependent, so consumers call
  :func:`db_row_bytes` instead of indexing ``DB_ELEM_BYTES``.
- ``highest`` / ``default``: the raw f32 rows, 4 B/elem.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

#: dim-chunk width every kernel slices the feature axis by (mirror of
#: ops.pallas_knn.DIM_CHUNK, pinned by test)
DIM_CHUNK = 128

#: db stream width per element by kernel matmul precision.  int4 is the
#: only fractional entry (two dims per byte); "pq" is deliberately
#: ABSENT — its row width is ``ceil(d / dsub)`` bytes, shape-dependent,
#: served by :func:`db_row_bytes`.
DB_ELEM_BYTES: Dict[str, float] = {
    "bf16x3": 4, "bf16x3f": 6, "int8": 1, "int4": 0.5,
    "highest": 4, "default": 4,
}

#: f32 sublane rows of the per-tile aux block: 8 rows of broadcast row
#: norms, and int8 stacks 8 broadcast scale rows under them (16).
#: int4 instead PACKS norms (row 0) + scales (row 1) into the default
#: 8-row block — the kernel reads exactly one row of each, and the
#: packed layout halves an aux stream that would otherwise weigh as
#: much as the nibble-packed values at d=128.  PQ needs no db-side
#: norms (the per-query LUT carries the reconstruction's norm term),
#: so its aux block is the 8-row pad-fill carrier only.
AUX_ROWS: Dict[str, int] = {"int8": 16}
AUX_ROWS_DEFAULT = 8

#: query operand width per element: the quantized arms stream int8
#: queries (int4 dbs score against int8 queries — the query side is
#: tiny, so halving IT buys nothing and would double the query
#: residual term of the bound).  PQ is absent here too: its query-side
#: operand is the per-query LUT, priced by :func:`pq_lut_bytes`.
QUERY_ELEM_BYTES: Dict[str, int] = {"int8": 1, "int4": 1}
QUERY_ELEM_BYTES_DEFAULT = 4

#: db operand parts per precision for the VMEM launch model:
#: (n_parts, chunk_w, bytes/elem) — one db block of ONE part occupies
#: (tile_n, chunk_w) at the part dtype.  int4's packed chunk is 64
#: bytes wide (two dims per byte over a 128-dim chunk).  "pq" is
#: absent: its chunk width is the shape-dependent code width
#: ``ceil(d / dsub)`` (analysis.vmem special-cases it via
#: :func:`db_row_bytes`).
DB_PARTS: Dict[str, Tuple[int, int, int]] = {
    "bf16x3": (2, DIM_CHUNK, 2),
    "bf16x3f": (1, 3 * DIM_CHUNK, 2),
    "int8": (1, DIM_CHUNK, 1),
    "int4": (1, DIM_CHUNK // 2, 1),
    "highest": (1, DIM_CHUNK, 4),
    "default": (1, DIM_CHUNK, 4),
}

#: f32 aux bytes beside each placed row (the hoisted squared norm) —
#: analysis.hbm's placement arithmetic
AUX_BYTES_PER_ROW = 4

#: PQ defaults: 4 dims per subspace and 256 codes (one byte) per
#: codebook — the classic 8-bit PQ point; at SIFT's d=128 a row is 32
#: code bytes = 1/16 the f32 row
PQ_DSUB_DEFAULT = 4
PQ_NCODES_DEFAULT = 256


def _ceil_div(a: int, b: int) -> int:
    return -(-int(a) // int(b))


def pq_nsub(d: int, dsub: Optional[int] = None) -> int:
    """Subspace count ``m = ceil(d / dsub)`` — also the PQ row's code
    bytes (one uint8 code per subspace)."""
    return _ceil_div(int(d), int(dsub or PQ_DSUB_DEFAULT))


def db_row_bytes(d: int, precision: str, *,
                 dsub: Optional[int] = None) -> int:
    """EXACT bytes one db row streams at this precision — the one
    entry point that covers the shape-dependent arms: int4 rounds the
    (DIM_CHUNK-padded) dim up to an even nibble pair, PQ streams
    ``ceil(d / dsub)`` code bytes."""
    d = int(d)
    if precision == "pq":
        return pq_nsub(d, dsub)
    if precision == "int4":
        return _ceil_div(_ceil_div(d, DIM_CHUNK) * DIM_CHUNK, 2)
    if precision not in DB_ELEM_BYTES:
        raise ValueError(
            f"precision {precision!r} not in "
            f"{sorted(DB_ELEM_BYTES) + ['pq']}")
    return int(d * DB_ELEM_BYTES[precision])


def aux_rows_for(precision: str) -> int:
    return AUX_ROWS.get(precision, AUX_ROWS_DEFAULT)


def query_elem_bytes(precision: str) -> int:
    return QUERY_ELEM_BYTES.get(precision, QUERY_ELEM_BYTES_DEFAULT)


def pq_lut_bytes(nq: int, d: int, *, dsub: Optional[int] = None,
                 ncodes: Optional[int] = None) -> int:
    """Bytes of the per-query PQ lookup tables one batch carries
    ([nq, m * ncodes] f32) — the query-side operand of the PQ arm."""
    m = pq_nsub(d, dsub)
    return int(nq) * m * int(ncodes or PQ_NCODES_DEFAULT) * 4


def pq_lut_flops(nq: int, d: int, *, dsub: Optional[int] = None,
                 ncodes: Optional[int] = None) -> float:
    """FLOPs of building the per-query LUTs: every (query, subspace,
    code) entry is a dsub-dim dot + norm fold, ~2·dsub flops — in total
    ``2 · nq · ncodes · (m · dsub) >= 2 · nq · ncodes · d``."""
    m = pq_nsub(d, dsub)
    return 2.0 * int(nq) * int(ncodes or PQ_NCODES_DEFAULT) * m * int(
        dsub or PQ_DSUB_DEFAULT)


__all__ = [
    "DIM_CHUNK", "DB_ELEM_BYTES", "AUX_ROWS", "AUX_ROWS_DEFAULT",
    "QUERY_ELEM_BYTES", "QUERY_ELEM_BYTES_DEFAULT", "DB_PARTS",
    "AUX_BYTES_PER_ROW", "PQ_DSUB_DEFAULT", "PQ_NCODES_DEFAULT",
    "pq_nsub", "db_row_bytes", "aux_rows_for", "query_elem_bytes",
    "pq_lut_bytes", "pq_lut_flops",
]
