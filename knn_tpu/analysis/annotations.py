"""Machine-readable source annotations the checkers key on.

Stdlib-only and import-cycle-free by construction (this module imports
nothing from knn_tpu): runtime modules — serving, obs, tuning — mark
their own hot paths and thread-safety contracts here, and the AST
checkers (knn_tpu.analysis) read the markers WITHOUT importing those
modules.

Two conventions:

- ``@hot_path`` / ``@hot_path(allow=("np.asarray",))`` — a function on
  the serving/dispatch hot path.  The jax-hygiene checker flags
  host-sync calls (``.block_until_ready()``, ``jax.device_get``,
  ``.item()``, ``.tolist()``, ``np.asarray``/``np.array``,
  ``float()``/``int()`` of a call result) and wall-clock reads
  (``time.time()``) inside it.  ``allow`` whitelists specific call
  names AT the annotation — the exemption rides next to the code it
  exempts, with the decorator itself as the written record (e.g. input
  coercion of host-side request arrays is np.asarray-by-design).
  Runtime cost: one identity call at def time, zero per invocation.

- **Thread-safety docstring markers** (no runtime artifact at all):
  a class whose docstring contains ``Thread-safety: guarded by
  ``self._lock``.`` (any attribute name) opts into the concurrency
  checker — writes to shared attributes outside a ``with self._lock:``
  block become findings.  A helper method that REQUIRES the lock held
  declares it with ``Caller holds ``self._lock``.`` in its docstring.
  Grammar: knn_tpu/analysis/check_concurrency.py and docs/ANALYSIS.md.
"""

from __future__ import annotations

from typing import Callable, Sequence


def hot_path(fn: Callable = None, *, allow: Sequence[str] = ()):
    """Mark a function as serving-hot-path (see module docstring).
    Identity at runtime; the checker reads the decorator — and its
    ``allow`` tuple — from the AST."""
    if fn is not None:  # bare @hot_path
        return fn

    def wrap(f):
        return f

    return wrap
